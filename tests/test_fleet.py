"""Replica fleet: router failover certification (docs/ROBUSTNESS.md
"Replica fleets").

The load-bearing claims, proven over real sockets on CPU:

- routed answers are BIT-identical to direct single-store execution;
- a replica killed abruptly (abort = the in-process kill -9) mid-burst
  under the fault harness loses nothing: every client answer is either
  a correct result (bit-identical to a single-replica run) or a typed
  retryable error — zero un-typed, zero dropped, zero duplicates;
- the drain verb is admin-gated and graceful (in-flight finishes, new
  traffic refused typed);
- a fresh replica refuses traffic (typed, retryable) until its warmup
  check is green, and the router never routes to it before `ready`;
- rolling restart drains one replica at a time and ends with fresh
  incarnations serving;
- ephemeral metrics ports (port=0) are reported in stats()/debug
  endpoints so N replicas on one host never collide.

Budget note (tier-1 wall): ONE tiny module-scoped catalog with the
same 384-row shape / k=5 kNN buckets the chaos suite (test_faults)
already compiled — the fleet pays sockets and routing, not kernels.
Process-spawn coverage (real `python -m geomesa_tpu.fleet.replica`
workers paying jax import) is marked slow.
"""

import json
import threading
import time

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.fleet import (
    FleetConfig, FleetSupervisor, ReplicaServer, ReplicaStateError,
    state_number, validate_transition)
from geomesa_tpu.fleet.health import burn_gates_fired
from geomesa_tpu.fleet.wire import connect_json
from geomesa_tpu.plan.datastore import DataStore

N_ROWS = 384
CQL = "BBOX(geom, -170, -80, 170, 80)"
K = 5


@pytest.fixture(scope="module")
def catalog(tmp_path_factory):
    rng = np.random.default_rng(5)
    sft = SimpleFeatureType.from_spec(
        "fleeted", "name:String,score:Double,dtg:Date,*geom:Point")
    root = str(tmp_path_factory.mktemp("fleet"))
    ds = DataStore(root, use_device_cache=True)
    ds.create_schema(sft).write(FeatureBatch.from_pydict(sft, {
        "name": rng.choice(["a", "b", "c"], N_ROWS).tolist(),
        "score": rng.uniform(-10, 10, N_ROWS),
        "dtg": rng.integers(
            1_590_000_000_000, 1_590_080_000_000, N_ROWS),
        "geom": np.stack([rng.uniform(-170, 170, N_ROWS),
                          rng.uniform(-80, 80, N_ROWS)], 1),
    }))
    del ds
    return root


@pytest.fixture(scope="module")
def oracle_store(catalog):
    """Direct single-store execution — the bit-identity reference."""
    return DataStore(catalog, use_device_cache=True)


def _fleet(catalog, n=2, **kw):
    return FleetSupervisor(FleetConfig(
        n_replicas=n, catalog=catalog, probe_interval_s=0.2, **kw))


def _qpts(n, seed=3):
    return np.random.default_rng(seed).uniform(-60, 60, (n, 2))


def _knn_doc(rid, x, y, timeout_ms=60_000):
    return {"id": rid, "op": "knn", "typeName": "fleeted", "cql": CQL,
            "x": [float(x)], "y": [float(y)], "k": K,
            "timeoutMs": timeout_ms}


class TestStateMachine:
    def test_legal_and_illegal_transitions(self):
        assert validate_transition("starting", "warming") == "warming"
        assert validate_transition("warming", "ready") == "ready"
        assert validate_transition("ready", "degraded") == "degraded"
        assert validate_transition("degraded", "ready") == "ready"
        assert validate_transition("ready", "draining") == "draining"
        assert validate_transition("draining", "dead") == "dead"
        assert validate_transition("ready", "ready") == "ready"  # no-op
        for bad in (("ready", "warming"), ("dead", "ready"),
                    ("warming", "degraded"), ("draining", "ready")):
            with pytest.raises(ReplicaStateError):
                validate_transition(*bad)
        with pytest.raises(ReplicaStateError):
            validate_transition("ready", "nonsense")
        with pytest.raises(ReplicaStateError):
            state_number("nonsense")

    def test_burn_gate_reading(self):
        assert not burn_gates_fired({})
        assert not burn_gates_fired({"enabled": False})
        assert burn_gates_fired({"enabled": True, "degrade_boost": 1})
        assert burn_gates_fired({
            "enabled": True, "degrade_boost": 0,
            "breaching": ["knn_p99"],
            "objectives": {"knn_p99": {"degrade": True}}})
        # a breaching objective NOT marked degrade does not shed
        assert not burn_gates_fired({
            "enabled": True, "degrade_boost": 0,
            "breaching": ["availability"],
            "objectives": {"availability": {"degrade": False}}})


class TestRouting:
    def test_routed_answers_bit_identical_to_direct(
            self, catalog, oracle_store):
        qp = _qpts(8)
        src = oracle_store.get_feature_source("fleeted")
        oracle = [src.knn(CQL, qp[i:i + 1, 0], qp[i:i + 1, 1], k=K)
                  for i in range(8)]
        want_count = src.get_count(CQL)
        sup = _fleet(catalog)
        try:
            port = sup.start()
            cli = connect_json("127.0.0.1", port)
            for i in range(8):
                got = cli.request(_knn_doc(f"q{i}", qp[i, 0], qp[i, 1]),
                                  timeout_s=300.0)
                assert got["ok"], got
                d, ix, _ = oracle[i]
                assert got["indices"] == [[int(j) for j in row]
                                          for row in ix]
                assert got["dists"] == [
                    [float(v) for v in row] for row in d]  # bit-exact
            got = cli.request({"id": "c", "op": "count",
                               "typeName": "fleeted", "cql": CQL},
                              timeout_s=300.0)
            assert got["ok"] and got["count"] == want_count
            # stats routes like a query and carries the replica's view
            got = cli.request({"id": "s", "op": "stats"})
            assert got["ok"] and got["stats"]["replica"]["state"] == \
                "ready"
            snap = sup.stats()
            assert snap["router"]["requests"] >= 10
            assert sum(r["routed"] for r in snap["replicas"]) >= 10
            cli.close()
        finally:
            sup.close()

    def test_wire_handoff_ops_refused_typed(self, catalog):
        """attach/detach carry a client-materialized wire handoff the
        router cannot audit for exactly-once replay: refused typed on
        EVERY router, rehome or not."""
        sup = _fleet(catalog, n=1)
        try:
            port = sup.start()
            cli = connect_json("127.0.0.1", port)
            for op in ("attach", "detach"):
                got = cli.request({"id": f"s-{op}", "op": op,
                                   "subscription": "sub-1"})
                assert not got["ok"] and got["error"] == "rejected"
                assert got["reason"] == "unsupported"
            cli.close()
        finally:
            sup.close()

    def test_rehome_disabled_back_compat(self, catalog):
        """rehome=False restores the pre-upgrade surface exactly: the
        hello advertises NO rehome capability and every subscribe verb
        refuses typed `unsupported` — an old client scripted against
        the refusal keeps working."""
        sup = _fleet(catalog, n=1, rehome=False)
        try:
            port = sup.start()
            cli = connect_json("127.0.0.1", port)
            hello = cli.request({"id": "h", "op": "hello"})
            assert hello["ok"] and "rehome" not in hello
            for op in ("subscribe", "unsubscribe", "poll",
                       "subscriptions", "export_subscription",
                       "pause", "resume"):
                got = cli.request({"id": f"s-{op}", "op": op,
                                   "typeName": "fleeted", "cql": CQL,
                                   "subscription": "sub-1"})
                assert not got["ok"] and got["error"] == "rejected", got
                assert got["reason"] == "unsupported"
                assert "replica-sticky" in got["message"]
            cli.close()
        finally:
            sup.close()

    def test_rehome_capability_advertised(self, catalog):
        sup = _fleet(catalog, n=1)
        try:
            port = sup.start()
            cli = connect_json("127.0.0.1", port)
            hello = cli.request({"id": "h", "op": "hello"})
            assert hello["ok"] and hello["rehome"] is True
            cli.close()
        finally:
            sup.close()

    def test_burn_gated_replica_sheds_to_healthy_peer(self, catalog):
        """SLO-burn-aware routing: when the affinity-preferred replica's
        burn gates fire, new traffic goes to a healthy peer (and the
        skip is counted); when EVERY replica is gated, traffic still
        flows."""
        sup = _fleet(catalog)
        try:
            sup.start()
            # find a key whose rendezvous affinity prefers r0
            doc = None
            for i in range(64):
                cand = _knn_doc(f"p{i}", float(i * 7 % 60), 5.0)
                ranked = sorted(
                    sup.membership.routable(),
                    key=lambda h: __import__("zlib").crc32(
                        f"{sup.router._affinity_key(cand)}|"
                        f"{h.replica_id}".encode()),
                    reverse=True)
                if ranked[0].replica_id == "r0":
                    doc = cand
                    break
            assert doc is not None
            sup.membership.get("r0").burn_gated = True
            shed0 = sup.stats()["router"]["shed"]
            picked = sup.router._pick(doc, exclude=())
            assert picked.replica_id == "r1"
            assert sup.stats()["router"]["shed"] == shed0 + 1
            # all gated: traffic still flows (shedding to nowhere is
            # an outage, not protection)
            sup.membership.get("r1").burn_gated = True
            assert sup.router._pick(doc, exclude=()) is not None
        finally:
            sup.close()


class TestFailover:
    def test_kill_mid_burst_every_answer_typed_or_exact(
            self, catalog, oracle_store):
        """The satellite certification: kill -9 a replica mid-burst
        under the fault harness; every client answer is a correct
        (bit-identical) result or a typed retryable error; zero
        dropped, zero duplicates."""
        from geomesa_tpu.faults import harness as _harness
        from geomesa_tpu.faults.plan import FaultPlan, FaultRule

        burst = 16
        qp = _qpts(burst, seed=9)
        src = oracle_store.get_feature_source("fleeted")
        oracle = {
            f"q{i}": src.knn(CQL, qp[i:i + 1, 0], qp[i:i + 1, 1], k=K)
            for i in range(burst)}
        sup = _fleet(catalog)
        try:
            port = sup.start()
            cli = connect_json("127.0.0.1", port)
            # warm both replicas so the burst measures routing, and so
            # in-flight work is genuinely mid-kernel when the kill lands
            for rep in sup.membership.all():
                w = connect_json(rep.host, rep.port)
                w.request(_knn_doc("w", 1.0, 2.0), timeout_s=300.0)
                w.close()
            # injected device latency keeps several requests in flight
            # across the kill (the harness is the load shaper here; its
            # fires need no replay determinism in this test)
            plan = FaultPlan(seed=13, rules=[FaultRule(
                site="device.transfer", error="latency",
                latency_ms=15.0, every=1)])
            with _harness.active(plan):
                for i in range(burst):
                    cli.send(_knn_doc(f"q{i}", qp[i, 0], qp[i, 1]))
                sup.kill_replica("r0", graceful=False)
                answers = {}
                stop = threading.Event()
                timer = threading.Timer(120.0, stop.set)
                timer.start()
                for got in cli.docs(stop):
                    assert got["id"] not in answers, \
                        f"duplicate response {got['id']}"
                    answers[got["id"]] = got
                    if len(answers) >= burst:
                        break
                timer.cancel()
            assert len(answers) == burst, sorted(answers)
            for rid, got in answers.items():
                if got.get("ok"):
                    d, ix, _ = oracle[rid]
                    assert got["indices"] == [
                        [int(j) for j in row] for row in ix], rid
                    assert got["dists"] == [
                        [float(v) for v in row] for row in d], rid
                else:
                    assert got.get("error") in (
                        "unavailable", "rejected", "timeout"), got
                    assert got.get("retryable", True), got
            snap = sup.stats()
            states = {r["replica"]: r["state"]
                      for r in snap["replicas"]}
            assert states == {"r0": "dead", "r1": "ready"}
            # gauge consistency: retries counted on both surfaces
            assert snap["router"]["retried"] == sum(
                r["retried_onto"] for r in snap["replicas"])
            cli.close()
        finally:
            sup.close()

    def test_drain_verb_admin_gated_and_graceful(self, catalog):
        sup = _fleet(catalog)
        try:
            port = sup.start()
            h0 = sup.membership.get("r0")
            # a plain client may not drain
            direct = connect_json(h0.host, h0.port)
            got = direct.request({"id": "d0", "op": "drain"})
            assert not got["ok"] and got["reason"] == "admin_required"
            # an admin connection drains: hello upgrades the role
            hello = direct.request({"id": "h", "op": "hello",
                                    "role": "admin"})
            assert hello["ok"] and hello["admin"] is True
            assert hello["replica"] == "r0"
            got = direct.request({"id": "d1", "op": "drain"},
                                 timeout_s=120.0)
            assert got["ok"] and got["state"] == "dead", got
            direct.close()
            assert h0.server.state == "dead"
            # the survivor keeps serving through the router
            cli = connect_json("127.0.0.1", port)
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                got = cli.request(_knn_doc("a1", 3.0, 4.0),
                                  timeout_s=120.0)
                if got.get("ok"):
                    break
                assert got.get("error") in ("unavailable",), got
            assert got["ok"], got
            cli.close()
        finally:
            sup.close()

    def test_warming_gate_refuses_until_check_green(self, catalog):
        """A fresh replica refuses traffic typed+retryable until its
        warmup manifest replays with --check semantics green — and the
        router never considers it routable before ready."""
        from geomesa_tpu.compilecache.manifest import WarmupManifest
        from geomesa_tpu.fleet.membership import ReplicaHandle

        sup = _fleet(catalog, n=1)
        try:
            sup.start()
            mpath = catalog + "/warm_manifest.json"
            WarmupManifest().save(mpath)
            hold = threading.Event()
            rep = ReplicaServer(
                lambda: DataStore(catalog, use_device_cache=True),
                replica_id="w0", warmup_manifest=mpath,
                warmup_hold=hold)
            port = rep.start()
            handle = ReplicaHandle(replica_id="w0", host="127.0.0.1",
                                   port=port, spawn="thread",
                                   server=rep)
            sup.membership.add(handle)
            sup.router.attach(handle)
            assert rep.wait_state("warming", timeout=60.0) == "warming"
            probe = connect_json("127.0.0.1", port)
            got = probe.request(_knn_doc("w1", 1.0, 2.0))
            assert not got["ok"] and got["reason"] == "warming"
            assert got["retryable"] is True
            # control verbs still answer while warming
            st = probe.request({"id": "s", "op": "stats"})
            assert st["ok"] and st["stats"]["replica"]["state"] == \
                "warming"
            assert not any(h.replica_id == "w0"
                           for h in sup.membership.routable())
            hold.set()
            assert rep.wait_state("ready", timeout=120.0) == "ready"
            assert rep.warmup_report is not None and \
                rep.warmup_report.ok
            got = probe.request(_knn_doc("w2", 1.0, 2.0),
                                timeout_s=120.0)
            assert got["ok"], got
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if any(h.replica_id == "w0"
                       for h in sup.membership.routable()):
                    break
                time.sleep(0.05)
            assert any(h.replica_id == "w0"
                       for h in sup.membership.routable())
            probe.close()
            rep.stop()
        finally:
            sup.close()

    def test_rolling_restart(self, catalog):
        sup = _fleet(catalog)
        try:
            port = sup.start()
            result = sup.rolling_restart()
            assert result["ok"], result
            assert len(result["rolled"]) == 2
            assert all(r["state"] == "ready" for r in result["rolled"])
            snap = sup.stats()
            states = {r["replica"]: r["state"]
                      for r in snap["replicas"]}
            # old incarnations dead, fresh ones (r0.1, r1.1) serving
            assert states["r0"] == "dead" and states["r1"] == "dead"
            assert states["r0.1"] == "ready"
            assert states["r1.1"] == "ready"
            cli = connect_json("127.0.0.1", port)
            got = cli.request(_knn_doc("rr", 5.0, 6.0), timeout_s=120.0)
            assert got["ok"], got
            cli.close()
        finally:
            sup.close()


class TestProtocolDrain:
    def test_standalone_serve_lines_drain(self, catalog):
        """The drain verb without a fleet: `serve_lines` (stdin is the
        process owner's, hence admin) drains in place — in-flight
        work finishes, later requests answer typed shutting_down."""
        from geomesa_tpu.serve.protocol import serve_lines

        store = DataStore(catalog, use_device_cache=True)
        out = []
        lines = [
            json.dumps({"id": "c1", "op": "count",
                        "typeName": "fleeted", "cql": CQL}),
            json.dumps({"id": "d1", "op": "drain"}),
            json.dumps({"id": "c2", "op": "count",
                        "typeName": "fleeted", "cql": CQL}),
        ]
        serve_lines(store, lines, out.append)
        docs = {json.loads(s)["id"]: json.loads(s) for s in out}
        assert docs["c1"]["ok"]
        assert docs["d1"]["ok"] and docs["d1"]["state"] == "drained"
        assert not docs["c2"]["ok"]
        assert docs["c2"]["reason"] == "shutting_down"

    def test_wire_restart_is_admin_gated(self, catalog):
        from geomesa_tpu.fleet import FleetClient

        sup = _fleet(catalog, n=1)
        try:
            port = sup.start()
            cli = FleetClient("127.0.0.1", port)
            got = cli.request({"op": "restart"})
            assert not got["ok"] and got["reason"] == "admin_required"
            cli.close()
        finally:
            sup.close()

    def test_router_never_proxies_drain(self, catalog):
        """The router's replica links are admin-privileged, so
        forwarding a client's drain would launder it past the
        replica-side admin gate: the router must refuse the verb for
        EVERY session and leave the replica serving."""
        from geomesa_tpu.fleet import FleetClient

        sup = _fleet(catalog, n=1)
        try:
            port = sup.start()
            cli = FleetClient("127.0.0.1", port)
            got = cli.request({"op": "drain"})
            assert not got["ok"] and got["reason"] == "admin_required"
            cli.hello(role="admin")
            got = cli.request({"op": "drain"})
            assert not got["ok"] and got["reason"] == "unsupported"
            # the replica is untouched and still serving
            assert sup.membership.get("r0").state == "ready"
            got = cli.request({"id": "q", "op": "count",
                               "typeName": "fleeted", "cql": CQL},
                              timeout_s=120.0)
            assert got["ok"]
            cli.close()
        finally:
            sup.close()


class TestMetricsPort:
    def test_ephemeral_port_reported(self, catalog):
        """Satellite: MetricsServer port=0 + the bound port reported in
        stats() and the debug endpoints — N replicas on one host must
        not collide on a fixed port."""
        import urllib.request

        rep = ReplicaServer(
            lambda: DataStore(catalog, use_device_cache=True),
            replica_id="m0", metrics_port=0)
        rep.start()
        try:
            assert rep.wait_state("ready", timeout=60.0) == "ready"
            assert rep.metrics_port not in (None, 0)
            assert rep.svc.stats()["metrics_port"] == rep.metrics_port
            assert rep.describe()["metrics_port"] == rep.metrics_port
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{rep.metrics_port}/healthz",
                    timeout=10) as r:
                doc = json.loads(r.read().decode())
            assert doc["endpoint"]["port"] == rep.metrics_port
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{rep.metrics_port}/debug/stats",
                    timeout=10) as r:
                doc = json.loads(r.read().decode())
            assert doc["endpoint"]["port"] == rep.metrics_port
            assert doc["serve"]["metrics_port"] == rep.metrics_port
        finally:
            rep.stop()

    def test_fleet_snapshot_reports_bound_ports(self, catalog):
        """The {"op": "fleet"} / status document must carry each
        replica's BOUND ephemeral metrics port (thread replicas bind
        theirs asynchronously during init)."""
        sup = _fleet(catalog, metrics_port=0)
        try:
            sup.start()
            ports = [r["metrics_port"]
                     for r in sup.stats()["replicas"]]
            assert all(p not in (None, 0) for p in ports), ports
            assert len(set(ports)) == len(ports), ports
        finally:
            sup.close()

    def test_two_replicas_distinct_ports(self, catalog):
        reps = [ReplicaServer(
            lambda: DataStore(catalog, use_device_cache=True),
            replica_id=f"mp{i}", metrics_port=0) for i in range(2)]
        try:
            for r in reps:
                r.start()
            for r in reps:
                assert r.wait_state("ready", timeout=60.0) == "ready"
            ports = {r.metrics_port for r in reps}
            assert len(ports) == 2 and None not in ports
        finally:
            for r in reps:
                r.stop()


@pytest.mark.slow
class TestProcessSpawn:
    def test_process_fleet_kill_and_failover(self, catalog):
        """Real OS-process replicas (jax import and all): spawn 2,
        serve, kill -9 one, keep serving. The deployment shape."""
        sup = FleetSupervisor(FleetConfig(
            n_replicas=2, catalog=catalog, spawn="process",
            probe_interval_s=0.3, force_cpu_workers=True))
        try:
            port = sup.start()
            cli = connect_json("127.0.0.1", port)
            got = cli.request(_knn_doc("p1", 1.0, 2.0),
                              timeout_s=600.0)
            assert got["ok"], got
            victim = sup.membership.get("r0")
            assert victim.pid is not None
            sup.kill_replica("r0", graceful=False)
            got = cli.request(_knn_doc("p2", 3.0, 4.0),
                              timeout_s=600.0)
            assert got["ok"], got
            states = {r["replica"]: r["state"]
                      for r in sup.stats()["replicas"]}
            assert states["r0"] == "dead" and states["r1"] == "ready"
            cli.close()
        finally:
            sup.close()


# -- fleet-native standing queries (router-side re-homing) -----------------

SUB_SFT = SimpleFeatureType.from_spec(
    "live", "name:String,score:Double,dtg:Date,*geom:Point")
SUB_CQL = "BBOX(geom, -20, -15, 25, 20)"
SUB_FIDS = [f"v{i}" for i in range(24)]


def _sub_rows(seed, fids=SUB_FIDS):
    rng = np.random.default_rng(seed)
    n = len(fids)
    return FeatureBatch.from_pydict(SUB_SFT, {
        "name": rng.choice(["a", "b", "c"], n).tolist(),
        "score": rng.uniform(-5, 5, n),
        "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
        "geom": np.stack([rng.uniform(-60, 60, n),
                          rng.uniform(-30, 30, n)], 1),
    }, fids=list(fids))


def _kafka_fleet(n=2, **kw):
    """A fleet whose replicas share ONE Kafka live layer (fold hooks
    are a store-level list, so every replica's evaluator sees every
    event — the deployment shape for standing queries)."""
    from geomesa_tpu.kafka.store import KafkaDataStore

    store = KafkaDataStore()
    src = store.create_schema(SUB_SFT)
    sup = FleetSupervisor(FleetConfig(
        n_replicas=n, store_factory=lambda: store,
        probe_interval_s=0.1, **kw))
    return store, src, sup


def _replay(frames, sid):
    """Host-oracle replay of a client's frame stream: asserts zero
    duplicate-enter / phantom-exit transitions, returns the final
    matched set. State frames (initial or resync) reset by contract."""
    state = set()
    for f in sorted((f for f in frames
                     if f.get("subscription") == sid
                     and f.get("event") in ("enter", "exit", "state")),
                    key=lambda f: f["seq"]):
        if f["event"] == "state":
            state = set(f["fids"])
        elif f["event"] == "enter":
            dup = set(f["fids"]) & state
            assert not dup, f"duplicate enter for {sorted(dup)}"
            state |= set(f["fids"])
        else:
            ghost = set(f["fids"]) - state
            assert not ghost, f"phantom exit for {sorted(ghost)}"
            state -= set(f["fids"])
    return state


def _assert_seq_monotonic(frames, sid):
    seqs = [f["seq"] for f in frames if f.get("subscription") == sid]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs), seqs


def _wait_rehomed(sup, sid, old_owner, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        row = sup.membership.sub_owner(sid)
        if row is not None and row.replica_id != old_owner:
            return row
        time.sleep(0.02)
    raise AssertionError(
        f"subscription {sid} never re-homed off {old_owner}")


def _wait_checkpoint(sup, sid, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        row = sup.membership.sub_owner(sid)
        if row is not None and row.checkpoint is not None:
            return row
        time.sleep(0.02)
    raise AssertionError(f"no checkpoint piggybacked for {sid}")


class TestRehome:
    """Fleet-native standing queries: the router homes, checkpoints,
    and re-homes subscriptions across replica failover — the client
    reads one connection and sees at most one resync per kill."""

    def test_routed_parity_with_direct_subscription(self):
        """The matched sets a routed subscription replays to are
        bit-identical to a direct single-replica subscription fed the
        same stream — routing adds zero semantic drift."""
        from geomesa_tpu.kafka.store import KafkaDataStore
        from geomesa_tpu.subscribe import SubscriptionManager

        # direct reference: one manager over its own store
        ref_store = KafkaDataStore()
        ref_store.create_schema(SUB_SFT)
        mgr = SubscriptionManager(ref_store)
        ref_sub = mgr.subscribe("live", SUB_CQL)
        ref_frames = []
        mgr.flush(ref_frames.append)

        store, src, sup = _kafka_fleet()
        frames = []
        try:
            from geomesa_tpu.fleet.router import FleetClient

            port = sup.start()
            cli = FleetClient("127.0.0.1", port, timeout_s=30.0)
            got = cli.request({"op": "subscribe", "typeName": "live",
                               "cql": SUB_CQL},
                              on_push=frames.append)
            assert got["ok"], got
            sid = got["subscription"]
            for k in range(3):
                b = _sub_rows(100 + k)
                src.write(b)
                got = cli.request({"op": "poll"},
                                  on_push=frames.append)
                assert got["ok"], got
                ref_store.write("live", _sub_rows(100 + k))
                ref_store.poll("live")
                mgr.flush(lambda f: ref_frames.append(f))
                # bit-identical matched set after EVERY batch
                assert _replay(frames, sid) == \
                    _replay(ref_frames, ref_sub.sub_id)
            cli.close()
        finally:
            sup.close()
            mgr.close()

    def test_kill_rehomes_single_resync(self):
        """The tentpole certification: abrupt owner death mid-stream →
        the router replays the subscription onto the survivor from the
        piggybacked checkpoint; the client sees exactly ONE resync,
        monotonic seq, and a replay that matches the live oracle —
        with zero client choreography."""
        store, src, sup = _kafka_fleet()
        frames = []
        try:
            from geomesa_tpu.fleet.router import FleetClient

            port = sup.start()
            cli = FleetClient("127.0.0.1", port, timeout_s=30.0)
            got = cli.request({"op": "subscribe", "typeName": "live",
                               "cql": SUB_CQL},
                              on_push=frames.append)
            assert got["ok"], got
            sid, owner = got["subscription"], got["replica"]
            assert sid.startswith("rs")   # the replica id never leaks
            src.write(_sub_rows(1))
            assert cli.request({"op": "poll"},
                               on_push=frames.append)["ok"]
            _wait_checkpoint(sup, sid)
            sup.kill_replica(owner, graceful=False)
            row = _wait_rehomed(sup, sid, owner)
            assert row.rehomes == 1
            src.write(_sub_rows(2))
            assert cli.request({"op": "poll"},
                               on_push=frames.append)["ok"]
            evs = [f for f in frames if f.get("subscription") == sid]
            _assert_seq_monotonic(evs, sid)
            resyncs = sum(1 for f in evs[1:]
                          if f.get("event") == "state")
            assert resyncs == 1, evs
            # replayed matched set == live snapshot oracle
            matched = _replay(evs, sid)
            h = sup.membership.get(row.replica_id)
            live = h.server.svc.subscriptions.registry.maybe(
                row.replica_sub_id)
            assert matched == live.matched
            # ownership + telemetry surfaces agree
            snap = sup.stats()
            assert snap["subscriptions"] == 1
            assert snap["sub_rehomes"] == 1
            assert snap["router"]["rehome_attempted"] == 1
            assert snap["router"]["rehome_succeeded"] == 1
            assert snap["router"]["rehome_failed"] == 0
            owned = {r["replica"]: r["subs_owned"]
                     for r in snap["replicas"]}
            assert owned[row.replica_id] == 1
            assert owned[owner] == 0
            assert isinstance(
                sup.membership.export_checkpoint_staleness(), dict)
            cli.close()
        finally:
            sup.close()

    def test_double_failover_seq_continuity(self):
        """Kill the owner, then kill the NEW owner: the sequence the
        client sees stays strictly monotonic across both moves — one
        resync per kill, never more."""
        store, src, sup = _kafka_fleet(n=3)
        frames = []
        try:
            from geomesa_tpu.fleet.router import FleetClient

            port = sup.start()
            cli = FleetClient("127.0.0.1", port, timeout_s=30.0)
            got = cli.request({"op": "subscribe", "typeName": "live",
                               "cql": SUB_CQL},
                              on_push=frames.append)
            assert got["ok"], got
            sid, owner = got["subscription"], got["replica"]
            for kill_round in (1, 2):
                src.write(_sub_rows(10 + kill_round))
                assert cli.request({"op": "poll"},
                                   on_push=frames.append)["ok"]
                _wait_checkpoint(sup, sid)
                sup.kill_replica(owner, graceful=False)
                row = _wait_rehomed(sup, sid, owner)
                assert row.rehomes == kill_round
                owner = row.replica_id
            src.write(_sub_rows(13))
            assert cli.request({"op": "poll"},
                               on_push=frames.append)["ok"]
            evs = [f for f in frames if f.get("subscription") == sid]
            _assert_seq_monotonic(evs, sid)
            resyncs = sum(1 for f in evs[1:]
                          if f.get("event") == "state")
            assert resyncs == 2, evs   # exactly one per kill
            matched = _replay(evs, sid)
            h = sup.membership.get(owner)
            row = sup.membership.sub_owner(sid)
            live = h.server.svc.subscriptions.registry.maybe(
                row.replica_sub_id)
            assert matched == live.matched
            assert sup.stats()["router"]["rehome_succeeded"] == 2
            cli.close()
        finally:
            sup.close()

    def test_lagged_overflow_then_kill_single_resync_each(self):
        """An outbox overflow (typed `subscription_lagged` + its state
        resync) racing a re-home stays coherent: the client sees the
        lagged resync, then ONE re-home resync — replay is exact, seq
        monotonic, nothing double-resynced."""
        store, src, sup = _kafka_fleet()
        frames = []
        try:
            from geomesa_tpu.fleet.router import FleetClient

            port = sup.start()
            cli = FleetClient("127.0.0.1", port, timeout_s=30.0)
            got = cli.request({"op": "subscribe", "typeName": "live",
                               "cql": SUB_CQL, "outboxLimit": 2},
                              on_push=frames.append)
            assert got["ok"], got
            sid, owner = got["subscription"], got["replica"]
            # fold server-side WITHOUT flushing (direct store.poll
            # skips the replica's drain): three folds queue more than
            # the 2-slot outbox holds -> overflow -> lagged marker
            for k in range(3):
                src.write(_sub_rows(30 + k))
                store.poll("live")
            assert cli.request({"op": "poll"},
                               on_push=frames.append)["ok"]
            assert any(f.get("event") == "subscription_lagged"
                       for f in frames
                       if f.get("subscription") == sid), frames
            _wait_checkpoint(sup, sid)
            sup.kill_replica(owner, graceful=False)
            _wait_rehomed(sup, sid, owner)
            src.write(_sub_rows(35))
            assert cli.request({"op": "poll"},
                               on_push=frames.append)["ok"]
            evs = [f for f in frames if f.get("subscription") == sid]
            _assert_seq_monotonic(evs, sid)
            # exactly two resyncs past the initial state: the lagged
            # recovery and the re-home — the race never stacks extras
            resyncs = sum(1 for f in evs[1:]
                          if f.get("event") == "state")
            assert resyncs == 2, evs
            row = sup.membership.sub_owner(sid)
            live = sup.membership.get(
                row.replica_id).server.svc.subscriptions.registry \
                .maybe(row.replica_sub_id)
            assert _replay(evs, sid) == live.matched
            cli.close()
        finally:
            sup.close()

    def test_paused_sub_rehomes_paused_resyncs_on_resume(self):
        """Pause rides the checkpoint: a paused subscription re-homes
        PAUSED (no frames while the client is away) and pays its one
        state resync when resumed."""
        store, src, sup = _kafka_fleet()
        frames = []
        try:
            from geomesa_tpu.fleet.router import FleetClient

            port = sup.start()
            cli = FleetClient("127.0.0.1", port, timeout_s=30.0)
            got = cli.request({"op": "subscribe", "typeName": "live",
                               "cql": SUB_CQL},
                              on_push=frames.append)
            assert got["ok"], got
            sid, owner = got["subscription"], got["replica"]
            src.write(_sub_rows(40))
            assert cli.request({"op": "poll"},
                               on_push=frames.append)["ok"]
            got = cli.request({"op": "pause", "subscription": sid},
                              on_push=frames.append)
            assert got["ok"] and got["status"] == "paused", got
            assert got["subscription"] == sid
            # wait for a checkpoint carrying the paused status
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                row = sup.membership.sub_owner(sid)
                if row is not None and row.paused \
                        and row.checkpoint is not None:
                    break
                time.sleep(0.02)
            row = sup.membership.sub_owner(sid)
            assert row.paused and row.checkpoint is not None
            n_before = len([f for f in frames
                            if f.get("subscription") == sid])
            sup.kill_replica(owner, graceful=False)
            row = _wait_rehomed(sup, sid, owner)
            # landed paused on the survivor: no frames delivered
            live = sup.membership.get(
                row.replica_id).server.svc.subscriptions.registry \
                .maybe(row.replica_sub_id)
            assert live.status == "paused"
            src.write(_sub_rows(41))
            assert cli.request({"op": "poll"},
                               on_push=frames.append)["ok"]
            evs = [f for f in frames if f.get("subscription") == sid]
            assert len(evs) == n_before, "paused sub leaked frames"
            got = cli.request({"op": "resume", "subscription": sid},
                              on_push=frames.append)
            assert got["ok"] and got["status"] == "active", got
            assert cli.request({"op": "poll"},
                               on_push=frames.append)["ok"]
            evs = [f for f in frames if f.get("subscription") == sid]
            _assert_seq_monotonic(evs, sid)
            # the resume's resync covers everything folded while away
            assert evs[-1]["event"] in ("state", "enter", "exit")
            live = sup.membership.get(
                row.replica_id).server.svc.subscriptions.registry \
                .maybe(row.replica_sub_id)
            assert _replay(evs, sid) == live.matched
            cli.close()
        finally:
            sup.close()

    def test_quarantined_sub_not_rehomed(self):
        """A quarantined subscription's stream ends with its terminal
        frame: ownership is dropped at the frame, so the death sweep
        has nothing to replay — a poisoned predicate cannot chase the
        fleet through failovers."""
        from geomesa_tpu.serve.service import ServeConfig

        store, src, sup = _kafka_fleet(
            serve_config=ServeConfig(quarantine_after=2))
        frames = []

        class _Poison:
            filter_ast = None
            _band_fn = None

            def params(self, batch):
                return {}

            def mask_fn(self):
                def bad(params, dev):
                    raise RuntimeError("poisoned predicate")
                return bad

            def mask_refined(self, dev, batch):
                raise RuntimeError("poisoned predicate")

        try:
            from geomesa_tpu.fleet.router import FleetClient

            port = sup.start()
            cli = FleetClient("127.0.0.1", port, timeout_s=30.0)
            got = cli.request({"op": "subscribe", "typeName": "live",
                               "cql": "score > 1.5"},
                              on_push=frames.append)
            assert got["ok"], got
            sid, owner = got["subscription"], got["replica"]
            mgr = sup.membership.get(owner).server.svc.subscriptions
            mgr.evaluator._filters[("live", "score > 1.5")] = _Poison()
            for k in range(3):
                src.write(_sub_rows(50 + k))
                assert cli.request({"op": "poll"},
                                   on_push=frames.append)["ok"]
            evs = [f for f in frames if f.get("subscription") == sid]
            assert any(f.get("event") == "quarantined"
                       for f in evs), evs
            # ownership died with the terminal frame
            assert sup.membership.sub_owner(sid) is None
            assert sup.stats()["subscriptions"] == 0
            sup.kill_replica(owner, graceful=False)
            time.sleep(0.5)
            st = sup.stats()["router"]
            assert st["rehome_attempted"] == 0
            assert st["rehome_succeeded"] == 0
            cli.close()
        finally:
            sup.close()

    def test_density_window_rehomes_by_reseed(self):
        """Density-window subscriptions have no incremental handoff
        snapshot (registry refuses one by contract) — the re-home path
        re-seeds from the survivor's live snapshot instead, and the
        client still pays exactly one resync."""
        store, src, sup = _kafka_fleet()
        frames = []
        try:
            from geomesa_tpu.fleet.router import FleetClient

            port = sup.start()
            cli = FleetClient("127.0.0.1", port, timeout_s=30.0)
            got = cli.request(
                {"op": "subscribe", "typeName": "live",
                 "density": {"bbox": [-60.0, -30.0, 60.0, 30.0],
                             "width": 16, "height": 8}},
                on_push=frames.append)
            assert got["ok"], got
            sid, owner = got["subscription"], got["replica"]
            assert got["mode"] == "density"
            src.write(_sub_rows(60))
            assert cli.request({"op": "poll"},
                               on_push=frames.append)["ok"]
            sup.kill_replica(owner, graceful=False)
            row = _wait_rehomed(sup, sid, owner)
            assert row.mode == "density"
            src.write(_sub_rows(61))
            assert cli.request({"op": "poll"},
                               on_push=frames.append)["ok"]
            evs = [f for f in frames if f.get("subscription") == sid]
            _assert_seq_monotonic(evs, sid)
            # density frames after the kill keep flowing off the
            # survivor's re-seeded window
            assert any(f.get("event") == "density" for f in evs), evs
            assert sup.stats()["router"]["rehome_succeeded"] == 1
            cli.close()
        finally:
            sup.close()

    def test_rolling_restart_drains_subscriptions(self):
        """Zero-downtime roll with live standing queries: every
        subscription is exported fresh, re-homed to a survivor, and
        still delivering after BOTH replicas have been replaced — the
        client reads one connection throughout."""
        store, src, sup = _kafka_fleet()
        frames = []
        try:
            from geomesa_tpu.fleet.router import FleetClient

            port = sup.start()
            cli = FleetClient("127.0.0.1", port, timeout_s=30.0)
            got = cli.request({"op": "subscribe", "typeName": "live",
                               "cql": SUB_CQL},
                              on_push=frames.append)
            assert got["ok"], got
            sid = got["subscription"]
            src.write(_sub_rows(70))
            assert cli.request({"op": "poll"},
                               on_push=frames.append)["ok"]
            result = sup.rolling_restart()
            assert result["ok"], result
            moved = sum(r["subs"]["moved"] for r in result["rolled"])
            failed = sum(r["subs"]["failed"] for r in result["rolled"])
            assert moved >= 1 and failed == 0, result
            # the subscription is live on a fresh incarnation
            row = sup.membership.sub_owner(sid)
            assert row is not None
            assert sup.membership.get(row.replica_id).state == "ready"
            src.write(_sub_rows(71))
            assert cli.request({"op": "poll"},
                               on_push=frames.append)["ok"]
            evs = [f for f in frames if f.get("subscription") == sid]
            _assert_seq_monotonic(evs, sid)
            live = sup.membership.get(
                row.replica_id).server.svc.subscriptions.registry \
                .maybe(row.replica_sub_id)
            assert _replay(evs, sid) == live.matched
            cli.close()
        finally:
            sup.close()

    def test_client_disconnect_releases_ownership(self):
        """A hung-up client's subscriptions are cancelled on the owner
        and dropped from the ownership table — no orphan streams, no
        leaked re-homes on a later kill."""
        store, src, sup = _kafka_fleet()
        try:
            from geomesa_tpu.fleet.router import FleetClient

            port = sup.start()
            cli = FleetClient("127.0.0.1", port, timeout_s=30.0)
            got = cli.request({"op": "subscribe", "typeName": "live",
                               "cql": SUB_CQL})
            assert got["ok"], got
            sid = got["subscription"]
            assert sup.membership.sub_owner(sid) is not None
            cli.close()
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if sup.membership.sub_owner(sid) is None:
                    break
                time.sleep(0.05)
            assert sup.membership.sub_owner(sid) is None
            assert sup.stats()["subscriptions"] == 0
        finally:
            sup.close()

    def test_export_subscription_renumbered_to_client_seq(self):
        """export_subscription through the router hands out a snapshot
        in CLIENT-visible numbering (watermark = what the client has
        seen), so a wire handoff taken through the fleet endpoint can
        seed a direct replica subscription without seq regression."""
        store, src, sup = _kafka_fleet()
        frames = []
        try:
            from geomesa_tpu.fleet.router import FleetClient

            port = sup.start()
            cli = FleetClient("127.0.0.1", port, timeout_s=30.0)
            got = cli.request({"op": "subscribe", "typeName": "live",
                               "cql": SUB_CQL},
                              on_push=frames.append)
            assert got["ok"], got
            sid = got["subscription"]
            src.write(_sub_rows(80))
            assert cli.request({"op": "poll"},
                               on_push=frames.append)["ok"]
            got = cli.request({"op": "export_subscription",
                               "subscription": sid},
                              on_push=frames.append)
            assert got["ok"], got
            snap = got["handoff"]
            evs = [f for f in frames if f.get("subscription") == sid]
            assert snap["watermark"] == max(f["seq"] for f in evs)
            assert snap["seq"] >= snap["watermark"]
            assert set(snap["matched"]) == _replay(evs, sid)
            cli.close()
        finally:
            sup.close()
