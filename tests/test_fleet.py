"""Replica fleet: router failover certification (docs/ROBUSTNESS.md
"Replica fleets").

The load-bearing claims, proven over real sockets on CPU:

- routed answers are BIT-identical to direct single-store execution;
- a replica killed abruptly (abort = the in-process kill -9) mid-burst
  under the fault harness loses nothing: every client answer is either
  a correct result (bit-identical to a single-replica run) or a typed
  retryable error — zero un-typed, zero dropped, zero duplicates;
- the drain verb is admin-gated and graceful (in-flight finishes, new
  traffic refused typed);
- a fresh replica refuses traffic (typed, retryable) until its warmup
  check is green, and the router never routes to it before `ready`;
- rolling restart drains one replica at a time and ends with fresh
  incarnations serving;
- ephemeral metrics ports (port=0) are reported in stats()/debug
  endpoints so N replicas on one host never collide.

Budget note (tier-1 wall): ONE tiny module-scoped catalog with the
same 384-row shape / k=5 kNN buckets the chaos suite (test_faults)
already compiled — the fleet pays sockets and routing, not kernels.
Process-spawn coverage (real `python -m geomesa_tpu.fleet.replica`
workers paying jax import) is marked slow.
"""

import json
import threading
import time

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.fleet import (
    FleetConfig, FleetSupervisor, ReplicaServer, ReplicaStateError,
    state_number, validate_transition)
from geomesa_tpu.fleet.health import burn_gates_fired
from geomesa_tpu.fleet.wire import connect_json
from geomesa_tpu.plan.datastore import DataStore

N_ROWS = 384
CQL = "BBOX(geom, -170, -80, 170, 80)"
K = 5


@pytest.fixture(scope="module")
def catalog(tmp_path_factory):
    rng = np.random.default_rng(5)
    sft = SimpleFeatureType.from_spec(
        "fleeted", "name:String,score:Double,dtg:Date,*geom:Point")
    root = str(tmp_path_factory.mktemp("fleet"))
    ds = DataStore(root, use_device_cache=True)
    ds.create_schema(sft).write(FeatureBatch.from_pydict(sft, {
        "name": rng.choice(["a", "b", "c"], N_ROWS).tolist(),
        "score": rng.uniform(-10, 10, N_ROWS),
        "dtg": rng.integers(
            1_590_000_000_000, 1_590_080_000_000, N_ROWS),
        "geom": np.stack([rng.uniform(-170, 170, N_ROWS),
                          rng.uniform(-80, 80, N_ROWS)], 1),
    }))
    del ds
    return root


@pytest.fixture(scope="module")
def oracle_store(catalog):
    """Direct single-store execution — the bit-identity reference."""
    return DataStore(catalog, use_device_cache=True)


def _fleet(catalog, n=2, **kw):
    return FleetSupervisor(FleetConfig(
        n_replicas=n, catalog=catalog, probe_interval_s=0.2, **kw))


def _qpts(n, seed=3):
    return np.random.default_rng(seed).uniform(-60, 60, (n, 2))


def _knn_doc(rid, x, y, timeout_ms=60_000):
    return {"id": rid, "op": "knn", "typeName": "fleeted", "cql": CQL,
            "x": [float(x)], "y": [float(y)], "k": K,
            "timeoutMs": timeout_ms}


class TestStateMachine:
    def test_legal_and_illegal_transitions(self):
        assert validate_transition("starting", "warming") == "warming"
        assert validate_transition("warming", "ready") == "ready"
        assert validate_transition("ready", "degraded") == "degraded"
        assert validate_transition("degraded", "ready") == "ready"
        assert validate_transition("ready", "draining") == "draining"
        assert validate_transition("draining", "dead") == "dead"
        assert validate_transition("ready", "ready") == "ready"  # no-op
        for bad in (("ready", "warming"), ("dead", "ready"),
                    ("warming", "degraded"), ("draining", "ready")):
            with pytest.raises(ReplicaStateError):
                validate_transition(*bad)
        with pytest.raises(ReplicaStateError):
            validate_transition("ready", "nonsense")
        with pytest.raises(ReplicaStateError):
            state_number("nonsense")

    def test_burn_gate_reading(self):
        assert not burn_gates_fired({})
        assert not burn_gates_fired({"enabled": False})
        assert burn_gates_fired({"enabled": True, "degrade_boost": 1})
        assert burn_gates_fired({
            "enabled": True, "degrade_boost": 0,
            "breaching": ["knn_p99"],
            "objectives": {"knn_p99": {"degrade": True}}})
        # a breaching objective NOT marked degrade does not shed
        assert not burn_gates_fired({
            "enabled": True, "degrade_boost": 0,
            "breaching": ["availability"],
            "objectives": {"availability": {"degrade": False}}})


class TestRouting:
    def test_routed_answers_bit_identical_to_direct(
            self, catalog, oracle_store):
        qp = _qpts(8)
        src = oracle_store.get_feature_source("fleeted")
        oracle = [src.knn(CQL, qp[i:i + 1, 0], qp[i:i + 1, 1], k=K)
                  for i in range(8)]
        want_count = src.get_count(CQL)
        sup = _fleet(catalog)
        try:
            port = sup.start()
            cli = connect_json("127.0.0.1", port)
            for i in range(8):
                got = cli.request(_knn_doc(f"q{i}", qp[i, 0], qp[i, 1]),
                                  timeout_s=300.0)
                assert got["ok"], got
                d, ix, _ = oracle[i]
                assert got["indices"] == [[int(j) for j in row]
                                          for row in ix]
                assert got["dists"] == [
                    [float(v) for v in row] for row in d]  # bit-exact
            got = cli.request({"id": "c", "op": "count",
                               "typeName": "fleeted", "cql": CQL},
                              timeout_s=300.0)
            assert got["ok"] and got["count"] == want_count
            # stats routes like a query and carries the replica's view
            got = cli.request({"id": "s", "op": "stats"})
            assert got["ok"] and got["stats"]["replica"]["state"] == \
                "ready"
            snap = sup.stats()
            assert snap["router"]["requests"] >= 10
            assert sum(r["routed"] for r in snap["replicas"]) >= 10
            cli.close()
        finally:
            sup.close()

    def test_subscribe_ops_refused_typed(self, catalog):
        sup = _fleet(catalog, n=1)
        try:
            port = sup.start()
            cli = connect_json("127.0.0.1", port)
            got = cli.request({"id": "s1", "op": "subscribe",
                               "typeName": "fleeted", "cql": CQL})
            assert not got["ok"] and got["error"] == "rejected"
            assert got["reason"] == "unsupported"
            cli.close()
        finally:
            sup.close()

    def test_burn_gated_replica_sheds_to_healthy_peer(self, catalog):
        """SLO-burn-aware routing: when the affinity-preferred replica's
        burn gates fire, new traffic goes to a healthy peer (and the
        skip is counted); when EVERY replica is gated, traffic still
        flows."""
        sup = _fleet(catalog)
        try:
            sup.start()
            # find a key whose rendezvous affinity prefers r0
            doc = None
            for i in range(64):
                cand = _knn_doc(f"p{i}", float(i * 7 % 60), 5.0)
                ranked = sorted(
                    sup.membership.routable(),
                    key=lambda h: __import__("zlib").crc32(
                        f"{sup.router._affinity_key(cand)}|"
                        f"{h.replica_id}".encode()),
                    reverse=True)
                if ranked[0].replica_id == "r0":
                    doc = cand
                    break
            assert doc is not None
            sup.membership.get("r0").burn_gated = True
            shed0 = sup.stats()["router"]["shed"]
            picked = sup.router._pick(doc, exclude=())
            assert picked.replica_id == "r1"
            assert sup.stats()["router"]["shed"] == shed0 + 1
            # all gated: traffic still flows (shedding to nowhere is
            # an outage, not protection)
            sup.membership.get("r1").burn_gated = True
            assert sup.router._pick(doc, exclude=()) is not None
        finally:
            sup.close()


class TestFailover:
    def test_kill_mid_burst_every_answer_typed_or_exact(
            self, catalog, oracle_store):
        """The satellite certification: kill -9 a replica mid-burst
        under the fault harness; every client answer is a correct
        (bit-identical) result or a typed retryable error; zero
        dropped, zero duplicates."""
        from geomesa_tpu.faults import harness as _harness
        from geomesa_tpu.faults.plan import FaultPlan, FaultRule

        burst = 16
        qp = _qpts(burst, seed=9)
        src = oracle_store.get_feature_source("fleeted")
        oracle = {
            f"q{i}": src.knn(CQL, qp[i:i + 1, 0], qp[i:i + 1, 1], k=K)
            for i in range(burst)}
        sup = _fleet(catalog)
        try:
            port = sup.start()
            cli = connect_json("127.0.0.1", port)
            # warm both replicas so the burst measures routing, and so
            # in-flight work is genuinely mid-kernel when the kill lands
            for rep in sup.membership.all():
                w = connect_json(rep.host, rep.port)
                w.request(_knn_doc("w", 1.0, 2.0), timeout_s=300.0)
                w.close()
            # injected device latency keeps several requests in flight
            # across the kill (the harness is the load shaper here; its
            # fires need no replay determinism in this test)
            plan = FaultPlan(seed=13, rules=[FaultRule(
                site="device.transfer", error="latency",
                latency_ms=15.0, every=1)])
            with _harness.active(plan):
                for i in range(burst):
                    cli.send(_knn_doc(f"q{i}", qp[i, 0], qp[i, 1]))
                sup.kill_replica("r0", graceful=False)
                answers = {}
                stop = threading.Event()
                timer = threading.Timer(120.0, stop.set)
                timer.start()
                for got in cli.docs(stop):
                    assert got["id"] not in answers, \
                        f"duplicate response {got['id']}"
                    answers[got["id"]] = got
                    if len(answers) >= burst:
                        break
                timer.cancel()
            assert len(answers) == burst, sorted(answers)
            for rid, got in answers.items():
                if got.get("ok"):
                    d, ix, _ = oracle[rid]
                    assert got["indices"] == [
                        [int(j) for j in row] for row in ix], rid
                    assert got["dists"] == [
                        [float(v) for v in row] for row in d], rid
                else:
                    assert got.get("error") in (
                        "unavailable", "rejected", "timeout"), got
                    assert got.get("retryable", True), got
            snap = sup.stats()
            states = {r["replica"]: r["state"]
                      for r in snap["replicas"]}
            assert states == {"r0": "dead", "r1": "ready"}
            # gauge consistency: retries counted on both surfaces
            assert snap["router"]["retried"] == sum(
                r["retried_onto"] for r in snap["replicas"])
            cli.close()
        finally:
            sup.close()

    def test_drain_verb_admin_gated_and_graceful(self, catalog):
        sup = _fleet(catalog)
        try:
            port = sup.start()
            h0 = sup.membership.get("r0")
            # a plain client may not drain
            direct = connect_json(h0.host, h0.port)
            got = direct.request({"id": "d0", "op": "drain"})
            assert not got["ok"] and got["reason"] == "admin_required"
            # an admin connection drains: hello upgrades the role
            hello = direct.request({"id": "h", "op": "hello",
                                    "role": "admin"})
            assert hello["ok"] and hello["admin"] is True
            assert hello["replica"] == "r0"
            got = direct.request({"id": "d1", "op": "drain"},
                                 timeout_s=120.0)
            assert got["ok"] and got["state"] == "dead", got
            direct.close()
            assert h0.server.state == "dead"
            # the survivor keeps serving through the router
            cli = connect_json("127.0.0.1", port)
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                got = cli.request(_knn_doc("a1", 3.0, 4.0),
                                  timeout_s=120.0)
                if got.get("ok"):
                    break
                assert got.get("error") in ("unavailable",), got
            assert got["ok"], got
            cli.close()
        finally:
            sup.close()

    def test_warming_gate_refuses_until_check_green(self, catalog):
        """A fresh replica refuses traffic typed+retryable until its
        warmup manifest replays with --check semantics green — and the
        router never considers it routable before ready."""
        from geomesa_tpu.compilecache.manifest import WarmupManifest
        from geomesa_tpu.fleet.membership import ReplicaHandle

        sup = _fleet(catalog, n=1)
        try:
            sup.start()
            mpath = catalog + "/warm_manifest.json"
            WarmupManifest().save(mpath)
            hold = threading.Event()
            rep = ReplicaServer(
                lambda: DataStore(catalog, use_device_cache=True),
                replica_id="w0", warmup_manifest=mpath,
                warmup_hold=hold)
            port = rep.start()
            handle = ReplicaHandle(replica_id="w0", host="127.0.0.1",
                                   port=port, spawn="thread",
                                   server=rep)
            sup.membership.add(handle)
            sup.router.attach(handle)
            assert rep.wait_state("warming", timeout=60.0) == "warming"
            probe = connect_json("127.0.0.1", port)
            got = probe.request(_knn_doc("w1", 1.0, 2.0))
            assert not got["ok"] and got["reason"] == "warming"
            assert got["retryable"] is True
            # control verbs still answer while warming
            st = probe.request({"id": "s", "op": "stats"})
            assert st["ok"] and st["stats"]["replica"]["state"] == \
                "warming"
            assert not any(h.replica_id == "w0"
                           for h in sup.membership.routable())
            hold.set()
            assert rep.wait_state("ready", timeout=120.0) == "ready"
            assert rep.warmup_report is not None and \
                rep.warmup_report.ok
            got = probe.request(_knn_doc("w2", 1.0, 2.0),
                                timeout_s=120.0)
            assert got["ok"], got
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if any(h.replica_id == "w0"
                       for h in sup.membership.routable()):
                    break
                time.sleep(0.05)
            assert any(h.replica_id == "w0"
                       for h in sup.membership.routable())
            probe.close()
            rep.stop()
        finally:
            sup.close()

    def test_rolling_restart(self, catalog):
        sup = _fleet(catalog)
        try:
            port = sup.start()
            result = sup.rolling_restart()
            assert result["ok"], result
            assert len(result["rolled"]) == 2
            assert all(r["state"] == "ready" for r in result["rolled"])
            snap = sup.stats()
            states = {r["replica"]: r["state"]
                      for r in snap["replicas"]}
            # old incarnations dead, fresh ones (r0.1, r1.1) serving
            assert states["r0"] == "dead" and states["r1"] == "dead"
            assert states["r0.1"] == "ready"
            assert states["r1.1"] == "ready"
            cli = connect_json("127.0.0.1", port)
            got = cli.request(_knn_doc("rr", 5.0, 6.0), timeout_s=120.0)
            assert got["ok"], got
            cli.close()
        finally:
            sup.close()


class TestProtocolDrain:
    def test_standalone_serve_lines_drain(self, catalog):
        """The drain verb without a fleet: `serve_lines` (stdin is the
        process owner's, hence admin) drains in place — in-flight
        work finishes, later requests answer typed shutting_down."""
        from geomesa_tpu.serve.protocol import serve_lines

        store = DataStore(catalog, use_device_cache=True)
        out = []
        lines = [
            json.dumps({"id": "c1", "op": "count",
                        "typeName": "fleeted", "cql": CQL}),
            json.dumps({"id": "d1", "op": "drain"}),
            json.dumps({"id": "c2", "op": "count",
                        "typeName": "fleeted", "cql": CQL}),
        ]
        serve_lines(store, lines, out.append)
        docs = {json.loads(s)["id"]: json.loads(s) for s in out}
        assert docs["c1"]["ok"]
        assert docs["d1"]["ok"] and docs["d1"]["state"] == "drained"
        assert not docs["c2"]["ok"]
        assert docs["c2"]["reason"] == "shutting_down"

    def test_wire_restart_is_admin_gated(self, catalog):
        from geomesa_tpu.fleet import FleetClient

        sup = _fleet(catalog, n=1)
        try:
            port = sup.start()
            cli = FleetClient("127.0.0.1", port)
            got = cli.request({"op": "restart"})
            assert not got["ok"] and got["reason"] == "admin_required"
            cli.close()
        finally:
            sup.close()

    def test_router_never_proxies_drain(self, catalog):
        """The router's replica links are admin-privileged, so
        forwarding a client's drain would launder it past the
        replica-side admin gate: the router must refuse the verb for
        EVERY session and leave the replica serving."""
        from geomesa_tpu.fleet import FleetClient

        sup = _fleet(catalog, n=1)
        try:
            port = sup.start()
            cli = FleetClient("127.0.0.1", port)
            got = cli.request({"op": "drain"})
            assert not got["ok"] and got["reason"] == "admin_required"
            cli.hello(role="admin")
            got = cli.request({"op": "drain"})
            assert not got["ok"] and got["reason"] == "unsupported"
            # the replica is untouched and still serving
            assert sup.membership.get("r0").state == "ready"
            got = cli.request({"id": "q", "op": "count",
                               "typeName": "fleeted", "cql": CQL},
                              timeout_s=120.0)
            assert got["ok"]
            cli.close()
        finally:
            sup.close()


class TestMetricsPort:
    def test_ephemeral_port_reported(self, catalog):
        """Satellite: MetricsServer port=0 + the bound port reported in
        stats() and the debug endpoints — N replicas on one host must
        not collide on a fixed port."""
        import urllib.request

        rep = ReplicaServer(
            lambda: DataStore(catalog, use_device_cache=True),
            replica_id="m0", metrics_port=0)
        rep.start()
        try:
            assert rep.wait_state("ready", timeout=60.0) == "ready"
            assert rep.metrics_port not in (None, 0)
            assert rep.svc.stats()["metrics_port"] == rep.metrics_port
            assert rep.describe()["metrics_port"] == rep.metrics_port
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{rep.metrics_port}/healthz",
                    timeout=10) as r:
                doc = json.loads(r.read().decode())
            assert doc["endpoint"]["port"] == rep.metrics_port
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{rep.metrics_port}/debug/stats",
                    timeout=10) as r:
                doc = json.loads(r.read().decode())
            assert doc["endpoint"]["port"] == rep.metrics_port
            assert doc["serve"]["metrics_port"] == rep.metrics_port
        finally:
            rep.stop()

    def test_fleet_snapshot_reports_bound_ports(self, catalog):
        """The {"op": "fleet"} / status document must carry each
        replica's BOUND ephemeral metrics port (thread replicas bind
        theirs asynchronously during init)."""
        sup = _fleet(catalog, metrics_port=0)
        try:
            sup.start()
            ports = [r["metrics_port"]
                     for r in sup.stats()["replicas"]]
            assert all(p not in (None, 0) for p in ports), ports
            assert len(set(ports)) == len(ports), ports
        finally:
            sup.close()

    def test_two_replicas_distinct_ports(self, catalog):
        reps = [ReplicaServer(
            lambda: DataStore(catalog, use_device_cache=True),
            replica_id=f"mp{i}", metrics_port=0) for i in range(2)]
        try:
            for r in reps:
                r.start()
            for r in reps:
                assert r.wait_state("ready", timeout=60.0) == "ready"
            ports = {r.metrics_port for r in reps}
            assert len(ports) == 2 and None not in ports
        finally:
            for r in reps:
                r.stop()


@pytest.mark.slow
class TestProcessSpawn:
    def test_process_fleet_kill_and_failover(self, catalog):
        """Real OS-process replicas (jax import and all): spawn 2,
        serve, kill -9 one, keep serving. The deployment shape."""
        sup = FleetSupervisor(FleetConfig(
            n_replicas=2, catalog=catalog, spawn="process",
            probe_interval_s=0.3, force_cpu_workers=True))
        try:
            port = sup.start()
            cli = connect_json("127.0.0.1", port)
            got = cli.request(_knn_doc("p1", 1.0, 2.0),
                              timeout_s=600.0)
            assert got["ok"], got
            victim = sup.membership.get("r0")
            assert victim.pid is not None
            sup.kill_replica("r0", graceful=False)
            got = cli.request(_knn_doc("p2", 3.0, 4.0),
                              timeout_s=600.0)
            assert got["ok"], got
            states = {r["replica"]: r["state"]
                      for r in sup.stats()["replicas"]}
            assert states["r0"] == "dead" and states["r1"] == "ready"
            cli.close()
        finally:
            sup.close()
