"""Device cache manager: residency, refresh, restart determinism, and
cached-vs-scan query parity (SURVEY.md §5.4 checkpoint/resume analog)."""

import numpy as np
import pytest

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.plan.hints import QueryHints
from geomesa_tpu.plan.query import Query
from geomesa_tpu.store.cache import DeviceCacheManager


def make_batch(n=300, seed=2):
    rng = np.random.default_rng(seed)
    sft = SimpleFeatureType.from_spec(
        "gdelt", "actor:String,score:Double,dtg:Date,*geom:Point"
    )
    return sft, FeatureBatch.from_pydict(
        sft,
        {
            "actor": rng.choice(["USA", "FRA", "CHN"], n).tolist(),
            "score": rng.uniform(-10, 10, n),
            "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
            "geom": np.stack(
                [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1
            ),
        },
    )


CQL = (
    "BBOX(geom, -120, -60, 120, 60) AND score > 0 AND "
    "dtg DURING 2020-06-01T00:00:00Z/2020-09-01T00:00:00Z"
)


@pytest.fixture()
def stores(tmp_path):
    sft, batch = make_batch()
    plain = DataStore(str(tmp_path / "plain"))
    cached = DataStore(str(tmp_path / "cached"), use_device_cache=True)
    plain.create_schema(sft).write(batch)
    cached.create_schema(sft).write(batch)
    return sft, batch, plain, cached


def test_cached_query_parity_features(stores):
    sft, batch, plain, cached = stores
    a = plain.get_feature_source("gdelt").get_features(CQL)
    b = cached.get_feature_source("gdelt").get_features(CQL)
    assert a.count == b.count
    sa = np.sort(np.asarray(a.features.columns["score"])) if a.features else []
    sb = np.sort(np.asarray(b.features.columns["score"])) if b.features else []
    np.testing.assert_allclose(sa, sb)


def test_cached_query_parity_density_stats(stores):
    sft, batch, plain, cached = stores
    q = Query(
        "gdelt", CQL,
        hints=QueryHints(density_bbox=(-120, -60, 120, 60),
                         density_width=16, density_height=16),
    )
    ga = plain.get_feature_source("gdelt").get_features(q)
    gb = cached.get_feature_source("gdelt").get_features(q)
    np.testing.assert_allclose(ga.grid, gb.grid, atol=1e-4)
    q2 = Query("gdelt", CQL, hints=QueryHints(stats_string="MinMax(score);Count()"))
    sa = plain.get_feature_source("gdelt").get_features(q2)
    sb = cached.get_feature_source("gdelt").get_features(q2)
    assert sa.stats.stats[0].result() == sb.stats.stats[0].result()


def test_cache_refresh_after_write(stores):
    sft, batch, plain, cached = stores
    src = cached.get_feature_source("gdelt")
    before = src.get_count(CQL)
    _, more = make_batch(150, seed=9)
    src.write(more)
    plain.get_feature_source("gdelt").write(more)
    after = src.get_count(CQL)
    expected = plain.get_feature_source("gdelt").get_count(CQL)
    assert after == expected
    assert after >= before


def test_manifest_resume_deterministic(tmp_path):
    sft, batch = make_batch()
    ds = DataStore(str(tmp_path / "c"))
    src = ds.create_schema(sft)
    src.write(batch)
    m1 = DeviceCacheManager(src.storage)
    m1.ensure()
    assert m1.resident()
    m1.save_manifest()
    stats1 = m1.stats()

    # fresh manager on the same storage rebuilds identical residency
    m2 = DeviceCacheManager(src.storage)
    restored, stale = m2.resume()
    assert restored == m1.resident()
    assert stale == []
    assert m2.stats() == stats1


def test_manifest_resume_detects_drift(tmp_path):
    sft, batch = make_batch()
    ds = DataStore(str(tmp_path / "c"))
    src = ds.create_schema(sft)
    src.write(batch)
    m1 = DeviceCacheManager(src.storage)
    m1.ensure()
    m1.save_manifest()
    # write more data -> file lists drift -> stale on resume
    _, more = make_batch(50, seed=4)
    src.write(more)
    m2 = DeviceCacheManager(src.storage)
    restored, stale = m2.resume()
    assert stale  # at least one partition changed
    # ensure() then brings everything fresh
    m2.ensure()
    assert set(m2.resident()) == set(src.storage.partitions())


def test_cache_invalidate_and_stats(tmp_path):
    sft, batch = make_batch()
    ds = DataStore(str(tmp_path / "c"))
    src = ds.create_schema(sft)
    src.write(batch)
    m = DeviceCacheManager(src.storage)
    m.ensure()
    s = m.stats()
    assert s["rows"] == len(batch)
    assert s["padded_rows"] >= s["rows"]
    p = m.resident()[0]
    m.invalidate(p)
    assert p not in m.resident()
    m.invalidate()
    assert m.resident() == []


def test_superbatch_stable_when_residency_unchanged(tmp_path):
    # repeat ensure() calls with unchanged residency must serve the SAME
    # superbatch object (a rebuild re-uploads every resident row)
    sft, batch = make_batch()
    ds = DataStore(str(tmp_path / "c"))
    src = ds.create_schema(sft)
    src.write(batch)
    m = DeviceCacheManager(src.storage)
    m.ensure()
    sb1 = m.superbatch()
    m.ensure()
    assert m.superbatch() is sb1
    # a genuine residency change invalidates
    src.write(batch)
    m.ensure()
    assert m.superbatch() is not sb1


def test_cached_loose_bbox_falls_back_exact(stores):
    """loose_bbox on the cached store must not return out-of-bbox rows:
    the cached path falls back to the scan path (parquet pushdown
    re-applies the bbox row-exactly)."""
    sft, batch, plain, cached = stores
    q = Query("gdelt", "BBOX(geom, -20, -10, 20, 10)",
              hints=QueryHints(loose_bbox=True))
    a = plain.get_feature_source("gdelt").get_features(q)
    b = cached.get_feature_source("gdelt").get_features(q)
    assert a.count == b.count


class TestMeshGrowthDelta:
    """Mesh residency GROWTH (ROADMAP item 4 foundation): appending new
    partitions uploads only the delta tile — host→device row counters
    must NOT scale with resident size on append. Layout-invalidating
    changes (rewriting an existing partition) still take the full
    re-tier."""

    def test_append_uploads_delta_not_resident_size(self, tmp_path):
        import numpy as np

        from geomesa_tpu.core.columnar import FeatureBatch
        from geomesa_tpu.core.sft import SimpleFeatureType
        from geomesa_tpu.parallel.mesh import serve_mesh
        from geomesa_tpu.plan.datastore import DataStore
        from geomesa_tpu.store.partition import DateTimeScheme

        sft = SimpleFeatureType.from_spec(
            "t", "actor:String,score:Double,dtg:Date,*geom:Point"
        )
        rng = np.random.default_rng(7)

        def mk(n, month, actors=("AA", "BB")):
            t0 = np.datetime64(f"2020-{month:02d}-10").astype(
                "datetime64[ms]").astype(np.int64)
            return FeatureBatch.from_pydict(sft, {
                "actor": rng.choice(list(actors), n).tolist(),
                "score": rng.uniform(-5, 5, n),
                "dtg": t0 + rng.integers(0, 86_400_000, n),
                "geom": np.stack([rng.uniform(-10, 10, n),
                                  rng.uniform(-10, 10, n)], 1),
            })

        ds = DataStore(str(tmp_path / "cat"), use_device_cache=True)
        src = ds.create_schema(sft, DateTimeScheme("yyyy/MM"))
        src.write(mk(50, 6))
        mesh = serve_mesh(4)
        assert mesh is not None  # conftest forces 8 host devices
        ds.set_mesh(mesh)
        q = "BBOX(geom, -20, -20, 20, 20)"
        n0 = src.get_count(q)
        cache = src.planner.cache
        assert cache.superbatch_peek() is not None
        assert cache.superbatch_peek().mesh is mesh
        r0 = cache.upload_rows

        # two equal-size appends: each delta must be the APPEND's rows
        # (plus pow2/mesh padding), not the resident total — equal
        # appends therefore cost equal uploads even as residency grows
        deltas = []
        counts = [n0]
        for month in (7, 8):
            src.write(mk(40, month))
            counts.append(src.get_count(q))
            r1 = cache.upload_rows
            deltas.append(r1 - r0)
            r0 = r1
        resident = sum(
            e.padded for e in cache._entries.values())
        assert deltas[0] == deltas[1], deltas
        assert deltas[1] < resident, (deltas, resident)
        assert counts[-1] >= counts[0]

        # bit-exact parity with the host path over the grown store
        ds2 = DataStore(str(tmp_path / "cat"), use_device_cache=False)
        assert ds2.get_feature_source("t").get_count(q) == counts[-1]

        # layout-invalidating change: an EXISTING partition's files
        # move → the full host concat re-uploads (ownership is stale)
        src.write(mk(30, 6))
        n_final = src.get_count(q)
        full_delta = cache.upload_rows - r0
        assert full_delta > deltas[1], (full_delta, deltas)
        # fresh host-path store AFTER the write (a pre-write instance
        # would pin the older manifest)
        ds3 = DataStore(str(tmp_path / "cat"), use_device_cache=False)
        assert ds3.get_feature_source("t").get_count(q) == n_final


class TestIncrementalSegments:
    """Round-3 (VERDICT #3): residency changes must not re-upload
    unchanged partition segments, and dict codes must stay consistent
    between the host superbatch and the device segments."""

    def test_partition_update_reuploads_only_changed(self, tmp_path):
        import numpy as np

        from geomesa_tpu.core.columnar import FeatureBatch
        from geomesa_tpu.core.sft import SimpleFeatureType
        from geomesa_tpu.plan.datastore import DataStore
        from geomesa_tpu.store.partition import DateTimeScheme

        sft = SimpleFeatureType.from_spec(
            "t", "actor:String,score:Double,dtg:Date,*geom:Point"
        )
        rng = np.random.default_rng(7)

        def mk(n, month, actors):
            t0 = np.datetime64(f"2020-{month:02d}-10").astype(
                "datetime64[ms]").astype(np.int64)
            return FeatureBatch.from_pydict(sft, {
                "actor": rng.choice(actors, n).tolist(),
                "score": rng.uniform(-5, 5, n),
                "dtg": t0 + rng.integers(0, 86_400_000, n),
                "geom": np.stack([rng.uniform(-10, 10, n),
                                  rng.uniform(-10, 10, n)], 1),
            })

        ds = DataStore(str(tmp_path / "cat"), use_device_cache=True)
        src = ds.create_schema(sft, DateTimeScheme("yyyy/MM"))
        src.write(mk(50, 6, ["AA", "BB"]))
        src.write(mk(40, 7, ["BB", "CC"]))

        q = "BBOX(geom, -20, -20, 20, 20) AND actor = 'BB'"
        n1 = src.get_count(q)
        planner = src.planner
        assert planner.cache is not None
        up0 = planner.cache.upload_count
        assert up0 >= 2  # both partitions were uploaded once

        # write to ONE partition: only it re-uploads
        src.write(mk(25, 7, ["CC", "AA"]))
        n2 = src.get_count(q)
        up1 = planner.cache.upload_count
        assert up1 == up0 + 1, (up0, up1)
        assert n2 >= n1

        # parity: host-path count equals cached-path count (dict codes in
        # the shared vocab space must agree between host and device)
        ds2 = DataStore(str(tmp_path / "cat"), use_device_cache=False)
        assert ds2.get_feature_source("t").get_count(q) == n2
