"""geomesa_tpu.approx: the approximate-answer tier + result cache.

The load-bearing suite is TestParity: 20 mixed workload batches —
writes between queries included, so version invalidation is OBSERVED —
where every sketch-served answer's reported bound must contain the
exact device answer, repeated exact queries are bit-identical cache
hits, and the sketch path compiles nothing. TestClosedLoopSlo drives
the exactness-budget governor end to end: budget exhaustion measurably
shifts traffic to the exact path (no silent accuracy loss).

Wall-clock discipline (tier-1 budget): one module store, a fixed small
CQL set (filter compiles amortize across tests), pure-numpy bound-math
fuzzing where no device is needed.
"""

import time

import numpy as np
import pytest

from geomesa_tpu.approx import (
    ApproxCount, PartitionSketchStore, ResultCache, entry_token,
    merge_count_bounds, resample_bounds, result_key, topk_cell_bounds)
from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.cql.extract import BBox, Interval
from geomesa_tpu.plan.hints import QueryHints
from geomesa_tpu.plan.query import Query
from geomesa_tpu.serve.scheduler import ServeRequest
from geomesa_tpu.serve.service import QueryService, ServeConfig

SFT_SPEC = "name:String,score:Double,dtg:Date,*geom:Point"

CQLS = [
    "BBOX(geom, -180, -90, 180, 90)",
    "BBOX(geom, -60, -30, 60, 30)",
    "BBOX(geom, 0, 0, 90, 45)",
]

T0, T1 = 1_590_000_000_000, 1_600_000_000_000


def _batch(sft, seed, n, narrow_dtg=False):
    rng = np.random.default_rng(seed)
    # narrow_dtg: confine the write to ~one weekly partition so the
    # incremental-write tests pay one partition's sketch rebuild + a
    # small residency delta, not a full-store churn (wall budget)
    dtg = (rng.integers(T0, T0 + 6 * 86_400_000, n) if narrow_dtg
           else rng.integers(T0, T1, n))
    return FeatureBatch.from_pydict(sft, {
        "name": rng.choice(["a", "b", "c"], n).tolist(),
        "score": rng.uniform(-10, 10, n),
        "dtg": dtg,
        "geom": np.stack([rng.uniform(-170, 170, n),
                          rng.uniform(-80, 80, n)], 1),
    })


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    from geomesa_tpu.plan.datastore import DataStore

    sft = SimpleFeatureType.from_spec("apx", SFT_SPEC)
    ds = DataStore(str(tmp_path_factory.mktemp("approx")),
                   use_device_cache=True)
    src = ds.create_schema(sft)
    src.write(_batch(sft, 1, 4096))
    return ds


# -- pure bound math (no device) --------------------------------------------


class TestBoundMath:
    """Deterministic-interval guarantees fuzzed against brute force:
    the whole tier's honesty rests on these brackets."""

    def _sketch_store(self, tmp_path, n=3000, seed=0):
        from geomesa_tpu.plan.datastore import DataStore

        sft = SimpleFeatureType.from_spec("bm", SFT_SPEC)
        ds = DataStore(str(tmp_path), use_device_cache=False)
        src = ds.create_schema(sft)
        rng = np.random.default_rng(seed)
        xs = rng.uniform(-179, 179, n)
        ys = rng.uniform(-89, 89, n)
        ts = rng.integers(T0, T1, n)
        src.write(FeatureBatch.from_pydict(sft, {
            "name": rng.choice(["a", "b"], n).tolist(),
            "score": rng.uniform(-1, 1, n),
            "dtg": ts,
            "geom": np.stack([xs, ys], 1),
        }))
        storage = src.storage
        pstore = PartitionSketchStore(storage)
        snap = storage.manifest_snapshot()
        sketches = [pstore.build(name, snap[name]) for name in snap]
        return xs, ys, ts, sketches

    def test_count_bounds_bracket_brute_force(self, tmp_path):
        xs, ys, ts, sketches = self._sketch_store(tmp_path)
        rng = np.random.default_rng(42)
        for i in range(40):
            x0, x1 = sorted(rng.uniform(-185, 185, 2))
            y0, y1 = sorted(rng.uniform(-95, 95, 2))
            if rng.random() < 0.3:
                interval = Interval(None, None)
            else:
                a, b = sorted(rng.integers(T0, T1, 2))
                interval = Interval(int(a), int(b))
            truth = np.sum(
                (xs >= x0) & (xs <= x1) & (ys >= y0) & (ys <= y1)
                & ((interval.start is None)
                   | (ts >= (interval.start or 0)))
                & ((interval.end is None) | (ts <= (interval.end or 0))))
            lo, hi = merge_count_bounds(
                sketches, BBox(x0, y0, x1, y1), interval)
            assert lo <= truth <= hi, (i, lo, truth, hi)

    def test_resample_bound_holds_per_cell(self, tmp_path):
        xs, ys, ts, sketches = self._sketch_store(tmp_path, seed=3)
        from geomesa_tpu.approx.sketches import merge_region

        sure, maybe, b = merge_region(sketches, Interval(None, None))
        rng = np.random.default_rng(7)
        for _ in range(8):
            x0, x1 = sorted(rng.uniform(-150, 150, 2))
            y0, y1 = sorted(rng.uniform(-70, 70, 2))
            if x1 - x0 < 20 or y1 - y0 < 10:
                continue
            w, h = int(rng.integers(4, 14)), int(rng.integers(3, 9))
            grid, bound = resample_bounds(sure, maybe, (x0, y0, x1, y1),
                                          w, h)
            # brute-force truth grid with the same floor binning
            dx, dy = (x1 - x0) / w, (y1 - y0) / h
            col = np.floor((xs - x0) / dx).astype(int)
            row = np.floor((ys - y0) / dy).astype(int)
            inb = (col >= 0) & (col < w) & (row >= 0) & (row < h)
            truth = np.zeros((h, w))
            np.add.at(truth, (row[inb], col[inb]), 1.0)
            assert np.abs(grid - truth).max() <= bound + 1e-9

    def test_topk_cells_bracket_brute_force(self, tmp_path):
        xs, ys, ts, sketches = self._sketch_store(tmp_path, seed=5)
        from geomesa_tpu.approx.sketches import merge_region

        sure, maybe, b = merge_region(sketches, Interval(None, None))
        bbox = BBox(-60, -30, 60, 30)
        cells = topk_cell_bounds(sure, maybe, bbox, 10)
        assert cells
        sel = (xs >= bbox.xmin) & (xs <= bbox.xmax) \
            & (ys >= bbox.ymin) & (ys <= bbox.ymax)
        cx = np.clip(((xs + 180.0) / 360.0 * b).astype(int), 0, b - 1)
        cy = np.clip(((ys + 90.0) / 180.0 * b).astype(int), 0, b - 1)
        for c in cells:
            truth = int(np.sum(sel & (cx == c["col"]) & (cy == c["row"])))
            assert abs(c["count"] - truth) <= c["bound"], (c, truth)

    def test_entry_token_moves_with_writes(self, store):
        storage = store.get_feature_source("apx").storage
        snap = storage.manifest_snapshot()
        name = next(iter(snap))
        assert entry_token(snap[name]) == entry_token(snap[name])
        assert entry_token(snap[name]) != entry_token(
            snap[name] + [{"file": "x", "count": 1}])


# -- parity over mixed batches (device exact vs sketch) ----------------------


class TestParity:
    def test_bounds_contain_exact_across_20_batches(self, store):
        """20 mixed workload batches, writes between queries: every
        sketch answer's bound contains the exact device answer, the
        version moves are OBSERVED (post-write answers track the new
        data), and the sketch path compiles nothing."""
        from geomesa_tpu.analysis.runtime import (
            acquire_engine_tracker, release_engine_tracker)

        src = store.get_feature_source("apx")
        pl = src.planner
        sft = src.sft
        interval_cql = (
            "BBOX(geom, -90, -45, 90, 45) AND dtg DURING "
            "2020-05-25T00:00:00Z/2020-08-01T00:00:00Z")
        cqls = CQLS + [interval_cql]
        # warm the exact path once (filter compiles + device cache)
        for cql in cqls:
            pl.count(Query("apx", cql))
        tracker, _ = acquire_engine_tracker()
        try:
            base_recompiles = tracker.total_recompiles()
            sketch_served = 0
            verified = 0
            version_changes = 0
            last = {}
            for i in range(20):
                if i % 5 == 4:
                    src.write(_batch(sft, 100 + i, 256,
                                     narrow_dtg=True))
                # the whole tolerant workload serves every batch; the
                # exact device verification rotates (wall budget) —
                # every cql is verified against exact several times,
                # including right after each write
                verify_cql = cqls[i % len(cqls)]
                for cql in cqls:
                    a = pl.count(Query(
                        "apx", cql, hints=QueryHints(tolerance=0.25)))
                    if not isinstance(a, ApproxCount):
                        continue
                    sketch_served += 1
                    assert a.confidence == 1.0
                    if cql == verify_cql:
                        exact = pl.count(Query("apx", cql))
                        verified += 1
                        assert abs(int(a) - exact) <= a.bound, (
                            i, cql, int(a), a.bound, exact)
                        if cql in last and last[cql] != (int(a), exact):
                            version_changes += 1
                        last[cql] = (int(a), exact)
            assert sketch_served >= 40  # the tier actually served
            assert verified >= 12       # bound-vs-exact, incl. post-write
            assert version_changes > 0  # invalidation observed
            # zero-recompile: the sketch path never touches the device
            assert tracker.total_recompiles() == base_recompiles
        finally:
            release_engine_tracker(tracker)

    def test_density_and_topk_parity(self, store):
        pl = store.get_feature_source("apx").planner
        dh = QueryHints(tolerance=0.5,
                        density_bbox=(-60.0, -30.0, 60.0, 30.0),
                        density_width=12, density_height=6)
        r = pl.execute(Query("apx", CQLS[1], hints=dh))
        re_ = pl.execute(Query("apx", CQLS[1], hints=QueryHints(
            density_bbox=(-60.0, -30.0, 60.0, 30.0),
            density_width=12, density_height=6)))
        assert r.approx and not re_.approx
        assert np.abs(np.asarray(r.grid)
                      - np.asarray(re_.grid)).max() <= r.bound + 1e-9
        rt = pl.execute(Query("apx", CQLS[1],
                              hints=QueryHints(tolerance=1.0,
                                               topk_cells=5)))
        rte = pl.execute(Query("apx", CQLS[1],
                               hints=QueryHints(topk_cells=5)))
        assert rt.approx and rt.kind == "topk_cells"
        assert not rte.approx and rte.kind == "topk_cells"
        exact_by_cell = {(c["row"], c["col"]): c["count"]
                         for c in rte.stats}
        for c in rt.stats:
            truth = exact_by_cell.get((c["row"], c["col"]))
            if truth is not None:
                assert abs(c["count"] - truth) <= c["bound"]

    def test_sketch_p50_speedup_over_exact(self, store):
        """The headline: warm tolerant counts vs warm exact device
        counts — asserted at a conservative 25x (measured >100x on CI
        hardware; ISSUE acceptance is 50x, reported by bench-serve
        --mode approx)."""
        pl = store.get_feature_source("apx").planner
        qa = Query("apx", CQLS[1], hints=QueryHints(tolerance=0.25))
        qe = Query("apx", CQLS[1])
        assert isinstance(pl.count(qa), ApproxCount)  # warm + eligible
        pl.count(qe)

        def p50(q, reps=15):
            ts = []
            for _ in range(reps):
                t = time.perf_counter()
                pl.count(q)
                ts.append(time.perf_counter() - t)
            return float(np.percentile(ts, 50))

        a, e = p50(qa), p50(qe)
        assert e / a >= 25.0, f"sketch p50 {a*1e3:.3f}ms vs exact " \
                              f"{e*1e3:.3f}ms = {e/a:.1f}x"

    def test_ineligible_filters_route_exact(self, store):
        pl = store.get_feature_source("apx").planner
        for cql in ("name = 'a'",
                    "BBOX(geom, -60, -30, 60, 30) AND score > 0",
                    "BBOX(geom,-10,-10,10,10) OR BBOX(geom,20,20,30,30)"):
            a = pl.count(Query("apx", cql, hints=QueryHints(tolerance=0.5)))
            assert not isinstance(a, ApproxCount), cql
            assert a == pl.count(Query("apx", cql))


# -- stale-sketch fallthrough (the torn-merge fix) ---------------------------


class TestStaleFallthrough:
    def test_version_mismatch_never_serves(self, store):
        """A sketch built at version V is NEVER merged at V+1: the
        typed StaleSketch fallthrough routes exact (metered) instead
        of a torn merge — the satellite fix for the stats_manager's
        lazy-rebuild race."""
        src = store.get_feature_source("apx")
        pl = src.planner
        eng = pl.approx_engine()
        q = Query("apx", CQLS[1], hints=QueryHints(tolerance=0.25))
        assert isinstance(pl.count(q), ApproxCount)
        src.write(_batch(src.sft, 999, 128, narrow_dtg=True))  # version moves
        storage = src.storage
        snap = storage.manifest_snapshot()
        # every partition the write touched: the cached sketch's token
        # no longer matches -> get() refuses
        stale = [name for name in snap
                 if eng.store.get(name, snap[name]) is None]
        assert stale, "the write must have invalidated some partition"
        # with builds disabled the engine must fall through TYPED
        eng.allow_build = False
        try:
            a = pl.count(q)
            assert not isinstance(a, ApproxCount)
            assert eng.last_reason == "stale_sketch"
            assert a == pl.count(Query("apx", CQLS[1]))  # exact answer
        finally:
            eng.allow_build = True
        # builds re-enabled: version-exact again, bound contains exact
        a2 = pl.count(q)
        assert isinstance(a2, ApproxCount)
        assert abs(int(a2) - pl.count(Query("apx", CQLS[1]))) <= a2.bound


# -- sidecar persistence ------------------------------------------------------


class TestSidecarPersistence:
    """The manifest-versioned sketch sidecar (ROADMAP item 2 remaining
    rung): a second process loads version-exact sketches from disk
    instead of re-scanning partitions; a stale entry is a typed
    skip-and-rebuild, never a torn load."""

    def test_warm_spinup_answers_without_builds(self, tmp_path):
        import os

        from geomesa_tpu.plan.datastore import DataStore

        sft = SimpleFeatureType.from_spec("apx", SFT_SPEC)
        root = str(tmp_path / "cat")
        ds = DataStore(root, use_device_cache=True)
        src = ds.create_schema(sft)
        src.write(_batch(sft, 21, 1024))
        q = Query("apx", CQLS[1], hints=QueryHints(tolerance=0.25))
        pl = src.planner
        a1 = pl.count(q)
        assert isinstance(a1, ApproxCount)
        eng1 = pl.approx_engine()
        assert os.path.exists(eng1.store.sidecar_path)

        # "replica spin-up": a fresh process-equivalent store over the
        # same catalog — the sidecar pre-installs every sketch, so the
        # first tolerant answer runs ZERO partition builds
        ds2 = DataStore(root, use_device_cache=True)
        pl2 = ds2.get_feature_source("apx").planner
        eng2 = pl2.approx_engine()
        st = eng2.store.stats()
        assert st["sidecar_loaded"] >= 1 and st["sidecar_stale"] == 0
        # every partition already serves version-exact from the loaded
        # sidecar — the first tolerant answer needs ZERO builds
        snap = pl2.storage.manifest_snapshot()
        assert all(eng2.store.get(n, snap[n]) is not None for n in snap)
        eng2.allow_build = False  # a build attempt would now raise/route exact
        try:
            a2 = pl2.count(q)
        finally:
            eng2.allow_build = True
        assert isinstance(a2, ApproxCount)
        assert int(a2) == int(a1) and a2.bound == a1.bound

    def test_stale_sidecar_is_typed_rebuild(self, tmp_path):
        from geomesa_tpu.plan.datastore import DataStore

        sft = SimpleFeatureType.from_spec("apx", SFT_SPEC)
        root = str(tmp_path / "cat")
        ds = DataStore(root, use_device_cache=True)
        src = ds.create_schema(sft)
        src.write(_batch(sft, 31, 512, narrow_dtg=True))
        q = Query("apx", CQLS[0], hints=QueryHints(tolerance=0.25))
        pl = src.planner
        assert isinstance(pl.count(q), ApproxCount)
        # the write happens AFTER the sidecar was persisted: its
        # token no longer matches the committed manifest
        src.write(_batch(sft, 32, 256, narrow_dtg=True))

        ds2 = DataStore(root, use_device_cache=True)
        src2 = ds2.get_feature_source("apx")
        eng2 = src2.planner.approx_engine()
        st = eng2.store.stats()
        assert st["sidecar_stale"] >= 1  # never installed torn
        # the stale partition rebuilds from a pinned read on first use;
        # the answer stays bound-correct against the exact count
        a = src2.planner.count(q)
        assert isinstance(a, ApproxCount)
        exact = src2.planner.count(Query("apx", CQLS[0]))
        assert abs(int(a) - int(exact)) <= a.bound


# -- result cache ------------------------------------------------------------


class TestResultCache:
    def test_lru_hit_miss_evict(self):
        c = ResultCache(max_entries=2)
        k1 = ("count", "t", "CQL1", "h", None, 1)
        k2 = ("count", "t", "CQL2", "h", None, 1)
        k3 = ("count", "t", "CQL3", "h", None, 1)
        assert c.get(k1) == (False, None)
        c.put(k1, 11)
        c.put(k2, 22)
        assert c.get(k1) == (True, 11)
        c.put(k3, 33)  # evicts k2 (k1 was touched more recently)
        assert c.get(k2) == (False, None)
        s = c.stats()
        assert s["hits"] == 1 and s["misses"] == 2 and s["evictions"] == 1

    def test_result_key_canonicalizes_and_gates(self):
        qa = Query("t", "BBOX(geom, 0,0, 10, 10)  AND name = 'a'")
        qb = Query("t", "BBOX(geom,0,0,10,10) AND name='a'")
        assert result_key("count", qa, 7) == result_key("count", qb, 7)
        assert result_key("count", qa, 7) != result_key("count", qa, 8)
        assert result_key("knn", qa, 7) is None
        assert result_key("count", qa, None) is None
        qt = Query("t", "INCLUDE", hints=QueryHints(tolerance=0.1))
        assert result_key("count", qt, 7) is None

    def test_serve_cache_bit_identical_and_version_exact(self, store):
        svc = QueryService(store, ServeConfig(max_wait_ms=0.0))
        try:
            r1 = svc.query("apx", CQLS[1]).result(timeout=300)
            r2 = svc.query("apx", CQLS[1]).result(timeout=300)
            assert r2 is r1  # bit-identical by object identity
            st = svc.stats()
            assert st["cache"]["hits"] >= 1
            assert st["approx"]["tiers"]["cached"] >= 1
            # a write bumps the version: the next run recomputes
            src = store.get_feature_source("apx")
            src.write(_batch(src.sft, 500, 64, narrow_dtg=True))
            r3 = svc.query("apx", CQLS[1]).result(timeout=300)
            assert r3 is not r1
        finally:
            svc.close(drain=True)


# -- serve tier + closed-loop SLO governor -----------------------------------


class TestServeTier:
    def test_admission_resolution_and_attribution(self, store):
        svc = QueryService(store, ServeConfig(max_wait_ms=0.0))
        try:
            base = svc.stats()
            req = ServeRequest(kind="count", query=Query(
                "apx", CQLS[1], hints=QueryHints(tolerance=0.25)))
            got = svc.submit(req).result(timeout=300)
            assert isinstance(got, ApproxCount) and req.approx
            st = svc.stats()
            assert st["approx"]["tiers"]["sketch"] >= \
                base["approx"]["tiers"]["sketch"] + 1
            evs = [e for e in store.audit.snapshot()
                   if getattr(e, "approx", False)]
            assert evs and evs[-1].kind == "count"
        finally:
            svc.close(drain=True)

    def test_wire_carries_bound_and_cached(self, store):
        import json as _json

        from geomesa_tpu.serve.protocol import serve_lines

        out = []

        def lines():
            yield _json.dumps({"id": "a1", "op": "count",
                               "typeName": "apx", "cql": CQLS[1],
                               "tolerance": 0.25})
            yield _json.dumps({"id": "e1", "op": "count",
                               "typeName": "apx", "cql": CQLS[1]})
            # e2 is the dashboard's REPEAT: it must arrive after e1
            # resolved (else the batcher dedups them into one window
            # and the cache never comes into play)
            deadline = time.monotonic() + 60
            while not any(_json.loads(d)["id"] == "e1" for d in out):
                assert time.monotonic() < deadline
                time.sleep(0.005)
            yield _json.dumps({"id": "e2", "op": "count",
                               "typeName": "apx", "cql": CQLS[1]})

        serve_lines(store, lines(), out.append,
                    ServeConfig(max_wait_ms=0.0))
        docs = {d["id"]: d for d in map(_json.loads, out)}
        assert docs["a1"]["approx"] is True
        assert docs["a1"]["confidence"] == 1.0
        exact = docs["e1"]["count"]
        assert abs(docs["a1"]["count"] - exact) <= docs["a1"]["bound"]
        assert "approx" not in docs["e1"]
        assert docs["e2"]["count"] == exact
        assert docs["e2"].get("cached") is True

    def test_closed_loop_exactness_budget(self, store):
        """Budget spent => MORE traffic to the exact path: tolerant
        counts serve from sketches (each spends exactness budget)
        until the budget is gone, after which the SAME tolerant
        request is served EXACT — never silently less accurate."""
        from geomesa_tpu.telemetry.slo import SloEngine, SloSpec

        now = [1000.0]
        spec = SloSpec.from_dict({
            "slo": {"fast_window_s": 5.0, "slow_window_s": 10.0,
                    "burn_threshold": 1.5},
            "objective": {
                "exactness": {"kind": "exactness", "goal": 0.9,
                              "degrade": True, "min_count": 4},
            },
        })
        engine = SloEngine(spec, clock=lambda: now[0])
        svc = QueryService(store, ServeConfig(max_wait_ms=0.0,
                                              slo=engine))
        try:
            q = Query("apx", CQLS[1], hints=QueryHints(tolerance=0.25))
            approx_phase = 0
            exact_phase = 0
            for i in range(10):
                req = ServeRequest(kind="count", query=Query(
                    "apx", CQLS[1], hints=QueryHints(tolerance=0.25)))
                got = svc.submit(req).result(timeout=300)
                if isinstance(got, ApproxCount):
                    approx_phase += 1
                else:
                    exact_phase += 1
                now[0] += 0.2
            # the first requests served approx and burned the budget;
            # once spent, tolerance is stripped at admission and the
            # tail of the workload is exact
            assert approx_phase >= 4
            assert exact_phase >= 1
            assert svc.stats()["approx_budget_exact"] >= 1
            assert not svc._approx_ok()
            # recovery: the degraded observations age out of the
            # budget window and sketch serving resumes
            now[0] += 30.0
            req = ServeRequest(kind="count", query=q)
            got = svc.submit(req).result(timeout=300)
            assert isinstance(got, ApproxCount)
        finally:
            svc.close(drain=True)

    def test_degrade_ladder_sketch_rung(self, store):
        # warm the sketches at the CURRENT version first: the
        # admission peek never builds (builds belong to the dispatch
        # thread), so the rung resolves at submit only when warm
        pl = store.get_feature_source("apx").planner
        assert isinstance(
            pl.count(Query("apx", CQLS[1],
                           hints=QueryHints(tolerance=0.5))),
            ApproxCount)
        cfg = ServeConfig(max_queue=4, degrade=True,
                          degrade_watermark=0.25, shed_watermark=0.9,
                          max_wait_ms=0.0,
                          approx_degrade_tolerance=0.5)
        svc = QueryService(store, cfg, autostart=False)
        try:
            svc.count("apx", "score > 1")  # queue occupancy
            req = svc._request("count", Query("apx", CQLS[1]),
                               allow_degraded=True)
            fut = svc.submit(req)
            # sketch-eligible filter: the FIRST rung is the sketch
            # tier, not loose-bbox, and it resolved AT ADMISSION with
            # a typed bound — degraded accounting lands WITH the serve
            assert req.sketch_rung == 1
            assert req.query.hints.tolerance == \
                cfg.approx_degrade_tolerance
            assert not req.query.hints.loose_bbox
            assert fut.done()
            assert isinstance(fut.result(), ApproxCount)
            assert req.degraded
            # an INELIGIBLE filter under the same ladder keeps the
            # legacy loose-bbox rewrite (shedding lever preserved)
            req2 = svc._request("count",
                                Query("apx", "name = 'a'"),
                                allow_degraded=True)
            svc._degrade(req2, 1)
            assert req2.sketch_rung == 0
            assert req2.degraded and req2.query.hints.loose_bbox
        finally:
            svc.start()
            svc.close(drain=True)


# -- approximate density subscriptions ---------------------------------------

class TestApproxDensitySubscribe:
    def test_frames_bound_and_zero_dispatches(self):
        from geomesa_tpu.kafka.store import KafkaDataStore
        from geomesa_tpu.subscribe import (
            DensityWindow, SubscriptionManager)

        sft = SimpleFeatureType.from_spec("alive", SFT_SPEC)
        kstore = KafkaDataStore()
        kstore.create_schema(sft)
        mgr = SubscriptionManager(kstore)
        w = (-60.0, -30.0, 60.0, 30.0)
        sa = mgr.subscribe("alive", density=DensityWindow(
            w, 12, 6, tolerance=0.5))
        se = mgr.subscribe("alive", density=DensityWindow(w, 12, 6))
        assert sa.mode == "approx_density" and se.mode == "density"
        fids = [f"f{i}" for i in range(40)]
        frames = []
        for i in range(6):
            rng = np.random.default_rng(300 + i)
            n = 24 + 2 * i
            kstore.write("alive", FeatureBatch.from_pydict(sft, {
                "name": rng.choice(["a", "b"], n).tolist(),
                "score": rng.uniform(-1, 1, n),
                "dtg": rng.integers(T0, T1, n),
                "geom": np.stack([rng.uniform(-55, 55, n),
                                  rng.uniform(-25, 25, n)], 1),
            }, fids=fids[:n]))
            kstore.poll("alive")
        mgr.flush(frames.append)
        af = [f for f in frames if f.get("event") == "approx_density"]
        assert af, "approx_density frames must flow"
        for f in af:
            assert f["approx"] is True and f["confidence"] == 1.0
            assert "bound" in f and "within_tolerance" in f
        # per-cell parity against the exact incremental grid
        assert np.abs(sa.grid - se.grid).max() <= af[-1]["bound"] + 1e-9

        # an approx-ONLY manager folds with ZERO device dispatches —
        # the thousand-subscriber fan-out stops paying per-poll device
        # work
        kstore2 = KafkaDataStore()
        kstore2.create_schema(sft)
        mgr2 = SubscriptionManager(kstore2)
        for j in range(3):
            mgr2.subscribe("alive", density=DensityWindow(
                w, 8, 4, tolerance=1.0))
        for i in range(4):
            rng = np.random.default_rng(900 + i)
            kstore2.write("alive", FeatureBatch.from_pydict(sft, {
                "name": ["a"] * 16,
                "score": rng.uniform(-1, 1, 16),
                "dtg": rng.integers(T0, T1, 16),
                "geom": np.stack([rng.uniform(-50, 50, 16),
                                  rng.uniform(-20, 20, 16)], 1),
            }, fids=fids[:16]))
            kstore2.poll("alive")
        assert mgr2.evaluator.stats()["dispatches"] == 0
        frames2 = []
        mgr2.flush(frames2.append)
        assert sum(1 for f in frames2
                   if f.get("event") == "approx_density") >= 3
        mgr.close()
        mgr2.close()

    def test_approx_window_rejects_weight_and_decay(self):
        from geomesa_tpu.subscribe import DensityWindow

        with pytest.raises(ValueError):
            DensityWindow((-1.0, -1.0, 1.0, 1.0), 4, 4, tolerance=0.1,
                          weight_attr="score")
        with pytest.raises(ValueError):
            DensityWindow((-1.0, -1.0, 1.0, 1.0), 4, 4, tolerance=0.1,
                          decay=0.5)


class TestDistinct:
    """DISTINCT counts (QueryHints.distinct, docs/SERVING.md
    "Approximate answers"): a tolerance hint resolves at admission from
    per-partition HyperLogLog sketches merged under the manifest
    snapshot with a typed [lo, hi] bound; without one (or with a
    predicate — the HLL path is Include-only) the answer pays an exact
    feature scan + host unique count."""

    @pytest.fixture(scope="class")
    def dstore(self, tmp_path_factory):
        from geomesa_tpu.plan.datastore import DataStore

        sft = SimpleFeatureType.from_spec("dst", SFT_SPEC)
        ds = DataStore(str(tmp_path_factory.mktemp("distinct")),
                       use_device_cache=True)
        src = ds.create_schema(sft)
        rng = np.random.default_rng(3)
        n = 4096
        names = [f"u{int(v)}" for v in rng.integers(0, 1500, n)]
        src.write(FeatureBatch.from_pydict(sft, {
            "name": names,
            "score": rng.uniform(-10, 10, n),
            "dtg": rng.integers(T0, T1, n),
            "geom": np.stack([rng.uniform(-170, 170, n),
                              rng.uniform(-80, 80, n)], 1),
        }))
        return ds, len(set(names))

    def test_hll_resolve_within_bound(self, dstore):
        ds, truth = dstore
        planner = ds.get_feature_source("dst").planner
        res = planner.count_result(Query(
            "dst", "INCLUDE",
            hints=QueryHints(distinct="name", tolerance=0.1)))
        assert res.approx and res.bound > 0
        assert res.confidence == pytest.approx(0.99)
        assert abs(res.count - truth) <= res.bound, (
            f"HLL estimate {res.count} +/- {res.bound} missed exact "
            f"{truth}")
        # memoized under the manifest version: bit-identical repeat
        again = planner.count_result(Query(
            "dst", "INCLUDE",
            hints=QueryHints(distinct="name", tolerance=0.1)))
        assert (again.count, again.bound) == (res.count, res.bound)

    def test_exact_without_tolerance(self, dstore):
        ds, truth = dstore
        planner = ds.get_feature_source("dst").planner
        res = planner.count_result(Query(
            "dst", "INCLUDE", hints=QueryHints(distinct="name")))
        assert not getattr(res, "approx", False)
        assert res.count == truth

    def test_predicate_routes_exact(self, dstore):
        ds, _truth = dstore
        src = ds.get_feature_source("dst")
        cql = "BBOX(geom, -60, -30, 60, 30)"
        feats = src.get_features(Query("dst", cql)).features
        want = len(set(np.asarray(
            feats.columns["name"].decode(), dtype=object)))
        # tolerance offered, but the HLL tier is Include-only: the
        # filtered distinct must come back exact, never estimated
        res = src.planner.count_result(Query(
            "dst", cql, hints=QueryHints(distinct="name",
                                         tolerance=0.1)))
        assert not getattr(res, "approx", False)
        assert res.count == want

    def test_validation_is_typed(self, dstore):
        ds, _truth = dstore
        planner = ds.get_feature_source("dst").planner
        with pytest.raises(ValueError, match="not in schema"):
            planner.count_result(Query(
                "dst", "INCLUDE", hints=QueryHints(distinct="nosuch")))
        with pytest.raises(ValueError, match="geometry"):
            planner.count_result(Query(
                "dst", "INCLUDE", hints=QueryHints(distinct="geom")))

    def test_wire_carries_lo_hi(self, dstore):
        import json as _json

        from geomesa_tpu.serve.protocol import serve_lines

        ds, truth = dstore
        out = []

        def lines():
            yield _json.dumps({"id": "d1", "op": "count",
                               "typeName": "dst", "cql": "INCLUDE",
                               "distinct": "name", "tolerance": 0.1})
            yield _json.dumps({"id": "d2", "op": "count",
                               "typeName": "dst", "cql": "INCLUDE",
                               "distinct": "name"})

        serve_lines(ds, lines(), out.append, ServeConfig(pipeline=False))
        by_id = {d["id"]: d for d in map(_json.loads, out)}
        d1 = by_id["d1"]
        assert d1["ok"] and d1["approx"]
        # the typed bound rides the wire as a [lo, hi] interval that
        # must contain the exact answer
        assert d1["lo"] <= truth <= d1["hi"]
        assert d1["lo"] == max(0, d1["count"] - d1["bound"])
        assert d1["hi"] == d1["count"] + d1["bound"]
        d2 = by_id["d2"]
        assert d2["ok"] and d2["count"] == truth
        assert not d2.get("approx") and "lo" not in d2
