#!/usr/bin/env python
"""CI gate: `gmtpu lint --fail-on warn` over geomesa_tpu/.

Runs EVERY registered rule — the JAX hazards GT01..GT06 and the
concurrency pass GT07..GT12 (lock discipline, lock-order cycles,
blocking-under-lock, per-call locks, callback-under-lock, unguarded
shared state) — and exits nonzero on any unwaived finding, printing
each with file:line and rule code. Rides the tier-1 pytest run via
tests/test_lint_gate.py and is runnable standalone:

    python scripts/lint_gate.py [--format json|sarif]

Rule catalog + waiver syntax: docs/ANALYSIS.md.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:  # standalone invocation from anywhere
    sys.path.insert(0, REPO_ROOT)


def main(argv=None) -> int:
    from geomesa_tpu.analysis.linter import (
        exit_code, lint_paths, render_json, render_sarif, render_text)

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--format", default="text",
                   choices=["text", "json", "sarif"])
    args = p.parse_args(argv)
    findings = lint_paths([os.path.join(REPO_ROOT, "geomesa_tpu")])
    if args.format == "json":
        print(render_json(findings))
    elif args.format == "sarif":
        print(render_sarif(findings))
    else:
        print(render_text(findings))
    return exit_code(findings, "warn")


if __name__ == "__main__":
    sys.exit(main())
