#!/usr/bin/env python
"""CI gate: `gmtpu lint --fail-on warn` over geomesa_tpu/ + warmup smoke.

Runs EVERY registered rule — the JAX hazards GT01..GT06, the concurrency
pass GT07..GT12 (lock discipline, lock-order cycles, blocking-under-lock,
per-call locks, callback-under-lock, unguarded shared state), the
serving-hot-path rule GT13 and the robustness rule GT14 (swallowed
errors / unbounded retry loops at the store/kafka/serve boundaries),
the interprocedural SPMD pass GT24..GT27 (unbound collective axes,
process-divergent control flow, sharding-spec drift, ungated process-
local side effects — docs/ANALYSIS.md "Reading an SPMD report"), and
the provenance dataflow pass GT28..GT31 (raw shapes reaching hot-path
dispatches, f32→f64 exactness laundering, unmatchable registry keys,
device→host→device bounces — docs/ANALYSIS.md "Reading a provenance
report") — and exits nonzero on any unwaived finding, printing each
with file:line and rule code. The lint itself runs through the incremental engine
(analysis/incremental.py): warm runs on an unchanged tree replay the
content-hash cache in well under a second, with findings byte-identical
to a cold scan. In text mode a clean lint is
followed by the smokes: the spmd smoke (lint a known-dirty miniature
repo fixture, require all four SPMD rules to fire and the gate verdict
to go nonzero — the pass itself stays honest), the warmup smoke
(`gmtpu warmup --check`
semantics against the committed fixture manifest on CPU, proving the
manifest record→replay→check loop stays green), the chaos smoke
(`gmtpu chaos --check` semantics replaying scripts/chaos_smoke_plan.json
against a tiny serve workload, proving the fault-injection + recovery
fabric invariants — docs/ROBUSTNESS.md), the telemetry smoke (a
traced serve workload whose /metrics scrape must parse and whose
dispatch-gap report must be non-empty — docs/OBSERVABILITY.md), and
the sentinel smoke (record a perf baseline, replay it to an `ok`
verdict, then prove a synthetic 3x phase slowdown exits nonzero —
docs/OBSERVABILITY.md "Sentinel"), and the lane smoke (the vmapped-lane
vs fused-slot standing-query comparison at S=256 with membership churn:
>=10x events/s floor, identical event totals, lane dispatches/poll <=4
— docs/SERVING.md "Standing queries"). Rides the tier-1 pytest run via
tests/test_lint_gate.py and is runnable standalone:

    python scripts/lint_gate.py [--format json|sarif]
        [--no-spmd-smoke] [--no-dataflow-smoke] [--no-warmup-smoke]
        [--no-chaos-smoke] [--no-telemetry-smoke] [--no-sentinel-smoke]
        [--no-fleet-smoke] [--no-rehome-smoke] [--no-approx-smoke]
        [--no-wire-smoke] [--no-ring-smoke] [--no-lane-smoke]

Rule catalog + waiver syntax: docs/ANALYSIS.md.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:  # standalone invocation from anywhere
    sys.path.insert(0, REPO_ROOT)

SMOKE_MANIFEST = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "warmup_smoke_manifest.json")
CHAOS_PLAN = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "chaos_smoke_plan.json")


def _pin_cpu() -> None:
    """Pin jax to CPU for the smokes (shared with warmup_smoke; the env
    var alone does not stick — the axon site pins jax_platforms at
    register time). Idempotent."""
    os.environ.setdefault("XLA_FLAGS", "")
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    from jax._src import xla_bridge as xb

    xb._backend_factories.pop("axon", None)
    jax.config.update("jax_platforms", "cpu")


def chaos_smoke(plan_path: str = CHAOS_PLAN) -> int:
    """`gmtpu chaos --check` semantics against the committed smoke plan
    on CPU: faults injected at every registered site class, the serve
    workload survives with typed errors only, breakers cycle visibly,
    and a seeded replay reproduces the exact fire log. Stderr-only like
    the warmup smoke — stdout stays machine-parseable."""
    _pin_cpu()
    from geomesa_tpu.faults.chaos import run_chaos
    from geomesa_tpu.faults.plan import FaultPlan

    report = run_chaos(FaultPlan.load(plan_path), requests=32,
                       replay=True, out=sys.stderr)
    print(
        f"chaos smoke: {report.ok}/{report.requests} ok, "
        f"{sum(report.typed_errors.values())} typed error(s), "
        f"{report.fires} fault(s) fired at "
        f"{len(report.fired_sites)} site(s), replay_match="
        f"{report.replay_match}, noop={report.noop_us_per_call}us",
        file=sys.stderr)
    for f in report.invariant_failures:
        print(f"chaos smoke: FAIL {f}", file=sys.stderr)
    return 0 if report.ok_overall else 1


def telemetry_smoke() -> int:
    """Serve a tiny traced workload, then prove the observability layer
    end to end: the /metrics scrape parses as Prometheus text (and
    carries the serving + breaker families), and the dispatch-gap
    report over the recorded traces is non-empty with sane coverage.
    Stderr-only like the other smokes — stdout stays machine-parseable
    for the lint formats."""
    _pin_cpu()
    import json
    import re
    import tempfile
    import urllib.request

    import numpy as np

    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan.datastore import DataStore
    from geomesa_tpu.serve.service import QueryService, ServeConfig
    from geomesa_tpu.telemetry import (
        RECORDER, TRACER, MetricsServer, gap_report)

    failures = []
    RECORDER.clear()
    TRACER.enable()
    try:
        rng = np.random.default_rng(5)
        n = 256
        sft = SimpleFeatureType.from_spec(
            "telesmoke", "name:String,dtg:Date,*geom:Point")
        with tempfile.TemporaryDirectory() as tmp:
            store = DataStore(tmp, use_device_cache=True)
            src = store.create_schema(sft)
            src.write(FeatureBatch.from_pydict(sft, {
                "name": rng.choice(["a", "b"], n).tolist(),
                "dtg": rng.integers(
                    1_590_000_000_000, 1_600_000_000_000, n),
                "geom": np.stack([rng.uniform(-170, 170, n),
                                  rng.uniform(-80, 80, n)], 1),
            }))
            cql = "BBOX(geom, -180, -90, 180, 90)"
            svc = QueryService(store, ServeConfig(max_wait_ms=20.0),
                               autostart=False)
            qp = rng.uniform(-60, 60, (6, 2))
            futs = [svc.knn("telesmoke", cql, qp[i:i + 1, 0],
                            qp[i:i + 1, 1], k=4) for i in range(6)]
            futs += [svc.count("telesmoke", cql) for _ in range(2)]
            svc.start()
            for f in futs:
                f.result(timeout=180)
            # drain BEFORE scraping: futures resolve inside the dispatch
            # window, but traces land in the recorder slightly later in
            # the completion loop — close() joins the dispatch thread,
            # so the scrape and the in-process report see the same set
            svc.close(drain=True)
            server = MetricsServer(port=0, stats_fn=svc.stats,
                                   pre_scrape=svc.export_gauges)
            port = server.start()
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics",
                        timeout=10) as r:
                    body = r.read().decode()
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/debug/gap",
                        timeout=10) as r:
                    http_gap = json.loads(r.read().decode())
            finally:
                server.stop()
    finally:
        TRACER.disable()
    # the scrape must PARSE: every non-comment line is
    # `name[{labels}] <float>`
    sample = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [^ ]+$')
    bad = [ln for ln in body.splitlines()
           if ln and not ln.startswith("#") and not sample.match(ln)]
    if bad:
        failures.append(f"unparseable /metrics line(s): {bad[:3]}")
    for needle in ("serve_latency_seconds_bucket", "serve_queue_depth",
                   "fault_breaker_", "fault_quarantine_active"):
        if needle not in body:
            failures.append(f"/metrics missing {needle}")
    rep = gap_report(RECORDER.traces())
    if not rep["phases"] or rep["dispatch_gap"]["windows"] < 1:
        failures.append(f"gap report empty: {rep}")
    elif rep["coverage"] < 0.90:
        failures.append(
            f"gap coverage {rep['coverage']} < 0.90 (un-instrumented "
            f"serve seam?)")
    if http_gap.get("traces") != rep["traces"]:
        failures.append("/debug/gap disagrees with in-process report")
    print(
        f"telemetry smoke: {rep['traces']} trace(s), coverage "
        f"{rep['coverage']}, {rep['dispatch_gap']['windows']} dispatch "
        f"window(s), /metrics {len(body.splitlines())} line(s)",
        file=sys.stderr)
    for f in failures:
        print(f"telemetry smoke: FAIL {f}", file=sys.stderr)
    return 1 if failures else 0


def sentinel_smoke() -> int:
    """The perf-regression sentinel loop, self-relative (docs/
    OBSERVABILITY.md "Sentinel"): record a baseline from a tiny traced
    serve workload, replay the identical workload, and require the
    comparison to verdict `ok` (no false regression on CI jitter);
    then inject a synthetic 3x slowdown into one phase's samples and
    require `regressed` with a nonzero exit code (a real slowdown
    cannot slip through). Self-relative on purpose — wall-clock
    baselines do not transfer across CI hosts, so the property CI can
    assert anywhere is exactly record -> replay -> verdict. Stderr-only
    like the other smokes."""
    _pin_cpu()
    import tempfile

    import numpy as np

    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan.datastore import DataStore
    from geomesa_tpu.serve.service import QueryService, ServeConfig
    from geomesa_tpu.telemetry import RECORDER, TRACER, sentinel
    from geomesa_tpu.telemetry.prof import PROFILER

    failures = []
    rng = np.random.default_rng(9)
    n = 256
    sft = SimpleFeatureType.from_spec(
        "sentsmoke", "name:String,dtg:Date,*geom:Point")

    def workload(store):
        # SEQUENTIAL requests on purpose: each one is its own dispatch
        # window, so every per-phase reservoir collects >= min_n
        # samples and the comparison verdicts instead of answering
        # insufficient-data (a single coalesced window would fold one
        # sample per phase). result_cache=0 + 8 exact counts keep the
        # plan/residency/filter.mask families sampled past min_n now
        # that ring-served kNN windows pay them only at arm time
        # (docs/SERVING.md "Persistent serve loop")
        svc = QueryService(store, ServeConfig(max_wait_ms=1.0,
                                              result_cache=0))
        qp = rng.uniform(-60, 60, (10, 2))
        cql = "BBOX(geom, -180, -90, 180, 90)"
        for i in range(10):
            svc.knn("sentsmoke", cql, qp[i:i + 1, 0],
                    qp[i:i + 1, 1], k=4).result(timeout=180)
        for _ in range(8):
            svc.count("sentsmoke", cql).result(timeout=180)
        svc.close(drain=True)

    TRACER.enable()
    try:
        with tempfile.TemporaryDirectory() as tmp:
            store = DataStore(tmp, use_device_cache=True)
            src = store.create_schema(sft)
            src.write(FeatureBatch.from_pydict(sft, {
                "name": rng.choice(["a", "b"], n).tolist(),
                "dtg": rng.integers(
                    1_590_000_000_000, 1_600_000_000_000, n),
                "geom": np.stack([rng.uniform(-170, 170, n),
                                  rng.uniform(-80, 80, n)], 1),
            }))
            workload(store)  # warm pass: compiles stay out of both
            RECORDER.clear()
            PROFILER.reset()
            PROFILER.enable()
            workload(store)
            base = sentinel.baseline_from_profile(
                PROFILER.snapshot(include_samples=True))
            # round-trip through disk exactly like the real workflow
            # (bench-serve --record-baseline -> gmtpu sentinel)
            with tempfile.NamedTemporaryFile(
                    "w", suffix=".json", delete=False) as tf:
                base_path = tf.name
            sentinel.save_baseline(base_path, base)
            base = sentinel.load_baseline(base_path)
            os.unlink(base_path)
            PROFILER.reset()
            workload(store)
            current = sentinel.baseline_from_profile(
                PROFILER.snapshot(include_samples=True))
    finally:
        PROFILER.disable()
        TRACER.disable()
    replay = sentinel.compare(base, current)
    if replay["regressed"] or sentinel.exit_code(replay) != 0:
        failures.append(
            f"identical replay verdicted regressed: "
            f"{[k for k, v in replay['metrics'].items() if v['verdict'] == 'regressed']}")
    if sentinel.exit_code(replay, strict=True) != 0:
        # the identical replay must COMPARE every baseline metric: an
        # insufficient-data verdict here means a phase/kernel family
        # stopped being instrumented (or the workload stopped sampling
        # it), which would silently un-guard that metric in every
        # future sentinel run
        failures.append(
            f"identical replay left metrics uncompared: "
            f"{[k for k, v in replay['metrics'].items() if v['verdict'] == 'insufficient-data']}")
    # synthetic regression: one phase 3x slower, everything else as
    # measured — the sentinel must flag exactly a regression and the
    # exit code must go nonzero
    slowed = {k: dict(v) for k, v in current["metrics"].items()}
    victim = ("phase.dispatch" if "phase.dispatch" in slowed
              else next(iter(slowed)))
    slowed[victim] = {
        "n": current["metrics"][victim]["n"],
        "median_ms": current["metrics"][victim]["median_ms"] * 3.0,
        "samples_ms": [v * 3.0 for v in
                       current["metrics"][victim]["samples_ms"]],
    }
    tripped = sentinel.compare(base, {"metrics": slowed})
    if not tripped["regressed"] or sentinel.exit_code(tripped) == 0:
        failures.append(
            f"synthetic 3x slowdown on {victim} not flagged: "
            f"{tripped['metrics'].get(victim)}")
    elif tripped["metrics"][victim]["verdict"] != "regressed":
        failures.append(
            f"victim verdict {tripped['metrics'][victim]['verdict']}, "
            f"expected regressed")
    print(
        f"sentinel smoke: replay {replay['counts']}, synthetic-3x on "
        f"{victim} -> {tripped['metrics'].get(victim, {}).get('verdict')}"
        f" (exit {sentinel.exit_code(tripped)})", file=sys.stderr)
    for f in failures:
        print(f"sentinel smoke: FAIL {f}", file=sys.stderr)
    return 1 if failures else 0


def fleet_smoke() -> int:
    """A 2-replica thread fleet on CPU over a tiny store, one scripted
    abrupt replica kill mid-burst (docs/ROBUSTNESS.md "Replica
    fleets"): every request must come back as a result or a typed
    retryable error — zero un-typed, zero dropped, zero duplicate
    responses — and the router's gauges must stay consistent with the
    answers the client actually saw (routed >= answered requests,
    retried reflected in membership). Stderr-only like the other
    smokes."""
    _pin_cpu()
    import json
    import tempfile
    import threading
    import time

    import numpy as np

    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.fleet import FleetConfig, FleetSupervisor
    from geomesa_tpu.fleet.wire import connect_json
    from geomesa_tpu.plan.datastore import DataStore

    failures = []
    rng = np.random.default_rng(7)
    n = 384
    burst = 16
    sft = SimpleFeatureType.from_spec(
        "fleetsmoke", "name:String,score:Double,dtg:Date,*geom:Point")
    with tempfile.TemporaryDirectory() as tmp:
        ds = DataStore(tmp, use_device_cache=True)
        ds.create_schema(sft).write(FeatureBatch.from_pydict(sft, {
            "name": rng.choice(["a", "b"], n).tolist(),
            "score": rng.uniform(-10, 10, n),
            "dtg": rng.integers(
                1_590_000_000_000, 1_600_000_000_000, n),
            "geom": np.stack([rng.uniform(-170, 170, n),
                              rng.uniform(-80, 80, n)], 1),
        }))
        del ds
        sup = FleetSupervisor(FleetConfig(
            n_replicas=2, catalog=tmp, probe_interval_s=0.2))
        try:
            port = sup.start()
            conn = connect_json("127.0.0.1", port)
            # warm both replica planners outside the measured burst
            conn.request({"id": "w", "op": "count",
                          "typeName": "fleetsmoke", "cql": "INCLUDE"},
                         timeout_s=300.0)
            qp = rng.uniform(-60, 60, (burst, 2))
            for i in range(burst):
                conn.send({"id": f"q{i}", "op": "knn",
                           "typeName": "fleetsmoke", "cql": "INCLUDE",
                           "x": [float(qp[i, 0])],
                           "y": [float(qp[i, 1])], "k": 4,
                           "timeoutMs": 60_000})
            sup.kill_replica("r0", graceful=False)
            answers = {}
            stop = threading.Event()
            timer = threading.Timer(120.0, stop.set)
            timer.start()
            for got in conn.docs(stop):
                rid = got.get("id")
                if rid in answers:
                    failures.append(f"duplicate response for {rid}")
                answers[rid] = got
                if len(answers) >= burst:
                    break
            timer.cancel()
            conn.close()
            if len(answers) != burst:
                failures.append(
                    f"{burst} requests, {len(answers)} answers: "
                    f"requests dropped during failover")
            untyped = [r for r in answers.values()
                       if not r.get("ok")
                       and r.get("error") not in ("unavailable",
                                                  "rejected",
                                                  "timeout")]
            if untyped:
                failures.append(f"un-typed client error(s): "
                                f"{untyped[:3]}")
            snap = sup.stats()
            routed_total = sum(r["routed"] for r in snap["replicas"])
            if routed_total < len(answers):
                failures.append(
                    f"router gauges inconsistent: routed_total="
                    f"{routed_total} < answered={len(answers)}")
            retried_onto = sum(r["retried_onto"]
                               for r in snap["replicas"])
            if snap["router"]["retried"] != retried_onto:
                failures.append(
                    f"router gauges inconsistent: retried="
                    f"{snap['router']['retried']} but membership "
                    f"says {retried_onto}")
            states = {r["replica"]: r["state"]
                      for r in snap["replicas"]}
            if states.get("r0") != "dead" or states.get("r1") != "ready":
                failures.append(f"post-kill states wrong: {states}")
            ok_n = sum(1 for r in answers.values() if r.get("ok"))
            print(
                f"fleet smoke: {len(answers)}/{burst} answered "
                f"({ok_n} ok), retried={snap['router']['retried']}, "
                f"states={states}", file=sys.stderr)
        finally:
            sup.close()
    for f in failures:
        print(f"fleet smoke: FAIL {f}", file=sys.stderr)
    return 1 if failures else 0


def rehome_smoke() -> int:
    """Fleet-native standing queries (docs/ROBUSTNESS.md "Standing
    queries"): a geofence subscription placed THROUGH the router over
    a shared Kafka live layer must survive an abrupt owner-replica
    kill with zero client choreography — the router re-homes it onto
    the survivor, the client's seq stays strictly monotonic, the frame
    stream replays to the exact matched set with at most ONE state
    resync, and the rehome counters account for the move. Stderr-only
    like the other smokes."""
    _pin_cpu()
    import time

    import numpy as np

    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.fleet import FleetConfig, FleetSupervisor
    from geomesa_tpu.fleet.router import FleetClient
    from geomesa_tpu.kafka.store import KafkaDataStore

    failures = []
    rng = np.random.default_rng(29)
    n = 24
    sft = SimpleFeatureType.from_spec(
        "rehomesmoke", "name:String,score:Double,dtg:Date,*geom:Point")
    fence = (-20.0, -15.0, 25.0, 20.0)
    cql = f"BBOX(geom, {fence[0]}, {fence[1]}, {fence[2]}, {fence[3]})"
    fids = [f"v{i}" for i in range(n)]

    def batch():
        return FeatureBatch.from_pydict(sft, {
            "name": rng.choice(["a", "b"], n).tolist(),
            "score": rng.uniform(-5, 5, n),
            "dtg": rng.integers(
                1_590_000_000_000, 1_600_000_000_000, n),
            "geom": np.stack([rng.uniform(-60, 60, n),
                              rng.uniform(-30, 30, n)], 1),
        }, fids=list(fids))

    def inside(b):
        g = b.columns[sft.default_geometry.name]
        x, y = np.asarray(g.x), np.asarray(g.y)
        keep = ((x >= fence[0]) & (x <= fence[2])
                & (y >= fence[1]) & (y <= fence[3]))
        return {f for f, k in zip(b.fids.decode(), keep) if k}

    store = KafkaDataStore()
    src = store.create_schema(sft)
    sup = FleetSupervisor(FleetConfig(
        n_replicas=2, store_factory=lambda: store,
        probe_interval_s=0.1))
    frames = []
    oracle = None
    try:
        port = sup.start()
        cli = FleetClient("127.0.0.1", port, timeout_s=30.0)
        got = cli.request({"op": "subscribe",
                           "typeName": "rehomesmoke", "cql": cql},
                          on_push=frames.append)
        if not got.get("ok"):
            failures.append(f"routed subscribe refused: {got}")
            raise SystemExit
        sid, owner = got["subscription"], got["replica"]
        for k in range(3):
            b = batch()
            oracle = inside(b)
            src.write(b)
            if k == 1:
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    row = sup.membership.sub_owner(sid)
                    if row is not None and row.checkpoint is not None:
                        break
                    time.sleep(0.02)
                sup.kill_replica(owner, graceful=False)
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline:
                    row = sup.membership.sub_owner(sid)
                    if row is not None and row.replica_id != owner:
                        break
                    time.sleep(0.02)
                row = sup.membership.sub_owner(sid)
                if row is None or row.replica_id == owner:
                    failures.append("subscription not re-homed after "
                                    "the owner kill")
                    raise SystemExit
            got = cli.request({"op": "poll"}, on_push=frames.append)
            if not got.get("ok"):
                failures.append(f"poll {k} failed: {got}")
        cli.close()
        evs = [f for f in frames if f.get("subscription") == sid]
        seqs = [f.get("seq") for f in evs]
        if seqs != sorted(seqs) or len(set(seqs)) != len(seqs):
            failures.append(f"client seq not monotonic: {seqs}")
        resyncs = sum(1 for f in evs[1:] if f.get("event") == "state")
        if resyncs != 1:
            failures.append(f"expected exactly one resync, saw "
                            f"{resyncs}")
        state = set()
        for f in evs:
            ev = f.get("event")
            if ev == "state":
                state = set(f["fids"])
            elif ev == "enter":
                if set(f["fids"]) & state:
                    failures.append("duplicate enter transition")
                state |= set(f["fids"])
            elif ev == "exit":
                if set(f["fids"]) - state:
                    failures.append("phantom exit transition")
                state -= set(f["fids"])
        if oracle is not None and state != oracle:
            failures.append(
                f"replayed matched set diverged from oracle "
                f"(missed={sorted(oracle - state)}, "
                f"extra={sorted(state - oracle)})")
        st = sup.stats()["router"]
        if st["rehome_succeeded"] != 1:
            failures.append(
                f"rehome counters wrong: {st}")
        print(f"rehome smoke: {len(evs)} frames, 1 resync, "
              f"rehomed={st['rehome_succeeded']}", file=sys.stderr)
    except SystemExit:
        pass
    finally:
        sup.close()
    for f in failures:
        print(f"rehome smoke: FAIL {f}", file=sys.stderr)
    return 1 if failures else 0


def approx_smoke() -> int:
    """The approximate-answer tier loop (docs/SERVING.md "Approximate
    answers"): a tolerant count workload over a tiny store must serve
    from SKETCHES with every reported bound containing the exact
    replayed answer, and a repeated exact query must hit the
    version-exact result cache with a bit-identical result on the
    second pass. Stderr-only like the other smokes."""
    _pin_cpu()
    import tempfile

    import numpy as np

    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan.datastore import DataStore
    from geomesa_tpu.plan.hints import QueryHints
    from geomesa_tpu.plan.query import Query
    from geomesa_tpu.serve.scheduler import ServeRequest
    from geomesa_tpu.serve.service import QueryService, ServeConfig

    failures = []
    rng = np.random.default_rng(17)
    n = 2048
    sft = SimpleFeatureType.from_spec(
        "approxsmoke", "name:String,dtg:Date,*geom:Point")
    cqls = ["BBOX(geom, -180, -90, 180, 90)",
            "BBOX(geom, -60, -30, 60, 30)"]
    with tempfile.TemporaryDirectory() as tmp:
        store = DataStore(tmp, use_device_cache=True)
        src = store.create_schema(sft)
        src.write(FeatureBatch.from_pydict(sft, {
            "name": rng.choice(["a", "b"], n).tolist(),
            "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
            "geom": np.stack([rng.uniform(-170, 170, n),
                              rng.uniform(-80, 80, n)], 1),
        }))
        svc = QueryService(store, ServeConfig(max_wait_ms=1.0))
        try:
            served = 0
            for cql in cqls:
                req = ServeRequest(kind="count", query=Query(
                    "approxsmoke", cql,
                    hints=QueryHints(tolerance=0.2)))
                got = svc.submit(req).result(timeout=300)
                exact = svc.count("approxsmoke", cql).result(timeout=300)
                if not getattr(got, "approx", False):
                    failures.append(
                        f"tolerant count {cql!r} not sketch-served")
                    continue
                served += 1
                if abs(int(got) - int(exact)) > got.bound:
                    failures.append(
                        f"bound violated for {cql!r}: approx {int(got)} "
                        f"+/- {got.bound} vs exact replay {int(exact)}")
            # second pass: the exact queries above populated the cache
            for cql in cqls:
                svc.count("approxsmoke", cql).result(timeout=300)
            cache = svc.stats().get("cache", {})
            if cache.get("hits", 0) < len(cqls):
                failures.append(
                    f"repeated exact queries did not hit the result "
                    f"cache: {cache}")
            tiers = svc.stats()["approx"]["tiers"]
        finally:
            svc.close(drain=True)
    print(f"approx smoke: {served} sketch-served (tiers {tiers}), "
          f"cache {cache.get('hits', 0)}h/{cache.get('misses', 0)}m",
          file=sys.stderr)
    for f in failures:
        print(f"approx smoke: FAIL {f}", file=sys.stderr)
    return 1 if failures else 0


def wire_smoke() -> int:
    """The columnar-wire loop (docs/SERVING.md "Columnar wire"): a
    negotiated columnar session over an in-process stream must answer
    bulk execute/density responses as binary frames whose DECODED
    payloads are bit-identical to a JSON-lines replay of the same
    queries, and a PushMux fan-out to 64 in-process subscribers must
    serialize each frame exactly once (encode-call counter asserted).
    Stderr-only like the other smokes."""
    _pin_cpu()
    import json
    import tempfile

    import numpy as np

    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan.datastore import DataStore
    from geomesa_tpu.serve import columnar as colwire
    from geomesa_tpu.serve.protocol import serve_connection
    from geomesa_tpu.serve.service import QueryService, ServeConfig

    failures = []
    if not colwire.have_pyarrow():
        # typed skip, same stance as the wire itself: json-only
        # environments downgrade, they do not fail
        print("wire smoke: pyarrow unavailable — columnar capability "
              "off, smoke skipped typed", file=sys.stderr)
        return 0
    rng = np.random.default_rng(13)
    n = 1024
    sft = SimpleFeatureType.from_spec(
        "wiresmoke", "name:String,score:Double,dtg:Date,*geom:Point")
    dens = {"bbox": [-180, -90, 180, 90], "width": 64, "height": 32}
    with tempfile.TemporaryDirectory() as tmp:
        store = DataStore(tmp, use_device_cache=True)
        store.create_schema(sft).write(FeatureBatch.from_pydict(sft, {
            "name": rng.choice(["a", "b", "c"], n).tolist(),
            "score": rng.uniform(-10, 10, n),
            "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
            "geom": np.stack([rng.uniform(-170, 170, n),
                              rng.uniform(-80, 80, n)], 1),
        }))
        svc = QueryService(store, ServeConfig(max_wait_ms=1.0))
        mem = colwire.MemoryWire()
        mem.add({"id": "h", "op": "hello", "wire": "columnar"})
        mem.add({"id": "qc", "op": "query", "typeName": "wiresmoke",
                 "cql": "INCLUDE", "maxFeatures": n})
        mem.add({"id": "qj", "op": "query", "typeName": "wiresmoke",
                 "cql": "INCLUDE", "maxFeatures": n, "wire": "json"})
        mem.add({"id": "dc", "op": "query", "typeName": "wiresmoke",
                 "cql": "INCLUDE", "density": dens})
        mem.add({"id": "dj", "op": "query", "typeName": "wiresmoke",
                 "cql": "INCLUDE", "density": dens, "wire": "json"})
        out = bytearray()
        try:
            serve_connection(store, svc, mem.lines(),
                             lambda s: out.extend(s.encode()),
                             write_bytes=out.extend,
                             read_bytes=mem.read_exact)
            # push fan-out: 64 in-process subscribers, one encode per
            # frame (the mux's own counter is the assertion)
            mux = svc.wire_mux()
            got = [0] * 64
            sinks = []
            for i in range(64):
                def make(i=i):
                    def w(buf: bytes) -> None:
                        got[i] += len(buf)
                    return w
                sinks.append(mux.register(make(), mode="json",
                                          threaded=False))
            frames = 10
            for k in range(frames):
                mux.publish({"event": "enter", "subscription": "s",
                             "seq": k + 1, "fids": ["a", "b"]}, sinks)
            st = mux.stats()
            if st["encodes"] != frames:
                failures.append(
                    f"fan-out encoded {st['encodes']}x for {frames} "
                    f"frames at 64 sinks (want one encode per frame)")
            if len(set(got)) != 1 or got[0] == 0:
                failures.append(f"sinks saw unequal bytes: {set(got)}")
        finally:
            svc.close(drain=True)
    resp = {d.get("id"): (d, p)
            for d, p in colwire.parse_stream(bytes(out))}
    hello = resp["h"][0]
    if hello.get("wireMode") != "columnar" \
            or "columnar" not in hello.get("wire", ()):
        failures.append(f"hello did not negotiate columnar: {hello}")
    qc, qp = resp["qc"]
    qj = resp["qj"][0]
    if qp is None or qj.get("features") is None:
        failures.append("execute responses missing frame/features")
    elif colwire.decode_execute_payload(qp) != qj["features"]:
        failures.append("columnar execute decode != JSON replay")
    dc, dp = resp["dc"]
    dj = resp["dj"][0]
    if dp is None:
        failures.append("density response missing frame")
    else:
        grid = colwire.decode_density_payload(dc["frame"], dp)
        if (dc["shape"] != dj["shape"] or dc["total"] != dj["total"]
                or float(grid.sum()) != dj["total"]):
            failures.append(
                f"columnar density decode != JSON replay: "
                f"{dc['shape']}/{dc['total']} vs "
                f"{dj['shape']}/{dj['total']}")
    print(
        f"wire smoke: {len(resp)} response(s), execute parity over "
        f"{qc.get('count')} rows, density {dc.get('shape')}, fan-out "
        f"64 sinks x {frames} frames -> {st['encodes']} encode(s)",
        file=sys.stderr)
    for f in failures:
        print(f"wire smoke: FAIL {f}", file=sys.stderr)
    return 1 if failures else 0


def ring_smoke() -> int:
    """The persistent serve loop end to end (docs/SERVING.md
    "Persistent serve loop"): a small sequential kNN workload through
    the ring path must (a) serve every window past warmup over ONE
    armed ring program, (b) answer bit-identical to a serial-path
    replay of the same queries, and (c) measure dispatches_per_window
    strictly below an identical ring-off (pipelined) run — the
    structural form of the dispatch-amortization claim CPU CI can
    assert. Stderr-only like the other smokes."""
    _pin_cpu()
    import tempfile

    import numpy as np

    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan.datastore import DataStore
    from geomesa_tpu.serve.loadgen import device_ops_count
    from geomesa_tpu.serve.service import QueryService, ServeConfig

    failures = []
    rng = np.random.default_rng(23)
    n = 512
    windows = 18
    sft = SimpleFeatureType.from_spec(
        "ringsmoke", "name:String,dtg:Date,*geom:Point")
    cql = "BBOX(geom, -180, -90, 180, 90)"
    with tempfile.TemporaryDirectory() as tmp:
        store = DataStore(tmp, use_device_cache=True)
        src = store.create_schema(sft)
        src.write(FeatureBatch.from_pydict(sft, {
            "name": rng.choice(["a", "b"], n).tolist(),
            "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
            "geom": np.stack([rng.uniform(-170, 170, n),
                              rng.uniform(-80, 80, n)], 1),
        }))
        pts = rng.uniform(-60, 60, (windows, 2))
        planner = store.get_feature_source("ringsmoke").planner
        from geomesa_tpu.plan.query import Query

        serial = [planner.knn(Query("ringsmoke", cql), pts[i:i + 1, 0],
                              pts[i:i + 1, 1], k=4)
                  for i in range(windows)]

        def run(cfg):
            svc = QueryService(store, cfg)
            try:
                # warm pass: arm/compile outside the measured loop
                for i in range(2):
                    svc.knn("ringsmoke", cql, pts[i:i + 1, 0],
                            pts[i:i + 1, 1], k=4).result(timeout=300)
                o0 = device_ops_count()
                out = []
                for i in range(windows):
                    out.append(svc.knn(
                        "ringsmoke", cql, pts[i:i + 1, 0],
                        pts[i:i + 1, 1], k=4).result(timeout=300))
                per_window = (device_ops_count() - o0) / windows
                return out, per_window, svc.stats()["pipeline"]
            finally:
                svc.close(drain=True)

        ring_res, ring_pw, ring_stats = run(ServeConfig(max_wait_ms=1.0))
        pipe_res, pipe_pw, _ = run(
            ServeConfig(max_wait_ms=1.0, ring=False))
    for i, ((d, ix, _b), (sd, six, _sb)) in enumerate(
            zip(ring_res, serial)):
        if not (np.array_equal(d, sd) and np.array_equal(ix, six)):
            failures.append(f"ring window {i} != serial replay")
            break
    for i, ((d, ix, _b), (pd, pix, _pb)) in enumerate(
            zip(ring_res, pipe_res)):
        if not (np.array_equal(d, pd) and np.array_equal(ix, pix)):
            failures.append(f"ring window {i} != pipelined replay")
            break
    ring = ring_stats.get("ring") or {}
    if ring.get("windows", 0) < windows:
        failures.append(
            f"only {ring.get('windows')} of {windows} windows rode "
            f"the ring (fallbacks: {ring.get('fallbacks')})")
    if not ring_pw < pipe_pw:
        failures.append(
            f"dispatches_per_window not below the pipelined baseline: "
            f"ring {ring_pw} vs pipelined {pipe_pw}")
    print(
        f"ring smoke: {ring.get('windows')}/{windows} ring window(s) "
        f"over {ring.get('armed')} armed program(s), "
        f"dispatches/window ring={ring_pw:.2f} vs "
        f"pipelined={pipe_pw:.2f}", file=sys.stderr)
    for f in failures:
        print(f"ring smoke: FAIL {f}", file=sys.stderr)
    return 1 if failures else 0


def lane_smoke() -> int:
    """The vmapped-lane loop (docs/SERVING.md "Standing queries"): the
    lane-vs-fused-slot comparison at S=256 same-class bbox geofences
    with one membership-churn event in both measured windows — the
    lane leg must clear the >=10x events/s floor (the fused leg pays
    an S-proportional trace+compile on the first poll and a full
    rebuild on churn; the lane leg one batched kernel + a parameter-
    row write), lane dispatches-per-poll must stay <=4 (one geofence
    class => one batched dispatch per poll), and both legs must push
    the IDENTICAL event total (the speedup is not bought with dropped
    events). S=256 keeps the fused leg near ~20 s; the S=1024 floor
    itself rides tier-1 via tests/test_subscribe.py. Stderr-only like
    the other smokes."""
    _pin_cpu()
    import numpy as np

    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.kafka.store import KafkaDataStore
    from geomesa_tpu.serve.loadgen import run_subscribe_lanes

    failures = []
    sft = SimpleFeatureType.from_spec(
        "lanesmoke", "name:String,score:Double,dtg:Date,*geom:Point")
    n = 256

    def make_store():
        store = KafkaDataStore()
        store.create_schema(sft)
        return store

    def make_batch(i: int) -> FeatureBatch:
        rng = np.random.default_rng(997 * i + 13)
        return FeatureBatch.from_pydict(sft, {
            "name": rng.choice(["a", "b", "c"], n).tolist(),
            "score": rng.uniform(-10, 10, n),
            "dtg": rng.integers(
                1_590_000_000_000, 1_600_000_000_000, n),
            "geom": np.stack([rng.uniform(-60, 60, n),
                              rng.uniform(-30, 30, n)], 1),
        }, fids=[f"v{j}" for j in range(n)])

    rep = run_subscribe_lanes(make_store, "lanesmoke", make_batch,
                              subscriptions=256, batches=2)
    lanes, fused = rep["lanes"], rep["fused"]
    if lanes["events_total"] != fused["events_total"]:
        failures.append(
            f"event totals diverge: lanes {lanes['events_total']} vs "
            f"fused {fused['events_total']}")
    if rep.get("speedup", 0.0) < 10.0:
        failures.append(
            f"lane events/s floor missed: {rep.get('speedup')}x < 10x "
            f"(lanes {lanes['events_per_s']}/s vs fused "
            f"{fused['events_per_s']}/s)")
    if lanes["dispatches_per_poll"] > 4.0:
        failures.append(
            f"lane dispatches-per-poll {lanes['dispatches_per_poll']} "
            f"> 4 for one geofence class")
    if lanes["lane_dispatches"] < lanes["polls"]:
        failures.append(
            f"lane path not exercised: {lanes['lane_dispatches']} lane "
            f"dispatch(es) over {lanes['polls']} poll(s)")
    print(
        f"lane smoke: S=256 speedup {rep.get('speedup')}x "
        f"(lanes first_poll {lanes['first_poll_s']}s churn "
        f"{lanes.get('churn_poll_s')}s vs fused {fused['first_poll_s']}s"
        f"/{fused.get('churn_poll_s')}s), "
        f"{lanes['events_total']} event(s) both legs, lane "
        f"dispatches/poll {lanes['dispatches_per_poll']}",
        file=sys.stderr)
    for f in failures:
        print(f"lane smoke: FAIL {f}", file=sys.stderr)
    return 1 if failures else 0


def spmd_smoke() -> int:
    """Prove the SPMD pass still bites: lint a known-dirty fixture — a
    miniature repo skeleton (pyproject.toml + geomesa_tpu/parallel/
    launch.py, so the multi-process reachability and path scoping are
    exercised for real) seeded with one true positive per rule — and
    require the gate verdict to go nonzero with ALL FOUR rules firing.
    Pure AST analysis: no jax import, runs in milliseconds. Guards
    against the pass silently going blind (a refactor that stops a rule
    matching would otherwise read as a cleaner tree)."""
    import tempfile
    import textwrap

    from geomesa_tpu.analysis.linter import exit_code, lint_paths

    dirty = textwrap.dedent('''\
        import os

        import jax
        import numpy as np
        from jax import lax
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


        def merge(x):
            return lax.psum(x, "shard")  # GT24: axis bound nowhere


        def kernel(a):
            return lax.psum(a, "data")


        def run():
            mesh = Mesh(np.array(jax.devices()), ("data",))
            spec = NamedSharding(mesh, P("ghost"))  # GT26: axis drift
            wrapped = shard_map(kernel, mesh=mesh,
                                in_specs=(P("data"), P("data")),
                                out_specs=P("data"))  # GT26: arity
            if jax.process_index() == 0:  # GT25: divergent programs
                jax.config.update("jax_enable_x64", True)
            return wrapped, spec


        def persist(path, doc):
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                fh.write(doc)
            os.replace(tmp, path)  # GT27: ungated persist
        ''')
    want = {"GT24", "GT25", "GT26", "GT27"}
    with tempfile.TemporaryDirectory() as tmp:
        with open(os.path.join(tmp, "pyproject.toml"), "w") as fh:
            fh.write("[project]\nname = \"spmd-smoke\"\n")
        pkg = os.path.join(tmp, "geomesa_tpu", "parallel")
        os.makedirs(pkg)
        with open(os.path.join(pkg, "launch.py"), "w") as fh:
            fh.write(dirty)
        findings = lint_paths([os.path.join(tmp, "geomesa_tpu")],
                              rules=sorted(want), extra_ref_paths=[])
        fired = {f.rule for f in findings if not f.waived}
        rc = exit_code(findings, "warn")
    missing = sorted(want - fired)
    print(f"spmd smoke: {len(findings)} finding(s) on the dirty "
          f"fixture, rules fired: {sorted(fired)}", file=sys.stderr)
    if rc == 0 or missing:
        print(f"spmd smoke: FAIL the dirty fixture must trip the gate "
              f"(exit {rc}, missing {missing})", file=sys.stderr)
        return 1
    return 0


def dataflow_smoke() -> int:
    """Prove the provenance dataflow pass still bites: lint a known-
    dirty serve-scope fixture seeded with one true positive per rule
    (GT28 raw shape into an AOT dispatch, GT29 f32→f64 laundering
    upcast, GT30 unmatchable registry key, GT31 device→host→device
    bounce) and require ALL FOUR to fire with a nonzero gate verdict;
    then lint the bucketed/registered/device-resident clean twin and
    require silence. The dirty SARIF render must carry the GT29
    provenance chain as relatedLocations — the report format the docs
    teach ("Reading a provenance report") is asserted here, not just
    rendered. Pure AST analysis: no jax import, runs in milliseconds."""
    import json
    import tempfile
    import textwrap

    from geomesa_tpu.analysis.linter import (
        exit_code, lint_paths, render_sarif)

    dirty = textwrap.dedent('''\
        import jax
        import numpy as np

        from geomesa_tpu.compilecache.registry import registry


        def handle(payload):
            qx = np.frombuffer(payload)           # raw wire extent
            handle_ = registry.compile("knn.score@serve", qx)  # GT28+GT30
            out = handle_.call(qx)
            host = jax.device_get(out)
            back = jax.device_put(host)           # GT31: bounce
            small = qx.astype(np.float32)
            exact = small.astype(np.float64)      # GT29: launder
            return back, exact
        ''')
    clean = textwrap.dedent('''\
        import numpy as np

        from geomesa_tpu.compilecache.registry import registry
        from geomesa_tpu.utils.padding import next_pow2


        def score(qx):
            return qx * 2.0


        registry.serve_variant("knn.score", fn=score)


        def pad_to(a, size):
            return np.concatenate([a, np.zeros(size - len(a))])


        def handle(payload):
            raw = np.frombuffer(payload)
            qx = pad_to(raw, next_pow2(max(len(raw), 1)))
            handle_ = registry.compile("knn.score@serve", qx)
            out = handle_.call(qx)
            exact = np.asarray(payload, np.float64)
            return out, exact
        ''')
    want = {"GT28", "GT29", "GT30", "GT31"}

    def run(src):
        with tempfile.TemporaryDirectory() as tmp:
            with open(os.path.join(tmp, "pyproject.toml"), "w") as fh:
                fh.write("[project]\nname = \"dataflow-smoke\"\n")
            pkg = os.path.join(tmp, "geomesa_tpu", "serve")
            os.makedirs(pkg)
            with open(os.path.join(pkg, "handler.py"), "w") as fh:
                fh.write(src)
            return lint_paths([os.path.join(tmp, "geomesa_tpu")],
                              rules=sorted(want), extra_ref_paths=[])

    findings = run(dirty)
    fired = {f.rule for f in findings if not f.waived}
    rc = exit_code(findings, "warn")
    sarif = json.loads(render_sarif(findings))
    chains = [r for r in sarif["runs"][0]["results"]
              if r["ruleId"] == "GT29" and r.get("relatedLocations")]
    leftover = [f.render() for f in run(clean) if not f.waived]
    missing = sorted(want - fired)
    print(f"dataflow smoke: {len(findings)} finding(s) on the dirty "
          f"fixture, rules fired: {sorted(fired)}, clean twin: "
          f"{len(leftover)} finding(s)", file=sys.stderr)
    if rc == 0 or missing:
        print(f"dataflow smoke: FAIL the dirty fixture must trip the "
              f"gate (exit {rc}, missing {missing})", file=sys.stderr)
        return 1
    if not chains:
        print("dataflow smoke: FAIL GT29 SARIF result carries no "
              "relatedLocations provenance chain", file=sys.stderr)
        return 1
    if leftover:
        print(f"dataflow smoke: FAIL clean twin not clean: {leftover}",
              file=sys.stderr)
        return 1
    return 0


def warmup_smoke(manifest_path: str = SMOKE_MANIFEST) -> int:
    """`gmtpu warmup --check` against the fixture manifest, pinned to
    CPU (the fixture records interpret-mode kernels; this gate must run
    on hardware-less CI). Output goes to stderr only — stdout stays
    machine-parseable for the lint formats. Returns 0 on pass."""
    # same backend pinning as bench.py --smoke; the "tpu" factory must
    # stay registered for pallas lowering imports
    _pin_cpu()

    from geomesa_tpu.compilecache.manifest import WarmupManifest
    from geomesa_tpu.compilecache.warmup import check

    report = check(WarmupManifest.load(manifest_path))
    for msg in report.errors:
        print(f"warmup smoke: {msg}", file=sys.stderr)
    print(
        f"warmup smoke: {report.kernels_compiled} compiled, "
        f"{report.kernels_cached} cached, {report.kernels_failed} failed, "
        f"residual recompiles {report.residual_recompiles}",
        file=sys.stderr)
    if report.queries_skipped:
        # same refusal as `gmtpu warmup --check` without a catalog: a
        # skipped query entry was never verified, so a green exit would
        # read as "serving compiles nothing" when the check proved
        # nothing about it — the smoke manifest must stay kernel-only
        print("warmup smoke: manifest contains query entries this "
              "store-less smoke cannot replay; FAIL", file=sys.stderr)
        return 1
    return 0 if report.ok else 1


def main(argv=None) -> int:
    from geomesa_tpu.analysis.incremental import lint_paths_incremental
    from geomesa_tpu.analysis.linter import (
        exit_code, render_json, render_sarif, render_text)

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--format", default="text",
                   choices=["text", "json", "sarif"])
    p.add_argument("--no-spmd-smoke", action="store_true",
                   help="skip the SPMD-pass smoke (known-dirty fixture "
                        "must fire GT24..GT27 and trip the gate; text "
                        "mode only)")
    p.add_argument("--no-dataflow-smoke", action="store_true",
                   help="skip the dataflow-pass smoke (known-dirty "
                        "serve fixture must fire GT28..GT31 with a "
                        "GT29 SARIF provenance chain, clean twin must "
                        "stay silent; text mode only)")
    p.add_argument("--no-warmup-smoke", action="store_true",
                   help="skip the warmup-manifest smoke (it runs only "
                        "in text mode; json/sarif stdout stays pure)")
    p.add_argument("--no-chaos-smoke", action="store_true",
                   help="skip the chaos-plan smoke (text mode only, "
                        "like the warmup smoke)")
    p.add_argument("--no-telemetry-smoke", action="store_true",
                   help="skip the telemetry smoke (traced serve "
                        "workload + /metrics parse + gap report; text "
                        "mode only)")
    p.add_argument("--no-sentinel-smoke", action="store_true",
                   help="skip the perf-regression sentinel smoke "
                        "(record -> replay -> ok; synthetic 3x "
                        "slowdown -> regressed; text mode only)")
    p.add_argument("--no-fleet-smoke", action="store_true",
                   help="skip the replica-fleet smoke (2-replica "
                        "fleet on CPU, one scripted kill, zero "
                        "un-typed errors + consistent router gauges; "
                        "text mode only)")
    p.add_argument("--no-rehome-smoke", action="store_true",
                   help="skip the subscription re-home smoke (a "
                        "routed geofence standing query across an "
                        "abrupt owner kill: zero missed/dup/phantom "
                        "transitions, one state resync, seq monotonic;"
                        " text mode only)")
    p.add_argument("--no-approx-smoke", action="store_true",
                   help="skip the approximate-answer smoke (sketch-"
                        "served tolerant counts with bounds verified "
                        "against exact replay + result-cache hit on "
                        "the second pass; text mode only)")
    p.add_argument("--no-wire-smoke", action="store_true",
                   help="skip the columnar-wire smoke (negotiated "
                        "columnar session with decoded parity vs a "
                        "JSON replay + one-encode push fan-out to 64 "
                        "in-process subscribers; text mode only)")
    p.add_argument("--no-ring-smoke", action="store_true",
                   help="skip the persistent-serve-loop smoke "
                        "(sequential kNN windows over one armed ring "
                        "program: bit-identity vs serial + "
                        "dispatches_per_window strictly below the "
                        "pipelined baseline; text mode only)")
    p.add_argument("--no-lane-smoke", action="store_true",
                   help="skip the vmapped-lane smoke (lane vs fused-"
                        "slot standing-query comparison at S=256 with "
                        "membership churn: >=10x events/s floor, "
                        "identical event totals, lane dispatches/poll "
                        "<=4; text mode only)")
    args = p.parse_args(argv)
    # incremental: a warm cache replays findings byte-identical to a
    # cold scan (asserted by tests/test_analysis_spmd.py), so repeated
    # gate runs — and the json/sarif renders CI takes after a green
    # text run — pay for one analysis, not one per invocation
    findings = lint_paths_incremental(
        [os.path.join(REPO_ROOT, "geomesa_tpu")])
    if args.format == "json":
        print(render_json(findings))
    elif args.format == "sarif":
        print(render_sarif(findings))
    else:
        print(render_text(findings))
    rc = exit_code(findings, "warn")
    if args.format == "text" and not args.no_spmd_smoke and rc == 0:
        rc = spmd_smoke()
    if args.format == "text" and not args.no_dataflow_smoke and rc == 0:
        rc = dataflow_smoke()
    if args.format == "text" and not args.no_warmup_smoke and rc == 0:
        rc = warmup_smoke()
    if args.format == "text" and not args.no_chaos_smoke and rc == 0:
        rc = chaos_smoke()
    if args.format == "text" and not args.no_telemetry_smoke and rc == 0:
        rc = telemetry_smoke()
    if args.format == "text" and not args.no_sentinel_smoke and rc == 0:
        rc = sentinel_smoke()
    if args.format == "text" and not args.no_fleet_smoke and rc == 0:
        rc = fleet_smoke()
    if args.format == "text" and not args.no_rehome_smoke and rc == 0:
        rc = rehome_smoke()
    if args.format == "text" and not args.no_approx_smoke and rc == 0:
        rc = approx_smoke()
    if args.format == "text" and not args.no_wire_smoke and rc == 0:
        rc = wire_smoke()
    if args.format == "text" and not args.no_ring_smoke and rc == 0:
        rc = ring_smoke()
    if args.format == "text" and not args.no_lane_smoke and rc == 0:
        rc = lane_smoke()
    return rc


if __name__ == "__main__":
    sys.exit(main())
