"""Multichip harness, serve-path phase (docs/SERVING.md "Sharded serving").

MULTICHIP_r01–r05 certified the RAW sharded kernels (`__graft_entry__.
dryrun_multichip`: psum density, all_gather kNN merges, ring top-k, …).
This phase certifies the SERVE PATH over the same mesh: a real
`QueryService` with mesh residency on, proving

  1. parity — kNN / count / density answers over the mesh are
     BIT-identical to the single-chip serve path on the same store;
  2. one-program dispatch — a coalesced kNN window executes as ONE
     sharded program (the `knn.mesh.dispatches` counter moves by one
     per window);
  3. throughput — `run_sustained` pts/s over the mesh vs the same serve
     stack single-chip (the ROADMAP item-1 capacity-multiplier number).

Emits MULTICHIP_r06.json (shape mirrors the r05 artifact: n_devices,
ok, tail) with the serve-phase numbers inlined.

CPU dry run (any host):

    python scripts/multichip_serve.py --devices 4 --n 2097152

TPU (run per host; see MULTIHOST_MANUAL.log for the DCN variant):

    python scripts/multichip_serve.py --devices 0 --n 33554432
    # --devices 0 = use every local accelerator, no CPU forcing
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build_store(root: str, n: int):
    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan import DataStore

    rng = np.random.default_rng(11)
    sft = SimpleFeatureType.from_spec(
        "bench", "name:String,score:Double,dtg:Date,*geom:Point")
    store = DataStore(root, use_device_cache=True)
    src = store.create_schema(sft)
    src.write(FeatureBatch.from_pydict(sft, {
        "name": rng.choice(["a", "b", "c"], n).tolist(),
        "score": rng.uniform(-10, 10, n),
        "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
        "geom": np.stack([rng.uniform(-170, 170, n),
                          rng.uniform(-80, 80, n)], 1),
    }))
    return store


def _counter(name: str) -> float:
    from geomesa_tpu.utils.metrics import metrics

    return json.loads(metrics.to_json())["counters"].get(name, 0.0)


def serve_phase(n_devices: int, n: int, duration_s: float) -> dict:
    """The serve-path certification over an n_devices mesh."""
    from geomesa_tpu.plan.hints import QueryHints
    from geomesa_tpu.serve.loadgen import knn_request_factory, run_sustained
    from geomesa_tpu.serve.service import QueryService, ServeConfig

    cql = "BBOX(geom, -170, -80, 170, 80) AND score > -5"
    rng = np.random.default_rng(42)
    qpts = rng.uniform(-60, 60, (8, 2))
    hints = QueryHints(density_bbox=(-170, -80, 170, 80),
                       density_width=64, density_height=64)
    out: dict = {"n_devices": n_devices, "points": n}

    with tempfile.TemporaryDirectory() as tmp:
        store = _build_store(tmp, n)

        def answers(mesh_spec):
            svc = QueryService(store, ServeConfig(
                mesh=mesh_spec, max_wait_ms=20.0), autostart=False)
            futs = [svc.knn("bench", cql, qpts[i:i + 1, 0],
                            qpts[i:i + 1, 1], k=10) for i in range(8)]
            svc.start()
            try:
                knn = [f.result(timeout=600) for f in futs]
                cnt = svc.count("bench", cql).result(timeout=600)
                dens = svc.query("bench", cql, hints=hints).result(
                    timeout=600)
            finally:
                svc.close(drain=True)
            return knn, cnt, np.asarray(dens.grid), svc.stats()

        base_mesh = _counter("knn.mesh.dispatches")
        mesh_ans = answers(n_devices)
        out["one_program_windows"] = int(
            _counter("knn.mesh.dispatches") - base_mesh)
        out["coalesced_dispatches"] = mesh_ans[3]["dispatches"]
        serial_ans = answers("off")

        knn_parity = all(
            np.array_equal(a[0], b[0]) and np.array_equal(a[1], b[1])
            for a, b in zip(mesh_ans[0], serial_ans[0]))
        out["knn_bit_identical"] = bool(knn_parity)
        out["count_equal"] = bool(mesh_ans[1] == serial_ans[1])
        out["density_bit_identical"] = bool(
            np.array_equal(mesh_ans[2], serial_ans[2]))

        def sustained(mesh_spec):
            svc = QueryService(store, ServeConfig(
                mesh=mesh_spec, max_wait_ms=2.0))
            try:
                rep = run_sustained(
                    svc, knn_request_factory("bench", cql, k=10),
                    duration_s=duration_s, max_outstanding=16,
                    points_per_query=n)
            finally:
                svc.close(drain=True)
            return rep

        sustained(n_devices)  # warm the measured route
        rep_m = sustained(n_devices)
        rep_s = sustained("off")
        out["mesh_pts_per_s"] = round(rep_m.pts_per_s, 1)
        out["per_shard_pts_per_s"] = round(rep_m.per_shard_pts_per_s, 1)
        out["single_chip_pts_per_s"] = round(rep_s.pts_per_s, 1)
        out["mesh_speedup"] = (
            round(rep_m.pts_per_s / rep_s.pts_per_s, 3)
            if rep_s.pts_per_s > 0 else None)
    out["ok"] = bool(
        knn_parity and out["count_equal"] and out["density_bit_identical"]
        and out["one_program_windows"] >= 1)
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--devices", type=int, default=4,
                    help="force an N-device CPU platform; 0 = use the "
                         "local accelerators as-is (TPU runs)")
    ap.add_argument("--n", type=int, default=1 << 21,
                    help="synthetic store size (points)")
    ap.add_argument("--duration", type=float, default=6.0,
                    help="sustained-phase measurement window (s)")
    ap.add_argument("--out", default="MULTICHIP_r06.json",
                    help="artifact path ('-' = stdout only)")
    args = ap.parse_args()

    if args.devices > 0:
        from __graft_entry__ import _force_cpu_devices

        _force_cpu_devices(args.devices)
    import jax

    n_devices = len(jax.devices()) if args.devices == 0 else args.devices
    t0 = time.perf_counter()
    phase = serve_phase(n_devices, args.n, args.duration)
    phase["wall_s"] = round(time.perf_counter() - t0, 2)
    tail = (
        f"serve_phase({n_devices}): n={args.n} "
        f"knn_bit_identical={phase['knn_bit_identical']} "
        f"count_equal={phase['count_equal']} "
        f"density_bit_identical={phase['density_bit_identical']} "
        f"one_program_windows={phase['one_program_windows']} "
        f"mesh={phase['mesh_pts_per_s']:.0f} pts/s "
        f"({phase['per_shard_pts_per_s']:.0f}/shard) "
        f"single_chip={phase['single_chip_pts_per_s']:.0f} pts/s "
        f"speedup={phase['mesh_speedup']}"
    )
    doc = {"n_devices": n_devices, "rc": 0 if phase["ok"] else 1,
           "ok": phase["ok"], "skipped": False, "phase": "serve",
           "serve": phase, "tail": tail + "\n"}
    print(tail)
    if args.out != "-":
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1)
        print(f"wrote {args.out}")
    return 0 if phase["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
