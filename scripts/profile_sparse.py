"""Sparse-scan kNN on Z-ordered (store-order) data vs dense fullscan."""

from __future__ import annotations

import time

import numpy as np
import jax
import jax.numpy as jnp

from geomesa_tpu.engine.knn_scan import knn_fullscan, knn_sparse_scan
from scripts._util import RTT, sync, timeit


def morton(x, y):
    qx = np.clip(((x + 180.0) / 360.0 * 65535.0), 0, 65535).astype(np.uint64)
    qy = np.clip(((y + 90.0) / 180.0 * 65535.0), 0, 65535).astype(np.uint64)

    def spread(v):
        v = (v | (v << 16)) & np.uint64(0x0000FFFF0000FFFF)
        v = (v | (v << 8)) & np.uint64(0x00FF00FF00FF00FF)
        v = (v | (v << 4)) & np.uint64(0x0F0F0F0F0F0F0F0F)
        v = (v | (v << 2)) & np.uint64(0x3333333333333333)
        v = (v | (v << 1)) & np.uint64(0x5555555555555555)
        return v

    return spread(qx) | (spread(qy) << np.uint64(1))


def main():
    n = 1 << 26
    q = 256
    k = 10
    rng = np.random.default_rng(42)
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    # store order: Z-sorted (the FS/KV store's physical layout)
    order = np.argsort(morton(x, y))
    x, y = x[order], y[order]
    t = rng.integers(1_590_000_000_000, 1_600_000_000_000, n)
    speed = rng.uniform(0, 30, n)
    qx = rng.uniform(-30, 30, q)
    qy = rng.uniform(30, 60, q)
    BBOX = (-60.0, 20.0, 60.0, 70.0)
    T0, T1 = 1_592_000_000_000, 1_598_000_000_000

    dx = jnp.asarray(x, jnp.float32)
    dy = jnp.asarray(y, jnp.float32)
    dt = jnp.asarray(t, jnp.int64)
    dspeed = jnp.asarray(speed, jnp.float32)
    dqx = jnp.asarray(qx, jnp.float32)
    dqy = jnp.asarray(qy, jnp.float32)
    sync(dspeed)

    mask_np = (
        (x >= BBOX[0]) & (x <= BBOX[2]) & (y >= BBOX[1]) & (y <= BBOX[3])
        & (t > T0) & (t < T1) & (speed > 5.0)
    )
    ntiles = n // 16384
    tiles_hit = (mask_np.reshape(ntiles, -1).any(1)).sum()
    print(f"count {mask_np.sum()}, tiles {tiles_hit}/{ntiles} hit "
          f"({100*tiles_hit/ntiles:.1f}%)", flush=True)
    cap = 1 << int(np.ceil(np.log2(tiles_hit * 1.25)))
    print(f"tile capacity {cap}", flush=True)

    def mk_mask(x, y, t, speed):
        return (
            (x >= BBOX[0]) & (x <= BBOX[2]) & (y >= BBOX[1]) & (y <= BBOX[3])
            & (t > T0) & (t < T1) & (speed > 5.0)
        )

    @jax.jit
    def fused_sparse(x, y, t, speed, qx, qy):
        m = mk_mask(x, y, t, speed)
        cnt = jnp.sum(m.astype(jnp.int32))
        fd, fi, ov = knn_sparse_scan(qx, qy, x, y, m, k=k, tile_capacity=cap)
        return cnt, fd, fi, ov

    @jax.jit
    def fused_dense(x, y, t, speed, qx, qy):
        m = mk_mask(x, y, t, speed)
        cnt = jnp.sum(m.astype(jnp.int32))
        fd, fi = knn_fullscan(qx, qy, x, y, m, k=k)
        return cnt, fd, fi

    print("compiling sparse...", flush=True)
    s = time.perf_counter()
    out = fused_sparse(dx, dy, dt, dspeed, dqx, dqy)
    sync(out[1])
    print(f"  {time.perf_counter()-s:.0f}s; overflow={bool(out[3])}",
          flush=True)
    t1 = timeit(lambda: sync(fused_sparse(dx, dy, dt, dspeed, dqx, dqy)[1]))
    print(f"sparse latency:  {t1*1e3:7.1f} ms (net {(t1-RTT)*1e3:5.0f}) "
          f"-> {n/t1/1e6:.0f}M pts/s", flush=True)

    R = 8

    def sustained():
        outs = [fused_sparse(dx, dy, dt, dspeed, dqx, dqy)[1]
                for _ in range(R)]
        for o in outs:
            sync(o)

    ts = timeit(sustained, repeats=3)
    print(f"sparse sustained x{R}: {ts*1e3:7.1f} ms -> "
          f"{R*n/ts/1e6:.0f}M pts/s", flush=True)

    print("compiling dense...", flush=True)
    s = time.perf_counter()
    out = fused_dense(dx, dy, dt, dspeed, dqx, dqy)
    sync(out[1])
    print(f"  {time.perf_counter()-s:.0f}s", flush=True)
    t2 = timeit(lambda: sync(fused_dense(dx, dy, dt, dspeed, dqx, dqy)[1]))
    print(f"dense latency:   {t2*1e3:7.1f} ms (net {(t2-RTT)*1e3:5.0f}) "
          f"-> {n/t2/1e6:.0f}M pts/s", flush=True)

    # recall parity vs numpy oracle
    from geomesa_tpu.engine.geodesy import haversine_m_np

    cnt, fd, fi, ov = fused_sparse(dx, dy, dt, dspeed, dqx, dqy)
    got = np.sort(np.asarray(fd), axis=1)
    cx_np, cy_np = x[mask_np], y[mask_np]
    bad = 0
    for i in range(q):
        d = haversine_m_np(qx[i], qy[i], cx_np, cy_np)
        exp = np.sort(d[np.argpartition(d, k - 1)[:k]])
        if not np.allclose(exp, got[i], rtol=1e-4, atol=1.0):
            bad += 1
    print(f"sparse recall parity: {q-bad}/{q} exact; count {int(cnt)} "
          f"vs np {mask_np.sum()}", flush=True)


if __name__ == "__main__":
    main()
