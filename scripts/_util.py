"""Shared timing helpers for the perf-lab scripts (real-chip runs)."""

from __future__ import annotations

import time

import numpy as np
import jax

# measured dispatch+sync floor through the remote-tunnel TPU (one HTTP
# round trip per dispatch; see BASELINE.md "tunnel" notes)
RTT = 0.108


def sync(out):
    """Force device completion: fetch one scalar (block_until_ready
    returns early under the remote-tunnel platform)."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(leaf[(0,) * leaf.ndim])
    return out


def timeit(fn, repeats=4):
    fn()  # warm / compile
    best = float("inf")
    for _ in range(repeats):
        s = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - s)
    return best
