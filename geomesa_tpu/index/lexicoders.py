"""Order-preserving byte encodings for index key components.

Parity: the reference's lexicoders used by attribute index keys
(geomesa-index-api index/attribute key encoding; upstream uses a ByteArrays/
Lexicoders scheme) [upstream, unverified]. Property required of every coder:
a < b  <=>  encode(a) < encode(b) bytewise.
"""

from __future__ import annotations

import struct
from typing import Optional

NULL_BYTE = b"\x00"
# Escaping for embedded NULs in strings: 0x00 -> 0x01 0x01, 0x01 -> 0x01 0x02.
# Keeps bytewise order for all strings not containing 0x00/0x01 prefixes and
# makes the 0x00 field separator unambiguous.
_ESC = b"\x01"


def encode_string(s: str) -> bytes:
    raw = s.encode("utf-8")
    if b"\x00" in raw or b"\x01" in raw:
        raw = raw.replace(_ESC, b"\x01\x02").replace(NULL_BYTE, b"\x01\x01")
    return raw


def decode_string(b: bytes) -> str:
    if _ESC in b:
        b = b.replace(b"\x01\x01", NULL_BYTE).replace(b"\x01\x02", _ESC)
    return b.decode("utf-8")


def encode_int(v: int) -> bytes:
    """Signed 64-bit, order-preserving: flip the sign bit, big-endian."""
    return struct.pack(">Q", (int(v) ^ (1 << 63)) & 0xFFFFFFFFFFFFFFFF)


def decode_int(b: bytes) -> int:
    (u,) = struct.unpack(">Q", b)
    return u - (1 << 63)


def encode_float(v: float) -> bytes:
    """IEEE-754 double, order-preserving.

    Non-negative (sign bit 0): set the sign bit. Negative: invert all bits.
    NaN sorts above everything (encoded via its IEEE pattern); callers treat
    NaN as null before encoding.
    """
    (bits,) = struct.unpack(">Q", struct.pack(">d", float(v)))
    if bits & (1 << 63):
        bits = ~bits & 0xFFFFFFFFFFFFFFFF
    else:
        bits |= 1 << 63
    return struct.pack(">Q", bits)


def decode_float(b: bytes) -> float:
    (bits,) = struct.unpack(">Q", b)
    if bits & (1 << 63):
        bits &= ~(1 << 63) & 0xFFFFFFFFFFFFFFFF
    else:
        bits = ~bits & 0xFFFFFFFFFFFFFFFF
    (v,) = struct.unpack(">d", struct.pack(">Q", bits))
    return v


def encode_value(v, type_name: str) -> Optional[bytes]:
    """Encode a typed attribute value; None/NaN -> None (not indexed)."""
    if v is None:
        return None
    if type_name in ("Integer", "Long", "Short", "Byte"):
        return encode_int(int(v))
    if type_name in ("Float", "Double"):
        f = float(v)
        if f != f:  # NaN
            return None
        return encode_float(f)
    if type_name in ("Date", "Timestamp"):
        return encode_int(int(v))  # epoch millis
    if type_name == "Boolean":
        return b"\x01" if v else b"\x00"
    return encode_string(str(v))


def successor(b: bytes) -> bytes:
    """The smallest byte string strictly greater than every string with
    prefix b: append 0x00 is wrong (b itself < b+0x00 but b+x may sort
    between); the correct exclusive upper bound for prefix scans is b with
    the last non-0xff byte incremented and the tail dropped."""
    arr = bytearray(b)
    for i in range(len(arr) - 1, -1, -1):
        if arr[i] != 0xFF:
            arr[i] += 1
            return bytes(arr[: i + 1])
        # byte is 0xff: drop it and carry
    # all-0xff prefix: no finite exact bound; a long 0xff tail bounds every
    # realistic key (suffixes here are feature ids far shorter than 64 bytes)
    return b + b"\xff" * 64
