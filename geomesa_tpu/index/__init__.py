"""Key-value index layer: keyspaces, adapter SPI, and its two backends.

Parity: geomesa-index-api's index catalog + IndexAdapter SPI + the
TestGeoMesaDataStore in-memory reference backend (SURVEY.md C7, C9-C11, §4)
[upstream, unverified]. This is the row-key architecture the reference runs
on Accumulo/HBase/Cassandra/Redis; here one sorted-KV adapter contract backs
all index types, with two implementations proving the SPI the way the
reference's backend plurality does: the in-memory adapter (the
TestGeoMesaDataStore analog) and the durable SQLite adapter + row store
(index/durable.py), whose data survives process restarts.
"""

from geomesa_tpu.index.adapter import IndexAdapter, MemoryIndexAdapter
from geomesa_tpu.index.durable import DurableKVDataStore, SqliteIndexAdapter
from geomesa_tpu.index.keyspace import (
    AttributeIndex,
    IdIndex,
    IndexKeySpace,
    S2Index,
    XZ2Index,
    XZ3Index,
    Z2Index,
    Z3Index,
    default_indices,
)
from geomesa_tpu.index.kvstore import KVDataStore, KVFeatureSource
from geomesa_tpu.index.splitter import FilterSplitter, StrategyDecider

__all__ = [
    "IndexAdapter",
    "MemoryIndexAdapter",
    "SqliteIndexAdapter",
    "DurableKVDataStore",
    "IndexKeySpace",
    "Z3Index",
    "Z2Index",
    "S2Index",
    "XZ2Index",
    "XZ3Index",
    "IdIndex",
    "AttributeIndex",
    "default_indices",
    "FilterSplitter",
    "StrategyDecider",
    "KVDataStore",
    "KVFeatureSource",
]
