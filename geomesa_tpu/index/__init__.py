"""Key-value index layer: keyspaces, adapter SPI, and the in-memory backend.

Parity: geomesa-index-api's index catalog + IndexAdapter SPI + the
TestGeoMesaDataStore in-memory reference backend (SURVEY.md C7, C9-C11, §4)
[upstream, unverified]. This is the row-key architecture the reference runs
on Accumulo/HBase/Cassandra/Redis; here one sorted-KV adapter contract backs
all index types, and the in-memory implementation doubles as the test oracle
backend exactly as upstream's TestGeoMesaDataStore does.
"""

from geomesa_tpu.index.adapter import IndexAdapter, MemoryIndexAdapter
from geomesa_tpu.index.keyspace import (
    AttributeIndex,
    IdIndex,
    IndexKeySpace,
    XZ2Index,
    XZ3Index,
    Z2Index,
    Z3Index,
    default_indices,
)
from geomesa_tpu.index.kvstore import KVDataStore, KVFeatureSource
from geomesa_tpu.index.splitter import FilterSplitter, StrategyDecider

__all__ = [
    "IndexAdapter",
    "MemoryIndexAdapter",
    "IndexKeySpace",
    "Z3Index",
    "Z2Index",
    "XZ2Index",
    "XZ3Index",
    "IdIndex",
    "AttributeIndex",
    "default_indices",
    "FilterSplitter",
    "StrategyDecider",
    "KVDataStore",
    "KVFeatureSource",
]
