"""Durable sorted-KV backend: SQLite-backed IndexAdapter + row store.

Parity: the reference's core promise is ONE index/scan contract over many
stores (SURVEY.md:95, C9-C11 — Accumulo/HBase/Cassandra/Redis all implement
the same IndexAdapter SPI); round 1 shipped exactly one in-memory adapter,
which proved nothing about the abstraction and survived no restart. This
module is the second, durable implementation: every index keyspace and the
whole KVFeatureSource stack run on it unmodified, and a reopened store
serves identical results.

Design: one SQLite file per feature type.
- `idx(name, key BLOB, row)` with PRIMARY KEY (name, key): SQLite compares
  BLOBs by memcmp, so B-tree range scans over `key >= lo AND key < hi` are
  exactly the lexicographic ByteRange contract the keyspaces encode for
  (lexicoders produce order-preserving bytes precisely so a dumb byte-sorted
  store can serve them — same reason the reference's rowkeys work on any
  ordered KV store).
- `batches(id, ipc BLOB, fids TEXT)`: the row store — each written
  FeatureBatch as Arrow IPC stream bytes (the framework's one serialization
  substrate; no second row codec, per the C3 columnar-replaces-Kryo design
  decision) plus its fid list.
- `dead(row)`: tombstones. `meta(k, v)`: sft spec, shard count, fid seq.

Every logical write (tombstones + row batch + index keys + fid seq) commits
as ONE SQLite transaction via `transaction()`, so a crash leaves either the
complete write or nothing — no index keys without rows, no replaced
features lost between tombstone and re-store, no stale fid sequence
(§5.3 failure-detection stance: idempotent writes, fail-fast recovery).

This is deliberately NOT the performance path — the FS/Parquet store and
the HBM-resident cache are (SURVEY.md C14). It is the durability +
SPI-plurality path, sized for catalog/live-layer workloads.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sqlite3
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from geomesa_tpu.index.adapter import IndexAdapter
from geomesa_tpu.index.keyspace import ByteRange, WriteKey


class SqliteIndexAdapter(IndexAdapter):
    """IndexAdapter over a SQLite file; also the durable row/meta store
    the KVFeatureSource persistence hooks use (store_batch/load_batches/
    mark_dead/load_dead/meta_get/meta_set)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._db = sqlite3.connect(path)
        self._txn_depth = 0
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        with self._db:
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS idx ("
                "name TEXT NOT NULL, key BLOB NOT NULL, row INTEGER NOT NULL,"
                "PRIMARY KEY (name, key))"
            )
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS batches ("
                "id INTEGER PRIMARY KEY AUTOINCREMENT, ipc BLOB NOT NULL,"
                "fids TEXT NOT NULL)"
            )
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS dead (row INTEGER PRIMARY KEY)"
            )
            self._db.execute(
                "CREATE TABLE IF NOT EXISTS meta (k TEXT PRIMARY KEY, v TEXT)"
            )

    def close(self) -> None:
        self._db.close()

    # -- transactions ------------------------------------------------------

    def _commit(self) -> None:
        if self._txn_depth == 0:
            self._db.commit()

    @contextlib.contextmanager
    def transaction(self):
        """Group mutations into one atomic commit (reentrant). The
        KVFeatureSource write/delete paths wrap their whole multi-table
        sequence (tombstones + row batch + index keys + seq) in this, so a
        crash leaves either the complete logical write or none of it —
        never index keys without rows, dead rows without replacements, or
        a stale fid sequence (round-2 review crash-consistency findings)."""
        self._txn_depth += 1
        try:
            yield
        except BaseException:
            self._txn_depth -= 1
            if self._txn_depth == 0:
                self._db.rollback()
            raise
        else:
            self._txn_depth -= 1
            if self._txn_depth == 0:
                self._db.commit()

    # -- IndexAdapter SPI --------------------------------------------------

    def create_index(self, index_name: str) -> None:
        # single-table layout: nothing to create per index; record the
        # name so size() on a never-written index returns 0, not a miss
        self._db.execute(
            "INSERT OR IGNORE INTO meta (k, v) VALUES (?, '')",
            (f"index:{index_name}",),
        )
        self._commit()

    def write(self, index_name: str, keys: Iterable[WriteKey]) -> None:
        self._db.executemany(
            "INSERT OR REPLACE INTO idx (name, key, row) VALUES (?, ?, ?)",
            ((index_name, wk.key, wk.row) for wk in keys),
        )
        self._commit()

    def delete(self, index_name: str, keys: Iterable[bytes]) -> None:
        self._db.executemany(
            "DELETE FROM idx WHERE name = ? AND key = ?",
            ((index_name, k) for k in keys),
        )
        self._commit()

    def scan(self, index_name: str, ranges: Sequence[ByteRange]) -> List[int]:
        seen = set()
        out: List[int] = []
        cur = self._db.cursor()
        for lo, hi in ranges:
            for (row,) in cur.execute(
                "SELECT row FROM idx WHERE name = ? AND key >= ? AND key < ?"
                " ORDER BY key",
                (index_name, lo, hi),
            ):
                if row not in seen:
                    seen.add(row)
                    out.append(row)
        return out

    def scan_count(self, index_name: str, ranges: Sequence[ByteRange]) -> int:
        cur = self._db.cursor()
        total = 0
        for lo, hi in ranges:
            total += cur.execute(
                "SELECT COUNT(*) FROM idx WHERE name = ? AND key >= ?"
                " AND key < ?",
                (index_name, lo, hi),
            ).fetchone()[0]
        return total

    def size(self, index_name: str) -> int:
        return self._db.execute(
            "SELECT COUNT(*) FROM idx WHERE name = ?", (index_name,)
        ).fetchone()[0]

    # -- durable row store (KVFeatureSource persistence hooks) -------------

    def store_batch(self, ipc: bytes, fids: Sequence[str]) -> None:
        self._db.execute(
            "INSERT INTO batches (ipc, fids) VALUES (?, ?)",
            (ipc, json.dumps(list(fids))),
        )
        self._commit()

    def load_batches(self) -> List[Tuple[bytes, List[str]]]:
        return [
            (ipc, json.loads(fids))
            for ipc, fids in self._db.execute(
                "SELECT ipc, fids FROM batches ORDER BY id"
            )
        ]

    def mark_dead(self, rows: Iterable[int]) -> None:
        self._db.executemany(
            "INSERT OR IGNORE INTO dead (row) VALUES (?)",
            ((int(r),) for r in rows),
        )
        self._commit()

    def load_dead(self) -> set:
        return {r for (r,) in self._db.execute("SELECT row FROM dead")}

    def meta_set(self, key: str, value: str) -> None:
        self._db.execute(
            "INSERT OR REPLACE INTO meta (k, v) VALUES (?, ?)",
            (key, str(value)),
        )
        self._commit()

    def meta_get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        row = self._db.execute(
            "SELECT v FROM meta WHERE k = ?", (key,)
        ).fetchone()
        return row[0] if row is not None else default


def batch_to_ipc(batch) -> bytes:
    from geomesa_tpu.core.arrow_io import to_ipc_bytes

    return to_ipc_bytes(batch)


def ipc_to_batch(ipc: bytes, sft):
    import pyarrow as pa

    from geomesa_tpu.core.arrow_io import from_arrow

    reader = pa.ipc.open_stream(io.BytesIO(ipc))
    batches = [from_arrow(rb, sft) for rb in reader]
    if len(batches) != 1:
        from geomesa_tpu.core.columnar import FeatureBatch

        return FeatureBatch.concat(batches)
    return batches[0]


class DurableKVDataStore:
    """A KVDataStore whose schemas and features survive process restarts:
    one SQLite file per feature type under `root`, reopened on
    construction (upstream analog: any GeoMesaDataStore pointed at an
    existing catalog table finds its schemas and data)."""

    def __init__(self, root: str, shards: int = 4):
        from geomesa_tpu.core.sft import SimpleFeatureType
        from geomesa_tpu.index.keyspace import default_indices
        from geomesa_tpu.index.kvstore import KVFeatureSource

        self.root = root
        self._shards = shards
        self._sources: Dict[str, "KVFeatureSource"] = {}
        os.makedirs(root, exist_ok=True)
        for fn in sorted(os.listdir(root)):
            if not fn.endswith(".db"):
                continue
            adapter = SqliteIndexAdapter(os.path.join(root, fn))
            name = adapter.meta_get("sft_name")
            spec = adapter.meta_get("sft_spec")
            if not name or spec is None:
                adapter.close()
                continue  # half-created file: unreadable schema, skip
            sft = SimpleFeatureType.from_spec(name, spec)
            sh = int(adapter.meta_get("shards", str(shards)))
            src = KVFeatureSource(sft, adapter, default_indices(sft, sh))
            self._sources[name] = src

    def create_schema(self, sft, indices=None) -> "KVFeatureSource":
        from geomesa_tpu.index.keyspace import default_indices
        from geomesa_tpu.index.kvstore import KVFeatureSource

        if sft.name in self._sources:
            raise ValueError(f"schema {sft.name!r} already exists")
        if indices is not None:
            raise ValueError(
                "DurableKVDataStore reopens schemas with default_indices; "
                "custom index sets are not persisted"
            )
        adapter = SqliteIndexAdapter(
            os.path.join(self.root, f"{sft.name}.db")
        )
        adapter.meta_set("sft_name", sft.name)
        adapter.meta_set("sft_spec", sft.to_spec())
        adapter.meta_set("shards", str(self._shards))
        src = KVFeatureSource(
            sft, adapter, default_indices(sft, self._shards)
        )
        self._sources[sft.name] = src
        return src

    def get_feature_source(self, name: str):
        return self._sources[name]

    def get_schema(self, name: str):
        return self._sources[name].sft

    def get_type_names(self) -> List[str]:
        return sorted(self._sources)

    def remove_schema(self, name: str) -> None:
        src = self._sources.pop(name)
        src.adapter.close()
        os.remove(os.path.join(self.root, f"{name}.db"))

    def close(self) -> None:
        for src in self._sources.values():
            src.adapter.close()
