"""Index keyspaces: row-key schemas + query-range generators.

Parity: geomesa-index-api's index catalog (SURVEY.md C7) [upstream,
unverified]:

  Z3  [shard][2B epoch bin][8B z3][fid]     points + time (the default)
  Z2  [shard][8B z2][fid]                   points, no time
  XZ3 [shard][2B epoch bin][8B xz3][fid]    extended geometries + time
  XZ2 [shard][8B xz2][fid]                  extended geometries
  ID  [fid]                                 primary-key lookup
  ATTR [2B attr idx][lexicoded value][0x00][8B z3-tier suffix][fid]

Shards are hash-mod write-spreading bytes (upstream ShardStrategy). Range
generation returns *covering* byte ranges — false positives are removed by
the residual compiled-predicate mask downstream, exactly the role of the
reference's Z3Iterator/server-side residual filter.
"""

from __future__ import annotations

import dataclasses
import struct
import zlib
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.core.columnar import DictColumn, FeatureBatch, GeometryColumn
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.cql import ast
from geomesa_tpu.cql.extract import BBox, Interval, extract_bbox, extract_intervals
from geomesa_tpu.curve.binned_time import TimePeriod
from geomesa_tpu.curve.xz import XZ2SFC, XZ3SFC
from geomesa_tpu.curve.z2 import Z2SFC
from geomesa_tpu.curve.z3 import Z3SFC
from geomesa_tpu.index import lexicoders as lx

# An inclusive-lower / exclusive-upper byte-key range.
ByteRange = Tuple[bytes, bytes]

UNBOUNDED_MILLIS = (-(1 << 50), 1 << 50)


def _shard_of(fid: str, shards: int) -> int:
    return zlib.crc32(fid.encode("utf-8")) % shards


@dataclasses.dataclass
class WriteKey:
    """One index entry for one feature."""

    key: bytes
    row: int  # storage row id


class IndexKeySpace:
    """SPI: key schema + range generation for one index type."""

    name: str = "?"

    def __init__(self, sft: SimpleFeatureType, shards: int = 4):
        self.sft = sft
        self.shards = max(1, shards)

    # -- writes ------------------------------------------------------------

    def write_keys(
        self, batch: FeatureBatch, fids: Sequence[str], rows: Sequence[int]
    ) -> List[WriteKey]:
        raise NotImplementedError

    # -- reads -------------------------------------------------------------

    def supports(self, f: ast.Filter) -> bool:
        """Can this index produce bounded ranges for the filter?"""
        raise NotImplementedError

    def ranges(self, f: ast.Filter, max_ranges: int = 512) -> List[ByteRange]:
        raise NotImplementedError

    # -- helpers -----------------------------------------------------------

    def _geom(self) -> str:
        g = self.sft.default_geometry
        if g is None:
            raise ValueError(f"{self.name}: schema has no geometry")
        return g.name

    def _dtg(self) -> str:
        d = self.sft.default_dtg
        if d is None:
            raise ValueError(f"{self.name}: schema has no dtg")
        return d.name

    def _shard_ranges(self, inner: Iterable[Tuple[bytes, bytes]]) -> List[ByteRange]:
        """Cross each inner (lo, hi_exclusive) with every shard prefix."""
        inner = list(inner)
        out = []
        for s in range(self.shards):
            p = bytes([s])
            for lo, hi in inner:
                out.append((p + lo, p + hi))
        return out


class Z3Index(IndexKeySpace):
    name = "z3"

    def __init__(
        self,
        sft: SimpleFeatureType,
        shards: int = 4,
        period: "str | TimePeriod" = TimePeriod.WEEK,
    ):
        super().__init__(sft, shards)
        self.sfc = Z3SFC(period)

    def write_keys(self, batch, fids, rows):
        g, d = self._geom(), self._dtg()
        col: GeometryColumn = batch.columns[g]
        dtg = np.asarray(batch.columns[d], np.int64)
        bins, zs = self.sfc.index(col.x, col.y, dtg)
        out = []
        for i in range(len(batch)):
            shard = _shard_of(fids[i], self.shards)
            key = (
                bytes([shard])
                + struct.pack(">H", int(bins[i]) & 0xFFFF)
                + struct.pack(">Q", int(zs[i]))
                + fids[i].encode("utf-8")
            )
            out.append(WriteKey(key, rows[i]))
        return out

    def supports(self, f):
        # z3/xz3 need a fully bounded time range (upstream: the z3 index
        # requires a during/between-style interval; open-ended predicates
        # fall back to the spatial-only index or a full scan)
        interval = extract_intervals(f, self._dtg())
        return (
            interval.start is not None
            and interval.end is not None
            and not interval.is_empty
        )

    def ranges(self, f, max_ranges=512):
        bbox = extract_bbox(f, self._geom())
        interval = extract_intervals(f, self._dtg())
        if bbox.is_empty or interval.is_empty:
            return []
        per_bin = self.sfc.ranges(
            bbox.xmin, bbox.ymin, bbox.xmax, bbox.ymax,
            int(interval.start), int(interval.end),
            max_ranges=max_ranges,
        )
        inner = []
        for b, rs in per_bin.items():
            prefix = struct.pack(">H", int(b) & 0xFFFF)
            for r in rs:
                inner.append(
                    (prefix + struct.pack(">Q", r.lower),
                     prefix + struct.pack(">Q", r.upper + 1))
                )
        return self._shard_ranges(inner)


class Z2Index(IndexKeySpace):
    name = "z2"

    def __init__(self, sft: SimpleFeatureType, shards: int = 4):
        super().__init__(sft, shards)
        self.sfc = Z2SFC()

    def write_keys(self, batch, fids, rows):
        col: GeometryColumn = batch.columns[self._geom()]
        zs = self.sfc.index(col.x, col.y)
        out = []
        for i in range(len(batch)):
            shard = _shard_of(fids[i], self.shards)
            key = (
                bytes([shard])
                + struct.pack(">Q", int(zs[i]))
                + fids[i].encode("utf-8")
            )
            out.append(WriteKey(key, rows[i]))
        return out

    def supports(self, f):
        bbox = extract_bbox(f, self._geom())
        return not bbox.is_whole_world and not bbox.is_empty

    def ranges(self, f, max_ranges=512):
        bbox = extract_bbox(f, self._geom())
        if bbox.is_empty:
            return []
        rs = self.sfc.ranges(
            bbox.xmin, bbox.ymin, bbox.xmax, bbox.ymax, max_ranges=max_ranges
        )
        inner = [
            (struct.pack(">Q", r.lower), struct.pack(">Q", r.upper + 1)) for r in rs
        ]
        return self._shard_ranges(inner)


class S2Index(IndexKeySpace):
    """S2-style cube-face keyspace (curve/s2.py): [shard][8B cellid][fid].

    Parity: the reference's S2 index variant (SURVEY.md:241-242 [L],
    geomesa s2 module over the sidx library) — deferred in rounds 1-2,
    built in round 3. Point geometries only (the reference's S2 index is
    likewise point-oriented; extended geometries keep XZ2/XZ3). Wins over
    Z2 for high-latitude workloads: cube faces bound cell-area distortion
    where Z2's lon/lat cells degenerate toward the poles."""

    name = "s2"

    def __init__(self, sft: SimpleFeatureType, shards: int = 4,
                 level: int = 15):
        super().__init__(sft, shards)
        from geomesa_tpu.curve.s2 import S2SFC

        self.sfc = S2SFC(level)

    def write_keys(self, batch, fids, rows):
        col: GeometryColumn = batch.columns[self._geom()]
        cells = self.sfc.index(col.x, col.y)
        out = []
        for i in range(len(batch)):
            shard = _shard_of(fids[i], self.shards)
            key = (
                bytes([shard])
                + struct.pack(">Q", int(cells[i]))
                + fids[i].encode("utf-8")
            )
            out.append(WriteKey(key, rows[i]))
        return out

    def supports(self, f):
        bbox = extract_bbox(f, self._geom())
        return not bbox.is_whole_world and not bbox.is_empty

    def ranges(self, f, max_ranges=512):
        bbox = extract_bbox(f, self._geom())
        if bbox.is_empty:
            return []
        rs = self.sfc.ranges(
            bbox.xmin, bbox.ymin, bbox.xmax, bbox.ymax, max_ranges=max_ranges
        )
        inner = [
            (struct.pack(">Q", r.lower), struct.pack(">Q", r.upper + 1))
            for r in rs
        ]
        return self._shard_ranges(inner)


class XZ2Index(IndexKeySpace):
    name = "xz2"

    def __init__(self, sft: SimpleFeatureType, shards: int = 4, g: int = 12):
        super().__init__(sft, shards)
        self.sfc = XZ2SFC(g)

    def write_keys(self, batch, fids, rows):
        col: GeometryColumn = batch.columns[self._geom()]
        bbox = (
            col.bbox
            if not col.is_point
            else np.stack([col.x, col.y, col.x, col.y], axis=1)
        )
        out = []
        for i in range(len(batch)):
            xz = self.sfc.index(*(float(v) for v in bbox[i]))
            shard = _shard_of(fids[i], self.shards)
            key = (
                bytes([shard]) + struct.pack(">Q", xz) + fids[i].encode("utf-8")
            )
            out.append(WriteKey(key, rows[i]))
        return out

    def supports(self, f):
        bbox = extract_bbox(f, self._geom())
        return not bbox.is_whole_world and not bbox.is_empty

    def ranges(self, f, max_ranges=512):
        bbox = extract_bbox(f, self._geom())
        if bbox.is_empty:
            return []
        rs = self.sfc.ranges(
            bbox.xmin, bbox.ymin, bbox.xmax, bbox.ymax, max_ranges=max_ranges
        )
        inner = [
            (struct.pack(">Q", r.lower), struct.pack(">Q", r.upper + 1)) for r in rs
        ]
        return self._shard_ranges(inner)


class XZ3Index(IndexKeySpace):
    name = "xz3"

    def __init__(
        self,
        sft: SimpleFeatureType,
        shards: int = 4,
        g: int = 12,
        period: "str | TimePeriod" = TimePeriod.WEEK,
    ):
        super().__init__(sft, shards)
        self.sfc = XZ3SFC(period, g)

    def write_keys(self, batch, fids, rows):
        col: GeometryColumn = batch.columns[self._geom()]
        dtg = np.asarray(batch.columns[self._dtg()], np.int64)
        bbox = (
            col.bbox
            if not col.is_point
            else np.stack([col.x, col.y, col.x, col.y], axis=1)
        )
        out = []
        for i in range(len(batch)):
            b, xz = self.sfc.index(
                float(bbox[i][0]), float(bbox[i][1]),
                float(bbox[i][2]), float(bbox[i][3]), int(dtg[i]),
            )
            shard = _shard_of(fids[i], self.shards)
            key = (
                bytes([shard])
                + struct.pack(">H", int(b) & 0xFFFF)
                + struct.pack(">Q", xz)
                + fids[i].encode("utf-8")
            )
            out.append(WriteKey(key, rows[i]))
        return out

    def supports(self, f):
        # z3/xz3 need a fully bounded time range (upstream: the z3 index
        # requires a during/between-style interval; open-ended predicates
        # fall back to the spatial-only index or a full scan)
        interval = extract_intervals(f, self._dtg())
        return (
            interval.start is not None
            and interval.end is not None
            and not interval.is_empty
        )

    def ranges(self, f, max_ranges=512):
        bbox = extract_bbox(f, self._geom())
        interval = extract_intervals(f, self._dtg())
        if bbox.is_empty or interval.is_empty:
            return []
        per_bin = self.sfc.ranges(
            bbox.xmin, bbox.ymin, bbox.xmax, bbox.ymax,
            int(interval.start), int(interval.end),
            max_ranges=max_ranges,
        )
        inner = []
        for b, rs in per_bin.items():
            prefix = struct.pack(">H", int(b) & 0xFFFF)
            for r in rs:
                inner.append(
                    (prefix + struct.pack(">Q", r.lower),
                     prefix + struct.pack(">Q", r.upper + 1))
                )
        return self._shard_ranges(inner)


class IdIndex(IndexKeySpace):
    name = "id"

    def write_keys(self, batch, fids, rows):
        return [
            WriteKey(fids[i].encode("utf-8"), rows[i]) for i in range(len(batch))
        ]

    def supports(self, f):
        return _id_literals(f) is not None

    def ranges(self, f, max_ranges=512):
        ids = _id_literals(f)
        if ids is None:
            return []
        out = []
        for fid in ids:
            raw = fid.encode("utf-8")
            out.append((raw, raw + b"\x00"))
        return sorted(out)


def _id_literals(f: ast.Filter) -> Optional[List[str]]:
    """IN ('id1','id2') / = on the reserved __fid__ property -> literal ids.

    Parity: GeoTools Id filters (upstream `IN ('…')` bare-ID CQL). The CQL
    grammar here spells it as a predicate on the pseudo-attribute __fid__.
    """
    if isinstance(f, ast.In) and f.prop.name == "__fid__" and not f.negate:
        return [str(v) for v in f.values]
    if (
        isinstance(f, ast.Comparison)
        and f.op == "="
        and isinstance(f.left, ast.Property)
        and f.left.name == "__fid__"
        and isinstance(f.right, ast.Literal)
    ):
        return [str(f.right.value)]
    if isinstance(f, ast.And):
        for part in f.children:
            ids = _id_literals(part)
            if ids is not None:
                return ids
    return None


class AttributeIndex(IndexKeySpace):
    """Secondary index on one attribute, with a z3-tier suffix.

    Key = [2B attr index][lexicoded value][0x00][2B bin][8B z3 | zeros][fid].
    The tier suffix lets an `attr = v AND bbox/time` query narrow within the
    equality run (upstream's tiered attribute index).
    """

    name = "attr"

    def __init__(self, sft: SimpleFeatureType, attr: str, shards: int = 1):
        super().__init__(sft, shards)
        self.attr = attr
        self.attr_idx = sft.index_of(attr)
        self.type = sft.attribute(attr).type
        self._z3: Optional[Z3SFC] = None
        if sft.default_geometry is not None and sft.default_dtg is not None:
            if sft.default_geometry.type == "Point":
                self._z3 = Z3SFC()

    @property
    def full_name(self) -> str:
        return f"attr:{self.attr}"

    def _prefix(self) -> bytes:
        return struct.pack(">H", self.attr_idx)

    def _tier(self, batch: FeatureBatch) -> List[bytes]:
        n = len(batch)
        if self._z3 is None:
            return [b"\x00" * 10] * n
        col: GeometryColumn = batch.columns[self.sft.default_geometry.name]
        dtg = np.asarray(batch.columns[self.sft.default_dtg.name], np.int64)
        bins, zs = self._z3.index(col.x, col.y, dtg)
        return [
            struct.pack(">H", int(bins[i]) & 0xFFFF) + struct.pack(">Q", int(zs[i]))
            for i in range(n)
        ]

    def write_keys(self, batch, fids, rows):
        col = batch.columns[self.attr]
        values = col.decode() if isinstance(col, DictColumn) else np.asarray(col)
        tiers = self._tier(batch)
        out = []
        for i in range(len(batch)):
            enc = lx.encode_value(values[i], self.type)
            if enc is None:
                continue  # nulls are not indexed (upstream behavior)
            key = (
                self._prefix() + enc + lx.NULL_BYTE + tiers[i]
                + fids[i].encode("utf-8")
            )
            out.append(WriteKey(key, rows[i]))
        return out

    def _bounds(self, f: ast.Filter) -> Optional[List[Tuple[Optional[bytes], Optional[bytes], bool, bool]]]:
        """Extract (lo, hi, lo_incl, hi_incl) lexicoded bounds on self.attr.

        Returns None if the filter doesn't constrain the attribute. OR of
        equalities (IN) yields multiple bounds; AND intersects by keeping
        the first constraining clause (covering is still correct since the
        residual mask re-checks everything).
        """
        if isinstance(f, ast.And):
            for part in f.children:
                b = self._bounds(part)
                if b is not None:
                    return b
            return None
        if isinstance(f, ast.Or):
            parts = [self._bounds(p) for p in f.children]
            if any(p is None for p in parts):
                return None  # one branch unconstrained -> index can't cover OR
            return [b for p in parts for b in p]
        if isinstance(f, ast.In) and f.prop.name == self.attr and not f.negate:
            out = []
            for v in f.values:
                enc = lx.encode_value(v, self.type)
                if enc is not None:
                    out.append((enc, enc, True, True))
            return out
        if isinstance(f, ast.Between) and f.prop.name == self.attr and not f.negate:
            lo = lx.encode_value(f.lo.value, self.type)
            hi = lx.encode_value(f.hi.value, self.type)
            return [(lo, hi, True, True)]
        if isinstance(f, ast.Like) and f.prop.name == self.attr \
                and not f.negate and not f.case_insensitive:
            # prefix LIKE 'abc%' -> range scan on the literal prefix. Only
            # when the prefix is wildcard-free: '_' (single-char) and '\'
            # (escape) are LIKE metacharacters, and encoding them as literal
            # bytes would produce a non-covering range that drops matches.
            pat = f.pattern
            head = pat.rstrip("%")
            if (
                pat.endswith("%")
                and head
                and not any(c in head for c in ("%", "_", "\\"))
            ):
                prefix = lx.encode_string(head)
                return [(prefix, lx.successor(prefix), True, False)]
            return None
        if isinstance(f, ast.Comparison) and isinstance(f.left, ast.Property) \
                and f.left.name == self.attr and isinstance(f.right, ast.Literal):
            enc = lx.encode_value(f.right.value, self.type)
            if enc is None:
                return None
            if f.op == "=":
                return [(enc, enc, True, True)]
            if f.op in ("<", "<="):
                return [(None, enc, True, f.op == "<=")]
            if f.op in (">", ">="):
                return [(enc, None, f.op == ">=", True)]
        return None

    def supports(self, f):
        return self._bounds(f) is not None

    def ranges(self, f, max_ranges=512):
        bounds = self._bounds(f)
        if bounds is None:
            return []
        p = self._prefix()
        out = []
        for lo, hi, lo_incl, hi_incl in bounds:
            if lo is None:
                lo_key = p
            else:
                lo_key = p + lo + (lx.NULL_BYTE if lo_incl else b"\x01")
                if not lo_incl:
                    # strictly greater: skip the whole equality run of lo
                    lo_key = p + lx.successor(lo + lx.NULL_BYTE)
            if hi is None:
                hi_key = lx.successor(p)
            elif hi_incl:
                hi_key = p + lx.successor(hi + lx.NULL_BYTE)
            else:
                hi_key = p + hi + lx.NULL_BYTE
            out.append((lo_key, hi_key))
        return sorted(out)


def default_indices(
    sft: SimpleFeatureType, shards: int = 4
) -> List[IndexKeySpace]:
    """The reference's default index set for a schema (upstream
    GeoMesaFeatureIndexFactory behavior): z3 (point+dtg) or xz3
    (extended+dtg), z2/xz2 spatial-only, id always, plus an attribute index
    for every attribute flagged index=true in the spec."""
    out: List[IndexKeySpace] = [IdIndex(sft, shards=1)]
    g = sft.default_geometry
    d = sft.default_dtg
    if g is not None and g.type == "Point":
        out.append(Z2Index(sft, shards))
        if d is not None:
            out.append(Z3Index(sft, shards))
    elif g is not None:
        out.append(XZ2Index(sft, shards))
        if d is not None:
            out.append(XZ3Index(sft, shards))
    for a in sft.attributes:
        # "full" vs "join" (upstream: join indices store reduced columns and
        # join back to the record table) collapse to one behavior here: index
        # entries are (key, row-pointer) pairs and feature values live only
        # in the columnar record store, so every attribute index already has
        # join semantics with zero value duplication
        if a.options.get("index", "").lower() in ("true", "full", "join"):
            out.append(AttributeIndex(sft, a.name))
    return out
