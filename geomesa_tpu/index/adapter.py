"""IndexAdapter SPI + the in-memory sorted-KV implementation.

Parity: geomesa-index-api's IndexAdapter (the contract a storage backend
implements: create tables, write mutations, scan key ranges) and the
in-memory TestGeoMesaDataStore backend (SURVEY.md C9-C11, §4) [upstream,
unverified]. The memory adapter is a real backend, not a test shim: sorted
key arrays + bisect scans are the moral equivalent of a single-tablet
Accumulo, and every index keyspace runs on it unmodified.
"""

from __future__ import annotations

import bisect
from typing import Dict, Iterable, List, Sequence, Set, Tuple

from geomesa_tpu.index.keyspace import ByteRange, WriteKey


class IndexAdapter:
    """SPI: what a KV backend must implement (upstream IndexAdapter)."""

    def create_index(self, index_name: str) -> None:
        raise NotImplementedError

    def write(self, index_name: str, keys: Iterable[WriteKey]) -> None:
        raise NotImplementedError

    def delete(self, index_name: str, keys: Iterable[bytes]) -> None:
        raise NotImplementedError

    def scan(self, index_name: str, ranges: Sequence[ByteRange]) -> List[int]:
        """Row ids whose keys fall in any [lo, hi) range (dedupe preserved
        to the caller — an id may appear once per matching key)."""
        raise NotImplementedError

    def scan_count(self, index_name: str, ranges: Sequence[ByteRange]) -> int:
        """Number of keys in the ranges — the cost-estimation primitive
        (upstream estimates via stat sketches; a sorted store can afford
        exact counts, which is strictly better costing)."""
        raise NotImplementedError

    def size(self, index_name: str) -> int:
        raise NotImplementedError


class MemoryIndexAdapter(IndexAdapter):
    """Sorted parallel arrays per index; scans are bisect slices."""

    def __init__(self):
        self._keys: Dict[str, List[bytes]] = {}
        self._rows: Dict[str, List[int]] = {}

    def create_index(self, index_name: str) -> None:
        self._keys.setdefault(index_name, [])
        self._rows.setdefault(index_name, [])

    def write(self, index_name: str, keys: Iterable[WriteKey]) -> None:
        """Bulk merge: sort incoming pairs, one O(N+M) merge with the
        existing sorted arrays (per-key list.insert would make a batch
        load O(N^2)). Same-key writes replace (idempotent overwrite)."""
        incoming = sorted(((wk.key, wk.row) for wk in keys), key=lambda p: p[0])
        if not incoming:
            return
        # same key twice in one batch: last one wins
        dedup = []
        for key, row in incoming:
            if dedup and dedup[-1][0] == key:
                dedup[-1] = (key, row)
            else:
                dedup.append((key, row))
        ks, rs = self._keys[index_name], self._rows[index_name]
        out_k: List[bytes] = []
        out_r: List[int] = []
        i = j = 0
        while i < len(ks) and j < len(dedup):
            if ks[i] < dedup[j][0]:
                out_k.append(ks[i])
                out_r.append(rs[i])
                i += 1
            elif ks[i] == dedup[j][0]:
                out_k.append(dedup[j][0])
                out_r.append(dedup[j][1])
                i += 1
                j += 1
            else:
                out_k.append(dedup[j][0])
                out_r.append(dedup[j][1])
                j += 1
        out_k.extend(ks[i:])
        out_r.extend(rs[i:])
        out_k.extend(p[0] for p in dedup[j:])
        out_r.extend(p[1] for p in dedup[j:])
        self._keys[index_name] = out_k
        self._rows[index_name] = out_r

    def delete(self, index_name: str, keys: Iterable[bytes]) -> None:
        ks, rs = self._keys[index_name], self._rows[index_name]
        for key in keys:
            i = bisect.bisect_left(ks, key)
            if i < len(ks) and ks[i] == key:
                del ks[i]
                del rs[i]

    def _slices(
        self, index_name: str, ranges: Sequence[ByteRange]
    ) -> List[Tuple[int, int]]:
        ks = self._keys[index_name]
        out = []
        for lo, hi in ranges:
            a = bisect.bisect_left(ks, lo)
            b = bisect.bisect_left(ks, hi)
            if b > a:
                out.append((a, b))
        return out

    def scan(self, index_name: str, ranges: Sequence[ByteRange]) -> List[int]:
        rs = self._rows[index_name]
        seen: Set[int] = set()
        out: List[int] = []
        for a, b in self._slices(index_name, ranges):
            for r in rs[a:b]:
                if r not in seen:
                    seen.add(r)
                    out.append(r)
        return out

    def scan_count(self, index_name: str, ranges: Sequence[ByteRange]) -> int:
        return sum(b - a for a, b in self._slices(index_name, ranges))

    def size(self, index_name: str) -> int:
        return len(self._keys[index_name])
