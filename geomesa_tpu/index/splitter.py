"""Filter splitting and index strategy selection.

Parity: geomesa-index-api planning's FilterSplitter + StrategyDecider
(SURVEY.md C6 steps 3-4) [upstream, unverified]. Every candidate index
offers a (primary-ranges, residual) option; the decider costs each option —
here with *exact* range key counts from the sorted adapter (strictly better
than upstream's sketch estimates, same contract) — and the cheapest wins.
An explicit hint override (QUERY_INDEX) short-circuits costing, as upstream.

The residual is always the full filter: index ranges are covering, and the
compiled-predicate mask removes false positives on device. This matches the
reference's handling of covering indices (XZ especially), where the
server-side residual re-checks everything the key schema can't decide.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

from geomesa_tpu.cql import ast
from geomesa_tpu.index.adapter import IndexAdapter
from geomesa_tpu.index.keyspace import ByteRange, IndexKeySpace


@dataclasses.dataclass
class IndexOption:
    """One way to answer a query: this index, these ranges."""

    index: IndexKeySpace
    ranges: List[ByteRange]
    cost: int  # estimated rows scanned

    @property
    def name(self) -> str:
        return getattr(self.index, "full_name", self.index.name)


class FilterSplitter:
    """Enumerate viable (index, ranges) options for a filter."""

    def __init__(self, indices: Sequence[IndexKeySpace]):
        self.indices = list(indices)

    def options(
        self, f: ast.Filter, max_ranges: int = 512
    ) -> List[IndexOption]:
        out = []
        for idx in self.indices:
            if isinstance(f, (ast.Include,)) or not idx.supports(f):
                continue
            ranges = idx.ranges(f, max_ranges=max_ranges)
            if ranges:
                out.append(IndexOption(idx, ranges, cost=-1))
        return out


class StrategyDecider:
    def __init__(self, adapter: IndexAdapter):
        self.adapter = adapter

    def decide(
        self,
        options: List[IndexOption],
        override: Optional[str] = None,
        explain=None,
    ) -> Optional[IndexOption]:
        e = explain if explain is not None else (lambda *_: None)
        if not options:
            e("No index options: full-table scan")
            return None
        if override:
            for opt in options:
                if opt.name == override or opt.index.name == override:
                    e(f"Index override: {opt.name}")
                    return opt
            e(f"Index override {override!r} not viable; falling back to cost")
        for opt in options:
            opt.cost = self.adapter.scan_count(opt.name, opt.ranges)
        best = min(options, key=lambda o: o.cost)
        e(
            "Strategy costs: "
            + ", ".join(f"{o.name}={o.cost}" for o in options)
            + f" -> chose {best.name}"
        )
        return best
