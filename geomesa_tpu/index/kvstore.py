"""KVDataStore: the index-architecture datastore over an IndexAdapter.

Parity: GeoMesaDataStore over a KV backend — the Accumulo/HBase-shaped
path (SURVEY.md §3.1/§3.2): writes fan out to every enabled index's key
schema; reads run FilterSplitter -> StrategyDecider -> range scan ->
residual compiled-mask evaluation on device -> local runner. With the
MemoryIndexAdapter this is also the TestGeoMesaDataStore analog (§4): the
full planner/index/aggregation stack with no cluster.

Differences from the FS store (plan/datastore.py): the FS store prunes
*partitions* (file layout); this store scans *key ranges* (row layout) —
the two index disciplines of the reference, both ending in the same device
residual + aggregation pipeline (plan/runner.py).
"""

from __future__ import annotations

import bisect
from typing import Dict, List, Optional, Sequence

import numpy as np

from geomesa_tpu.core.columnar import DictColumn, FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.cql import ast, compile_filter
from geomesa_tpu.faults import BREAKERS, RetryPolicy, retry_call
from geomesa_tpu.faults import harness as _faults
from geomesa_tpu.index.adapter import IndexAdapter, MemoryIndexAdapter
from geomesa_tpu.index.keyspace import IndexKeySpace, default_indices
from geomesa_tpu.index.splitter import FilterSplitter, StrategyDecider
from geomesa_tpu.plan.explain import Explainer
from geomesa_tpu.plan.query import Query
from geomesa_tpu.utils.padding import next_pow2 as _next_pow2


# KV boundary fault sites (docs/ROBUSTNESS.md). Range scans are
# idempotent reads and retry against the storage breaker; the write
# transaction is DELIBERATELY non-retryable — on a durable adapter the
# failed transaction rolls back atomically, and the documented contract
# is "discard the source and reopen" (docstring below), which a blind
# replay inside half-advanced in-memory bookkeeping would violate
# (.gmtpu-waivers records this).
_KV_SCAN_SITE = _faults.site(
    "kvstore.scan", "index range scan (IndexAdapter.scan)")
_KV_WRITE_SITE = _faults.site(
    "kvstore.write", "index write transaction (fan-out + row store)")
_KV_RETRY = RetryPolicy(max_attempts=4, base_ms=5.0, cap_ms=250.0)


class KVFeatureSource:
    def __init__(
        self,
        sft: SimpleFeatureType,
        adapter: IndexAdapter,
        indices: Sequence[IndexKeySpace],
        coord_dtype=None,
    ):
        self.sft = sft
        self.adapter = adapter
        self.indices = list(indices)
        self.splitter = FilterSplitter(self.indices)
        self.decider = StrategyDecider(adapter)
        self.coord_dtype = coord_dtype
        # QueryInterceptor SPI (plan/interceptor.py), per feature type as
        # in the reference; SFT-configured interceptors load here too
        from geomesa_tpu.plan.interceptor import load_interceptors

        self.interceptors: List = load_interceptors(sft)
        for idx in self.indices:
            adapter.create_index(getattr(idx, "full_name", idx.name))
        # row storage: append-only batches with cumulative offsets
        self._batches: List[FeatureBatch] = []
        self._fids: List[List[str]] = []
        self._offsets: List[int] = [0]
        self._fid_row: Dict[str, int] = {}
        self._dead: set = set()
        self._seq = 0
        # a durable adapter (index/durable.py) also persists the row store;
        # restore batches / tombstones / fid map from it on (re)open
        self._durable = hasattr(adapter, "load_batches")
        if self._durable:
            from geomesa_tpu.index.durable import ipc_to_batch

            for ipc, fids in adapter.load_batches():
                batch = ipc_to_batch(ipc, self.sft)
                base = self._offsets[-1]
                self._batches.append(batch)
                self._fids.append(list(fids))
                self._offsets.append(base + len(batch))
            self._dead = adapter.load_dead()
            for b, fids in enumerate(self._fids):
                for i, f in enumerate(fids):
                    r = self._offsets[b] + i
                    if r not in self._dead:
                        self._fid_row[f] = r
            self._seq = int(adapter.meta_get("seq", "0"))

    # -- writes ------------------------------------------------------------

    def write(self, batch: FeatureBatch, fids: Optional[Sequence[str]] = None) -> List[str]:
        """Index + store a batch; same-fid writes replace (upstream:
        idempotent same-key overwrite, §5.3). Returns the feature ids.

        Padding rows (valid=False) are compacted away first: they are a
        device-shape artifact, and storing them would also desync the
        durable row store (Arrow IPC persists valid rows only).

        Failure contract (§5.3 fail-fast): on a durable adapter the disk
        transaction rolls back atomically, but in-memory bookkeeping may
        have advanced — discard this source and reopen the store after a
        write exception; the reopened state is the pre-write state."""
        if batch.valid is not None and not bool(batch.valid.all()):
            keep = np.nonzero(batch.valid)[0]
            if fids is not None:
                fids = [fids[int(i)] for i in keep]
            batch = batch.select(keep)
        n = len(batch)
        if fids is None:
            fids = batch.fids.decode() if batch.fids is not None else None
        if fids is None:
            fids = [f"{self.sft.name}-{self._seq + i}" for i in range(n)]
        fids = [str(f) for f in fids]
        self._seq += n

        # the whole logical write — tombstoning replaced fids, the row
        # batch, every index's keys, and the fid sequence — commits as one
        # transaction on durable adapters: a crash leaves all or nothing
        import contextlib

        txn = (
            self.adapter.transaction()
            if self._durable
            else contextlib.nullcontext()
        )
        with txn:
            _KV_WRITE_SITE.fire()
            # replace-by-id: tombstone + de-index any previous row per fid
            stale = [self._fid_row[f] for f in fids if f in self._fid_row]
            if stale:
                self._delete_rows(stale)

            base = self._offsets[-1]
            rows = list(range(base, base + n))
            self._batches.append(batch)
            self._fids.append(list(fids))
            self._offsets.append(base + n)
            for i, f in enumerate(fids):
                self._fid_row[f] = base + i
            if self._durable:
                from geomesa_tpu.index.durable import batch_to_ipc

                self.adapter.store_batch(batch_to_ipc(batch), fids)
            for idx in self.indices:
                name = getattr(idx, "full_name", idx.name)
                self.adapter.write(name, idx.write_keys(batch, fids, rows))
            if self._durable:
                self.adapter.meta_set("seq", str(self._seq))
        return list(fids)

    def _locate(self, row: int):
        b = bisect.bisect_right(self._offsets, row) - 1
        return b, row - self._offsets[b]

    def _delete_rows(self, rows: Sequence[int]) -> None:
        import contextlib

        # atomic on durable adapters (reentrant: write() already holds the
        # transaction on the replace-by-id path)
        txn = (
            self.adapter.transaction()
            if self._durable
            else contextlib.nullcontext()
        )
        with txn:
            by_batch: Dict[int, List[int]] = {}
            newly_dead: List[int] = []
            for r in rows:
                if r in self._dead:
                    continue
                b, i = self._locate(r)
                by_batch.setdefault(b, []).append(i)
                self._dead.add(r)
                newly_dead.append(r)
            if self._durable and newly_dead:
                self.adapter.mark_dead(newly_dead)
            for b, local in by_batch.items():
                sel = self._batches[b].select(np.asarray(sorted(local)))
                fids = [self._fids[b][i] for i in sorted(local)]
                rows_abs = [self._offsets[b] + i for i in sorted(local)]
                for idx in self.indices:
                    name = getattr(idx, "full_name", idx.name)
                    keys = [wk.key for wk in idx.write_keys(sel, fids, rows_abs)]
                    self.adapter.delete(name, keys)
                for f in fids:
                    if self._fid_row.get(f) in rows_abs:
                        del self._fid_row[f]

    def age_off(self, ttl_ms: int, now_ms: Optional[int] = None) -> int:
        """Delete features older than ttl (upstream: DtgAgeOffIterator /
        AgeOffIterator TTL enforcement, run as a maintenance sweep rather
        than scan-time filtering). Returns the number removed."""
        import time as _time

        d = self.sft.default_dtg
        if d is None:
            raise ValueError("age_off needs a default dtg attribute")
        now = now_ms if now_ms is not None else int(_time.time() * 1000)
        cutoff = now - int(ttl_ms)
        rows = []
        for b, batch in enumerate(self._batches):
            dtg = np.asarray(batch.columns[d.name], np.int64)
            for i in np.nonzero(dtg < cutoff)[0]:
                r = self._offsets[b] + int(i)
                if r not in self._dead:
                    rows.append(r)
        self._delete_rows(rows)
        return len(rows)

    def delete_features(self, query: "Query | str") -> int:
        """Delete everything matching the filter (upstream delete-features)."""
        r = self.get_features(query if not isinstance(query, str)
                              else Query(self.sft.name, query))
        if r.features is None or len(r.features) == 0:
            return 0
        fids = r.features.fids.decode() if r.features.fids is not None else []
        rows = [self._fid_row[f] for f in fids if f in self._fid_row]
        self._delete_rows(rows)
        return len(rows)

    # -- reads -------------------------------------------------------------

    @property
    def live_count(self) -> int:
        return self._offsets[-1] - len(self._dead)

    def _all_rows(self) -> List[int]:
        return [r for r in range(self._offsets[-1]) if r not in self._dead]

    def _gather(self, rows: Sequence[int]) -> FeatureBatch:
        by_batch: Dict[int, List[int]] = {}
        for r in sorted(rows):
            b, i = self._locate(r)
            by_batch.setdefault(b, []).append(i)
        parts = []
        for b in sorted(by_batch):
            idx = np.asarray(by_batch[b])
            sel = self._batches[b].select(idx)
            sel = FeatureBatch(
                sel.sft, sel.columns,
                DictColumn.encode([self._fids[b][i] for i in by_batch[b]]),
                sel.valid,
            )
            parts.append(sel)
        return FeatureBatch.concat(parts)

    def plan(self, query: "Query | str", explain: Optional[Explainer] = None):
        from geomesa_tpu.plan.interceptor import run_interceptors

        if isinstance(query, str):
            query = Query(self.sft.name, query)
        e = explain if explain is not None else Explainer()
        query = run_interceptors(query, self.interceptors, e)
        f = query.filter_ast
        e(f"Planning KV query: {ast.to_cql(f)}")
        options = self.splitter.options(f)
        e(f"Index options: {[o.name for o in options] or 'none (full scan)'}")
        chosen = self.decider.decide(options, query.hints.query_index, e)
        if chosen is not None:
            e(f"Chosen index: {chosen.name} with {len(chosen.ranges)} ranges "
              f"(~{chosen.cost} keys)")
        return query, f, chosen

    def explain(self, query: "Query | str") -> str:
        e = Explainer()
        self.plan(query, e)
        return e.render()

    def get_features(self, query: "Query | str" = "INCLUDE"):
        from geomesa_tpu.engine.device import to_device
        from geomesa_tpu.plan.planner import QueryResult
        from geomesa_tpu.plan.runner import aggregate, sample_mask

        query, f, chosen = self.plan(query)
        if chosen is not None:
            name = chosen.name

            def _scan():
                _KV_SCAN_SITE.fire()
                return [
                    r for r in self.adapter.scan(name, chosen.ranges)
                    if r not in self._dead
                ]

            rows = retry_call(_scan, policy=_KV_RETRY, label="storage",
                              breaker=BREAKERS.get("storage"))
        else:
            rows = self._all_rows()
        if not rows:
            return QueryResult("features", features=None, count=0)

        batch = self._gather(rows)
        padded = batch.pad_to(_next_pow2(len(batch)))
        dev = to_device(padded, **(
            {"coord_dtype": self.coord_dtype} if self.coord_dtype else {}
        ))
        if isinstance(f, ast.Include):
            mask = np.asarray(dev["__valid__"])
        else:
            residual = f
            if query.hints.loose_bbox:
                from geomesa_tpu.plan.planner import _loosen_bbox

                g = self.sft.default_geometry
                if g is not None:
                    residual = _loosen_bbox(f, g.name)
            compiled = compile_filter(residual, self.sft)
            # mask_refined: f64 re-check of rows inside the f32 polygon
            # boundary band (no-op for band-free filters)
            mask = compiled.mask_refined(dev, padded)
        if query.hints.sampling:
            groups = None
            if query.hints.sample_by:
                from geomesa_tpu.core.columnar import DictColumn

                col = padded.columns[query.hints.sample_by]
                groups = (
                    np.asarray(col.codes)
                    if isinstance(col, DictColumn)
                    else np.asarray(col)
                )
            mask = sample_mask(mask, query.hints.sampling, groups)
        return aggregate(self.sft, padded, dev, mask, query)

    def get_count(self, query: "Query | str" = "INCLUDE") -> int:
        from geomesa_tpu.plan.interceptor import run_interceptors

        if isinstance(query, str):
            query = Query(self.sft.name, query)
        # the shortcut must see the post-interceptor query; the intercepted
        # marker makes the nested get_features -> plan pass a no-op, so the
        # chain applies exactly once (no idempotence requirement)
        query = run_interceptors(query, self.interceptors)
        if (
            not query.hints.exact_count
            and isinstance(query.filter_ast, ast.Include)
            # live_count knows nothing about auths: visibility-configured
            # types count through the masked aggregation path
            and not (self.sft.user_data or {}).get("geomesa.vis.attr")
        ):
            return self.live_count
        r = self.get_features(query)
        if r.kind == "features":
            return len(r.features) if r.features is not None else 0
        return r.count

    def get_features_by_id(self, fids: Sequence[str]) -> FeatureBatch:
        rows = [self._fid_row[f] for f in fids if f in self._fid_row]
        if rows:
            return self._gather(rows)
        # well-formed empty batch (proper empty GeometryColumn/DictColumn)
        return FeatureBatch.from_pydict(
            self.sft, {a.name: [] for a in self.sft.attributes}
        )


class KVDataStore:
    """A catalog of KV-indexed feature types (in-memory by default)."""

    def __init__(self, adapter_factory=MemoryIndexAdapter, shards: int = 4):
        self._adapter_factory = adapter_factory
        self._shards = shards
        self._sources: Dict[str, KVFeatureSource] = {}

    def create_schema(
        self,
        sft: SimpleFeatureType,
        indices: Optional[Sequence[IndexKeySpace]] = None,
    ) -> KVFeatureSource:
        if sft.name in self._sources:
            raise ValueError(f"schema {sft.name!r} already exists")
        adapter = self._adapter_factory()
        if indices is None:
            indices = default_indices(sft, self._shards)
        src = KVFeatureSource(sft, adapter, indices)
        self._sources[sft.name] = src
        return src

    def get_feature_source(self, name: str) -> KVFeatureSource:
        return self._sources[name]

    def get_schema(self, name: str) -> SimpleFeatureType:
        return self._sources[name].sft

    def get_type_names(self) -> List[str]:
        return sorted(self._sources)

    def remove_schema(self, name: str) -> None:
        del self._sources[name]
