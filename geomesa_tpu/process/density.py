"""DensityProcess.

Parity: geomesa-process analytic/DensityProcess [upstream, unverified]:
heatmap of matching features via the DensityScan hint path, with
radiusPixels gaussian spread. Returns the (height, width) float grid
(row 0 = south; callers flip for raster rendering, as GeoServer does).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from geomesa_tpu.plan.datastore import FeatureSource
from geomesa_tpu.plan.hints import QueryHints
from geomesa_tpu.plan.query import Query


class DensityProcess:
    name = "DensityProcess"

    def execute(
        self,
        data: FeatureSource,
        bbox: Tuple[float, float, float, float],
        width: int = 512,
        height: int = 512,
        cql_filter: str = "INCLUDE",
        weight_attr: Optional[str] = None,
        radius_pixels: int = 0,
    ) -> np.ndarray:
        q = Query(
            data.sft.name,
            cql_filter,
            hints=QueryHints(
                density_bbox=tuple(bbox),
                density_width=width,
                density_height=height,
                density_weight=weight_attr,
            ),
        )
        grid = data.get_features(q).grid
        if radius_pixels > 0:
            import jax.numpy as jnp

            from geomesa_tpu.engine.density import gaussian_blur

            grid = np.asarray(gaussian_blur(jnp.asarray(grid), radius_pixels))
        return grid
