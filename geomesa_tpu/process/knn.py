"""KNearestNeighborSearchProcess.

Parity: geomesa-process knn/KNearestNeighborSearchProcess [upstream,
unverified]. Same parameters (inputFeatures, dataFeatures, numDesired,
estimatedDistance, maxSearchDistance); same guarantee (k nearest by geodesic
distance within maxSearchDistance).

Mechanism redesigned for TPU (SURVEY.md §3.4): instead of per-query-point
window queries with geometric radius growth, ONE covering window query for
all query points at the current radius feeds a dense tiled kNN kernel;
the radius doubles only if some query's k-th neighbor distance exceeds its
searched radius (the recall-parity condition at window edges), re-using the
same kernel on the wider candidate set. Worst case log2(max/estimated)
store scans; each scan is one fused device pass. A materialized FeatureBatch
input needs no window iteration at all — the kernel is exact over the batch
in a single pass.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.cql.extract import BBox
from geomesa_tpu.plan.datastore import FeatureSource
from geomesa_tpu.process.util import candidates_for, filter_batch, window_query


@dataclasses.dataclass
class KnnResult:
    indices: np.ndarray  # [Q, k] into `features`
    distances_m: np.ndarray  # [Q, k] (inf where fewer than k within range)
    features: FeatureBatch  # the candidate set the indices refer to
    # True when the widen-and-retry loop hit its iteration cap before
    # every query's recall condition held: the neighbors returned are
    # the best found within the searched radius, but a closer point MAY
    # exist between the last searched radius and max_search_distance_m.
    # Callers needing guaranteed recall should raise estimated_distance
    # or lower max_search_distance instead of looping forever.
    partial_recall: bool = False


# Bound on the widen-and-retry rounds: radius doubles per round, so 48
# rounds cover >14 decimal orders of magnitude from any sane estimate —
# hitting the cap means the window can never fill (e.g. an infinite
# max_search_distance over a region with < k points), and the honest
# answer is a partial_recall result, not an unbounded loop.
MAX_WIDEN_ROUNDS = 48


class KNearestNeighborSearchProcess:
    name = "KNearestNeighborSearchProcess"

    def __init__(self):
        # sparse-scan tile capacities cached across queries (planner-
        # stats analog): keyed by (batch identity, filter, k); dropped
        # when an overflow forced the fullscan fallback
        self._cap_cache: dict = {}
        # compiled CQL filters reused across execute() calls: a fresh
        # compile_filter carries a fresh jax.jit wrapper, forcing an XLA
        # recompile of the predicate kernel per query (planner has the
        # same cache for the same reason)
        self._filter_cache: dict = {}

    def execute(
        self,
        input_features: FeatureBatch,
        data_features: "FeatureSource | FeatureBatch",
        num_desired: int = 10,
        estimated_distance_m: float = 10_000.0,
        max_search_distance_m: float = 1_000_000.0,
        cql_filter: str = "INCLUDE",
        query_tile: int = 1024,
        impl: str = "auto",
    ) -> KnnResult:
        """impl: "sparse" (Pallas fused scan over match-bearing data tiles
        only — the flagship kernel; exact, with automatic dense fallback
        on tile-capacity overflow), "fullscan" (dense Pallas fused scan),
        "haversine" (f64 coords, bit-exact XLA), "mxu" (f32 chord matmul
        + exact refine), "grid" (device-built spatial index), or "auto":
        sparse for large batches under a selective filter (store scans
        emit Z-ordered rows, the layout where tile pruning wins — and it
        stays exact for any order), fullscan for large unfiltered
        batches, haversine below ~1M rows where kernel launch dominates.
        """
        qcol = input_features.geometry
        qx, qy = np.asarray(qcol.x), np.asarray(qcol.y)

        if isinstance(data_features, FeatureBatch):
            eff = self._resolve_impl(impl, len(data_features), cql_filter)
            if eff in ("sparse", "fullscan"):
                # fused-scan path: the FULL batch stays device-resident
                # (cached across calls) and the predicate becomes a device
                # mask — no host compaction (VERDICT r3 #1: the product
                # path must run the same kernel the bench headline runs)
                return self._solve_scan(
                    qx, qy, data_features, cql_filter, num_desired,
                    max_search_distance_m, eff, query_tile=query_tile,
                )
            # materialized input: one exact pass, no window growth possible
            candidates = filter_batch(data_features, cql_filter)
            return self._solve(
                qx, qy, candidates, num_desired, max_search_distance_m,
                query_tile, eff,
            )

        radius = max(float(estimated_distance_m), 1.0)
        # auto keeps the f64 bit-exact window path for small stores (the
        # fused scan is f32-keyed with exact-haversine refine — still
        # exact neighbor SETS, but distances carry f32 noise); the sparse
        # scan wins where kernel cost dominates, i.e. large stores
        use_planner_scan = hasattr(data_features, "planner") and (
            impl in ("sparse", "fullscan")
            or (
                impl == "auto"
                and getattr(data_features.storage, "count", 0) >= (1 << 20)
            )
        )
        rounds = 0
        while True:
            bbox = BBox(
                float(qx.min()), float(qy.min()), float(qx.max()), float(qy.max())
            ).buffer_degrees(radius)
            if use_planner_scan:
                # store path: the planner evaluates the window+filter as a
                # device mask over its (cached) batch and runs the sparse
                # scan — the index-scan-to-kernel pipeline with no host
                # materialization of candidates
                result = self._solve_planner(
                    qx, qy, data_features, bbox, cql_filter, num_desired,
                    max_search_distance_m, impl,
                )
            else:
                candidates = window_query(data_features, bbox, cql_filter)
                if candidates is None or len(candidates) == 0:
                    if (radius >= max_search_distance_m
                            or rounds >= MAX_WIDEN_ROUNDS):
                        empty = self._solve(
                            qx, qy,
                            candidates
                            if candidates is not None
                            else input_features.select(np.zeros(0, np.int64)),
                            num_desired, max_search_distance_m, query_tile,
                            impl,
                        )
                        if rounds >= MAX_WIDEN_ROUNDS:
                            empty.partial_recall = True
                        return empty
                    rounds += 1
                    radius = min(radius * 2, max_search_distance_m)
                    continue
                result = self._solve(
                    qx, qy, candidates, num_desired, max_search_distance_m,
                    query_tile, impl,
                )
            # recall condition: every query's k-th neighbor must lie within
            # the searched radius, else a closer point may sit outside the
            # window — widen and retry (reference: expand window, re-query)
            kth = result.distances_m[:, -1]
            unsafe = (kth > radius) & np.isfinite(kth)
            short = ~np.isfinite(kth)
            if (unsafe.any() or short.any()) and radius < max_search_distance_m:
                if rounds >= MAX_WIDEN_ROUNDS:
                    # the window never fills (see MAX_WIDEN_ROUNDS):
                    # surface what was found, flagged, instead of
                    # doubling the radius forever
                    result.partial_recall = True
                    return result
                rounds += 1
                radius = min(radius * 2, max_search_distance_m)
                continue
            return result

    @staticmethod
    def _resolve_impl(impl: str, n: int, cql_filter: str) -> str:
        if impl != "auto":
            return impl
        if n >= (1 << 20):
            return "sparse" if cql_filter != "INCLUDE" else "fullscan"
        return "haversine"

    def _solve_scan(
        self, qx, qy, batch: FeatureBatch, cql_filter: str, k: int,
        max_dist: float, eff: str, interpret: bool = False,
        query_tile: int = 256,
    ) -> KnnResult:
        """Fused-scan solve over the full device-resident batch.
        query_tile applies to the fullscan route (per-tile batch
        rescans); the sparse route ranks all queries in one pass."""
        import jax.numpy as jnp

        from geomesa_tpu.cql import ast, compile_filter, parse_cql
        from geomesa_tpu.engine.device import to_device_cached
        from geomesa_tpu.engine.knn_scan import (
            knn_fullscan_tiled, knn_sparse_auto)

        from geomesa_tpu.engine.knn_scan import default_interpret

        interpret = interpret or default_interpret()
        dev = to_device_cached(batch, coord_dtype=jnp.float32)
        g = batch.sft.default_geometry
        cx, cy = dev[f"{g.name}__x"], dev[f"{g.name}__y"]
        mask = dev["__valid__"]
        f = parse_cql(cql_filter)
        if not isinstance(f, ast.Include):
            fkey = (cql_filter, id(batch.sft))
            ent = self._filter_cache.get(fkey)
            # the value holds a strong ref to the sft so its id() cannot
            # be recycled onto a different schema while the entry lives;
            # the identity check guards the (cleared-then-reused) case
            if ent is not None and ent[0] is batch.sft:
                compiled = ent[1]
            else:
                if len(self._filter_cache) > 256:
                    self._filter_cache.clear()
                compiled = compile_filter(f, batch.sft)
                self._filter_cache[fkey] = (batch.sft, compiled)
            mask = mask & compiled.mask(dev, batch)
            if compiled.has_band:
                # f64 re-check of rows inside the f32 boundary band —
                # without it, polygon/geometry predicates on the f32
                # device coords misclassify band points that the
                # filter_batch path (f64) classified exactly. Device-
                # resident: exact values scatter into the mask at their
                # indices (the fetch-patch-reupload refine cost 23.6 s
                # per query at 67M rows — round-5 profile)
                bidx, bexact = compiled.band_corrections(dev, batch)
                if len(bidx):
                    if batch.valid is not None:
                        bexact = bexact & batch.valid[bidx]
                    mask = mask.at[jnp.asarray(bidx)].set(
                        jnp.asarray(bexact))
        # the clamp binds only when n < k, so the dispatch shape set
        # is bounded by k (a per-query constant), not by traffic;
        # steady-state batches always satisfy n >= k
        # gt: waive GT28
        kk = min(k, len(batch))
        mb = max(64, kk)
        jqx, jqy = jnp.asarray(qx, jnp.float32), jnp.asarray(qy, jnp.float32)
        if eff == "sparse":
            # per-batch capacity slot, evicted with the batch (id() alone
            # could be recycled onto a new batch; a stale cap is never
            # wrong — overflow falls back — but wastes a dense rerun)
            import weakref

            bkey = id(batch)
            slot = self._cap_cache.get(bkey)
            if slot is None:
                slot = self._cap_cache[bkey] = {}
                weakref.finalize(batch, self._cap_cache.pop, bkey, None)
            key = (cql_filter, kk)
            fd, fi, cap = knn_sparse_auto(
                jqx, jqy, cx, cy, mask, k=kk,
                tile_capacity=slot.get(key),
                m_blocks=mb, interpret=interpret,
            )
            if cap > 0:
                slot[key] = cap
            else:
                slot.pop(key, None)  # overflow: recalibrate
        else:
            fd, fi = knn_fullscan_tiled(
                jqx, jqy, cx, cy, mask, k=kk, m_blocks=mb,
                query_tile=query_tile, interpret=interpret,
            )
        from geomesa_tpu.plan.planner import _pad_to_k

        dists, idx = _pad_to_k(np.asarray(fd), np.asarray(fi), k)
        dists = np.where(dists <= max_dist, dists, np.inf)
        return KnnResult(idx, dists, batch)

    def _solve_planner(
        self, qx, qy, source, bbox: BBox, cql_filter: str, k: int,
        max_dist: float, impl: str,
    ) -> KnnResult:
        """Store path: planner-evaluated device mask + fused scan.
        planner.knn already pads to k columns; only the distance clamp
        applies here. "auto" flows through: the planner resolves it from
        its stats sketches (selectivity-typed, not string-typed —
        VERDICT r4 task 6)."""
        dists, idx, batch = source.planner.knn(
            _window_cql(source.sft, bbox, cql_filter), qx, qy, k=k,
            impl=impl,
        )
        dists = np.where(dists <= max_dist, dists, np.inf)
        return KnnResult(idx, dists, batch)

    def _solve(  # noqa: C901 — per-impl dispatch table
        self, qx, qy, candidates: FeatureBatch, k: int, max_dist: float,
        query_tile: int, impl: str = "haversine",
    ) -> KnnResult:
        if candidates is None or len(candidates) == 0:
            return KnnResult(
                np.zeros((len(qx), k), np.int32),
                np.full((len(qx), k), np.inf),
                candidates,
            )
        import jax.numpy as jnp

        from geomesa_tpu.engine.device import to_device
        from geomesa_tpu.engine.knn import knn, knn_mxu

        use_mxu = impl == "mxu"
        use_grid = impl == "grid" or (
            impl == "auto"
            and len(qx) >= 512
            and len(candidates) >= (1 << 20)
        )
        dev = to_device(
            candidates,
            coord_dtype=jnp.float32 if (use_mxu or use_grid) else jnp.float64,
        )
        g = candidates.sft.default_geometry
        cx, cy, valid = dev[f"{g.name}__x"], dev[f"{g.name}__y"], dev["__valid__"]
        # k clamp: binds only for degenerate n < k candidate sets;
        # at most k distinct shapes, not per-extent
        # gt: waive GT28
        kk = min(k, len(candidates))
        if use_grid:
            # many queries against a large batch: the device-built grid
            # index amortizes one sort over all queries (engine.grid_index;
            # certificate-failed queries fall back to the exact scan inside)
            from geomesa_tpu.engine.grid_index import (
                auto_grid_params, knn_indexed)

            g_edge, slots = auto_grid_params(len(candidates))
            dists, idx = knn_indexed(
                jnp.asarray(qx), jnp.asarray(qy), cx, cy, valid,
                k=kk, g=g_edge, ring_radius=2, cell_slots=slots,
            )
            dists, idx = np.asarray(dists), np.asarray(idx)
        elif use_mxu:
            dists, idx, flags = knn_mxu(
                jnp.asarray(qx), jnp.asarray(qy), cx, cy, valid,
                k=kk, with_flags=True,
            )
            dists, idx = np.array(dists), np.array(idx)
            flags = np.asarray(flags)
            if flags.any():
                # certificate failed for these queries (cluster-boundary
                # tiles): re-solve just them on the exact haversine path
                fqx, fqy = qx[flags], qy[flags]
                ed, ei = knn(
                    jnp.asarray(fqx), jnp.asarray(fqy), cx, cy, valid,
                    k=kk, query_tile=min(query_tile, max(len(fqx), 1)),
                )
                dists[flags] = np.asarray(ed)
                idx[flags] = np.asarray(ei)
        else:
            dists, idx = knn(
                jnp.asarray(qx), jnp.asarray(qy), cx, cy, valid,
                k=kk, query_tile=min(query_tile, max(len(qx), 1)),
            )
            dists, idx = np.asarray(dists), np.asarray(idx)
        from geomesa_tpu.plan.planner import _pad_to_k

        dists, idx = _pad_to_k(dists, idx, k)
        dists = np.where(dists <= max_dist, dists, np.inf)
        return KnnResult(idx, dists, candidates)


def _window_cql(sft, bbox: BBox, cql_filter: str):
    """BBOX-window Query ANDed with an optional ECQL filter."""
    from geomesa_tpu.plan.query import Query
    from geomesa_tpu.process.util import window_filter

    return Query(sft.name, window_filter(sft, bbox, cql_filter))
