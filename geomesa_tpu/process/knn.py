"""KNearestNeighborSearchProcess.

Parity: geomesa-process knn/KNearestNeighborSearchProcess [upstream,
unverified]. Same parameters (inputFeatures, dataFeatures, numDesired,
estimatedDistance, maxSearchDistance); same guarantee (k nearest by geodesic
distance within maxSearchDistance).

Mechanism redesigned for TPU (SURVEY.md §3.4): instead of per-query-point
window queries with geometric radius growth, ONE covering window query for
all query points at the current radius feeds a dense tiled kNN kernel;
the radius doubles only if some query's k-th neighbor distance exceeds its
searched radius (the recall-parity condition at window edges), re-using the
same kernel on the wider candidate set. Worst case log2(max/estimated)
store scans; each scan is one fused device pass. A materialized FeatureBatch
input needs no window iteration at all — the kernel is exact over the batch
in a single pass.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.cql.extract import BBox
from geomesa_tpu.plan.datastore import FeatureSource
from geomesa_tpu.process.util import candidates_for, filter_batch, window_query


@dataclasses.dataclass
class KnnResult:
    indices: np.ndarray  # [Q, k] into `features`
    distances_m: np.ndarray  # [Q, k] (inf where fewer than k within range)
    features: FeatureBatch  # the candidate set the indices refer to


class KNearestNeighborSearchProcess:
    name = "KNearestNeighborSearchProcess"

    def execute(
        self,
        input_features: FeatureBatch,
        data_features: "FeatureSource | FeatureBatch",
        num_desired: int = 10,
        estimated_distance_m: float = 10_000.0,
        max_search_distance_m: float = 1_000_000.0,
        cql_filter: str = "INCLUDE",
        query_tile: int = 1024,
        impl: str = "haversine",
    ) -> KnnResult:
        """impl: "haversine" (f64 coords, bit-exact), "mxu" (f32 coords,
        centered chord-distance matmul on the systolic array with exact
        haversine refine; certificate-flagged queries are re-solved on the
        exact path — see engine.knn.knn_mxu for the accuracy model),
        "grid" (device-built spatial index, certificate + exact fallback —
        engine.grid_index), or "auto" (grid when many queries hit a large
        batch, else haversine)."""
        qcol = input_features.geometry
        qx, qy = np.asarray(qcol.x), np.asarray(qcol.y)

        if isinstance(data_features, FeatureBatch):
            # materialized input: one exact pass, no window growth possible
            candidates = filter_batch(data_features, cql_filter)
            return self._solve(
                qx, qy, candidates, num_desired, max_search_distance_m,
                query_tile, impl,
            )

        radius = max(float(estimated_distance_m), 1.0)
        while True:
            bbox = BBox(
                float(qx.min()), float(qy.min()), float(qx.max()), float(qy.max())
            ).buffer_degrees(radius)
            candidates = window_query(data_features, bbox, cql_filter)
            if candidates is None or len(candidates) == 0:
                if radius >= max_search_distance_m:
                    return self._solve(
                        qx, qy,
                        candidates
                        if candidates is not None
                        else input_features.select(np.zeros(0, np.int64)),
                        num_desired, max_search_distance_m, query_tile, impl,
                    )
                radius = min(radius * 2, max_search_distance_m)
                continue
            result = self._solve(
                qx, qy, candidates, num_desired, max_search_distance_m,
                query_tile, impl,
            )
            # recall condition: every query's k-th neighbor must lie within
            # the searched radius, else a closer point may sit outside the
            # window — widen and retry (reference: expand window, re-query)
            kth = result.distances_m[:, -1]
            unsafe = (kth > radius) & np.isfinite(kth)
            short = ~np.isfinite(kth)
            if (unsafe.any() or short.any()) and radius < max_search_distance_m:
                radius = min(radius * 2, max_search_distance_m)
                continue
            return result

    def _solve(
        self, qx, qy, candidates: FeatureBatch, k: int, max_dist: float,
        query_tile: int, impl: str = "haversine",
    ) -> KnnResult:
        if candidates is None or len(candidates) == 0:
            return KnnResult(
                np.zeros((len(qx), k), np.int32),
                np.full((len(qx), k), np.inf),
                candidates,
            )
        import jax.numpy as jnp

        from geomesa_tpu.engine.device import to_device
        from geomesa_tpu.engine.knn import knn, knn_mxu

        use_mxu = impl == "mxu"
        use_grid = impl == "grid" or (
            impl == "auto"
            and len(qx) >= 512
            and len(candidates) >= (1 << 20)
        )
        dev = to_device(
            candidates,
            coord_dtype=jnp.float32 if (use_mxu or use_grid) else jnp.float64,
        )
        g = candidates.sft.default_geometry
        cx, cy, valid = dev[f"{g.name}__x"], dev[f"{g.name}__y"], dev["__valid__"]
        kk = min(k, len(candidates))
        if use_grid:
            # many queries against a large batch: the device-built grid
            # index amortizes one sort over all queries (engine.grid_index;
            # certificate-failed queries fall back to the exact scan inside)
            from geomesa_tpu.engine.grid_index import (
                auto_grid_params, knn_indexed)

            g_edge, slots = auto_grid_params(len(candidates))
            dists, idx = knn_indexed(
                jnp.asarray(qx), jnp.asarray(qy), cx, cy, valid,
                k=kk, g=g_edge, ring_radius=2, cell_slots=slots,
            )
            dists, idx = np.asarray(dists), np.asarray(idx)
        elif use_mxu:
            dists, idx, flags = knn_mxu(
                jnp.asarray(qx), jnp.asarray(qy), cx, cy, valid,
                k=kk, with_flags=True,
            )
            dists, idx = np.array(dists), np.array(idx)
            flags = np.asarray(flags)
            if flags.any():
                # certificate failed for these queries (cluster-boundary
                # tiles): re-solve just them on the exact haversine path
                fqx, fqy = qx[flags], qy[flags]
                ed, ei = knn(
                    jnp.asarray(fqx), jnp.asarray(fqy), cx, cy, valid,
                    k=kk, query_tile=min(query_tile, max(len(fqx), 1)),
                )
                dists[flags] = np.asarray(ed)
                idx[flags] = np.asarray(ei)
        else:
            dists, idx = knn(
                jnp.asarray(qx), jnp.asarray(qy), cx, cy, valid,
                k=kk, query_tile=min(query_tile, max(len(qx), 1)),
            )
            dists, idx = np.asarray(dists), np.asarray(idx)
        if dists.shape[1] < k:
            pad = k - dists.shape[1]
            dists = np.pad(dists, ((0, 0), (0, pad)), constant_values=np.inf)
            idx = np.pad(idx, ((0, 0), (0, pad)))
        dists = np.where(dists <= max_dist, dists, np.inf)
        return KnnResult(idx, dists, candidates)
