"""The remaining vector processes.

Parity: geomesa-process-vector [upstream, unverified]:
ProximitySearchProcess, QueryProcess, SamplingProcess, StatsProcess,
UniqueProcess, JoinProcess, Point2PointProcess, DateOffsetProcess,
HashAttributeProcess (+Color), RouteSearchProcess, ArrowConversionProcess,
BinConversionProcess.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.core.columnar import DictColumn, FeatureBatch, GeometryColumn
from geomesa_tpu.core.sft import AttributeDescriptor, SimpleFeatureType
from geomesa_tpu.plan.datastore import FeatureSource
from geomesa_tpu.plan.hints import QueryHints
from geomesa_tpu.plan.query import Query


class ProximitySearchProcess:
    """Features of `data` within `distance_m` of ANY input feature."""

    name = "ProximitySearchProcess"

    def execute(
        self,
        input_features: FeatureBatch,
        data: "FeatureSource | FeatureBatch",
        distance_m: float,
        cql_filter: str = "INCLUDE",
    ) -> FeatureBatch:
        import jax.numpy as jnp

        from geomesa_tpu.cql.extract import BBox
        from geomesa_tpu.engine.device import to_device
        from geomesa_tpu.engine.knn import knn
        from geomesa_tpu.process.util import candidates_for

        g_in = input_features.geometry
        bbox = BBox(
            float(np.min(g_in.x)), float(np.min(g_in.y)),
            float(np.max(g_in.x)), float(np.max(g_in.y)),
        ).buffer_degrees(distance_m)
        candidates = candidates_for(data, bbox, cql_filter)
        if candidates is None or len(candidates) == 0:
            return input_features.select(np.zeros(0, np.int64))
        dev = to_device(candidates, coord_dtype=jnp.float64)
        g = candidates.sft.default_geometry
        # nearest input point per candidate: 1-NN with roles swapped
        d, _ = knn(
            dev[f"{g.name}__x"], dev[f"{g.name}__y"],
            jnp.asarray(g_in.x), jnp.asarray(g_in.y),
            jnp.ones(len(g_in.x), bool), k=1,
            query_tile=min(1024, len(candidates)),
        )
        mask = np.asarray(d[:, 0]) <= distance_m
        valid = candidates.valid if candidates.valid is not None else np.ones(len(candidates), bool)
        return candidates.select(mask & valid)


class QueryProcess:
    """Run an ECQL query as a process (chaining primitive)."""

    name = "QueryProcess"

    def execute(self, data: FeatureSource, cql: str) -> FeatureBatch:
        r = data.get_features(Query(data.sft.name, cql))
        return r.features


class SamplingProcess:
    """Statistical thinning (every n-th match)."""

    name = "SamplingProcess"

    def execute(
        self, data: FeatureSource, n: int, cql_filter: str = "INCLUDE"
    ) -> FeatureBatch:
        q = Query(data.sft.name, cql_filter, hints=QueryHints(sampling=n))
        return data.get_features(q).features


class StatsProcess:
    """Evaluate a Stat DSL expression over matches (rides StatsScan)."""

    name = "StatsProcess"

    def execute(self, data: FeatureSource, stats: str, cql_filter: str = "INCLUDE"):
        q = Query(data.sft.name, cql_filter, hints=QueryHints(stats_string=stats))
        return data.get_features(q).stats


class UniqueProcess:
    """Distinct values of an attribute with counts."""

    name = "UniqueProcess"

    def execute(
        self, data: FeatureSource, attribute: str, cql_filter: str = "INCLUDE"
    ) -> List[Tuple[str, int]]:
        q = Query(
            data.sft.name, cql_filter,
            hints=QueryHints(stats_string=f"Enumeration({attribute})"),
        )
        stats = data.get_features(q).stats
        counts = stats.stats[0].result()
        return sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))


class JoinProcess:
    """Attribute equi-join: enrich `left` with columns of `right` matched on
    left.attr == right.attr (first match wins, inner join)."""

    name = "JoinProcess"

    def execute(
        self,
        left: FeatureBatch,
        right: FeatureBatch,
        left_attr: str,
        right_attr: str,
        right_attributes: Optional[Sequence[str]] = None,
    ) -> FeatureBatch:
        lcol = left.columns[left_attr]
        rcol = right.columns[right_attr]
        lvals = lcol.decode() if isinstance(lcol, DictColumn) else np.asarray(lcol).tolist()
        rvals = rcol.decode() if isinstance(rcol, DictColumn) else np.asarray(rcol).tolist()
        lookup = {}
        for i, v in enumerate(rvals):
            if v is not None and v not in lookup:
                lookup[v] = i
        lidx, ridx = [], []
        for i, v in enumerate(lvals):
            j = lookup.get(v)
            if j is not None:
                lidx.append(i)
                ridx.append(j)
        lsel = left.select(np.asarray(lidx, np.int64))
        rsel = right.select(np.asarray(ridx, np.int64))
        cols = dict(lsel.columns)
        attrs = list(lsel.sft.attributes)
        names = set(lsel.sft.attribute_names)
        wanted = right_attributes or [
            a.name for a in right.sft.attributes if not a.is_geometry
        ]
        for aname in wanted:
            a = right.sft.attribute(aname)
            out = aname if aname not in names else f"right_{aname}"
            attrs.append(AttributeDescriptor(out, a.type, False, dict(a.options)))
            cols[out] = rsel.columns[aname]
        sft = SimpleFeatureType(f"{left.sft.name}_join", attrs, dict(lsel.sft.user_data))
        return FeatureBatch(sft, cols, lsel.fids, lsel.valid)


class Point2PointProcess:
    """Convert per-track point sequences into LineString tracks."""

    name = "Point2PointProcess"

    def execute(
        self, data: FeatureBatch, track_attr: str, dtg_attr: Optional[str] = None
    ) -> FeatureBatch:
        from geomesa_tpu.core.wkt import Geometry

        g = data.geometry
        d = data.columns[dtg_attr] if dtg_attr else data.dtg
        tcol = data.columns[track_attr]
        tracks = tcol.decode() if isinstance(tcol, DictColumn) else np.asarray(tcol).tolist()
        order = np.argsort(np.asarray(d), kind="stable") if d is not None else np.arange(len(data))
        by_track = {}
        for i in order:
            key = tracks[int(i)]
            if key is not None:
                by_track.setdefault(key, []).append(int(i))
        names, geoms = [], []
        for key, idxs in by_track.items():
            if len(idxs) < 2:
                continue
            pts = np.stack([np.asarray(g.x)[idxs], np.asarray(g.y)[idxs]], axis=1)
            names.append(str(key))
            geoms.append(Geometry("LineString", [pts]))
        sft = SimpleFeatureType(
            f"{data.sft.name}_tracks",
            [
                AttributeDescriptor("track", "String"),
                AttributeDescriptor("geom", "LineString", True),
            ],
        )
        return FeatureBatch(
            sft,
            {
                "track": DictColumn.encode(names),
                "geom": GeometryColumn.from_geometries(geoms),
            },
        )


class DateOffsetProcess:
    """Shift a date attribute by a fixed offset (upstream utility)."""

    name = "DateOffsetProcess"

    def execute(self, data: FeatureBatch, dtg_attr: str, offset_ms: int) -> FeatureBatch:
        cols = dict(data.columns)
        cols[dtg_attr] = np.asarray(cols[dtg_attr], np.int64) + int(offset_ms)
        return FeatureBatch(data.sft, cols, data.fids, data.valid)


class HashAttributeProcess:
    """Add a stable int hash (mod `modulo`) of an attribute — upstream's
    HashAttribute(Color)Process used for stable symbology binning."""

    name = "HashAttributeProcess"

    def execute(self, data: FeatureBatch, attr: str, modulo: int = 256) -> FeatureBatch:
        col = data.columns[attr]
        vals = col.decode() if isinstance(col, DictColumn) else np.asarray(col).tolist()
        h = np.array(
            [
                int.from_bytes(
                    hashlib.blake2b(str(v).encode(), digest_size=4).digest(), "big"
                ) % modulo if v is not None else -1
                for v in vals
            ],
            np.int32,
        )
        attrs = list(data.sft.attributes) + [AttributeDescriptor("hash", "Integer")]
        sft = SimpleFeatureType(data.sft.name, attrs, dict(data.sft.user_data))
        cols = dict(data.columns)
        cols["hash"] = h
        return FeatureBatch(sft, cols, data.fids, data.valid)


class RouteSearchProcess:
    """Features along a route whose heading matches the route direction.

    Parity: RouteSearchProcess [L in the survey]: DWITHIN of the route line
    AND |heading - route bearing at nearest segment| <= tolerance.
    """

    name = "RouteSearchProcess"

    def execute(
        self,
        data: FeatureBatch,
        route_wkt: str,
        buffer_m: float,
        heading_attr: str,
        heading_tolerance_deg: float = 45.0,
        bidirectional: bool = False,
    ) -> FeatureBatch:
        import jax.numpy as jnp

        from geomesa_tpu.core.wkt import parse_wkt
        from geomesa_tpu.engine.pip import polygon_edges

        route = parse_wkt(route_wkt)
        x1, y1, x2, y2 = polygon_edges(route)
        g = data.geometry
        px, py = np.asarray(g.x), np.asarray(g.y)
        # nearest segment + distance (host numpy: routes are small)
        deg_m = 111_194.9
        coslat = np.cos(np.radians(py))[:, None]
        ax = (x1[None, :] - px[:, None]) * deg_m * coslat
        ay = (y1[None, :] - py[:, None]) * deg_m
        bx = (x2[None, :] - px[:, None]) * deg_m * coslat
        by = (y2[None, :] - py[:, None]) * deg_m
        dx, dy = bx - ax, by - ay
        L2 = np.maximum(dx * dx + dy * dy, 1e-12)
        t = np.clip(-(ax * dx + ay * dy) / L2, 0, 1)
        cx, cy = ax + t * dx, ay + t * dy
        dist = np.sqrt(cx * cx + cy * cy)
        seg = np.argmin(dist, axis=1)
        near = dist[np.arange(len(px)), seg] <= buffer_m
        bearing = (np.degrees(np.arctan2(dx, dy)) % 360.0)[np.arange(len(px)), seg]
        heading = np.asarray(data.columns[heading_attr], np.float64)
        diff = np.abs((heading - bearing + 180.0) % 360.0 - 180.0)
        if bidirectional:
            diff = np.minimum(diff, np.abs(diff - 180.0))
        ok = near & (diff <= heading_tolerance_deg)
        valid = data.valid if data.valid is not None else np.ones(len(data), bool)
        return data.select(ok & valid)


class ArrowConversionProcess:
    """Encode matching features as Arrow IPC bytes."""

    name = "ArrowConversionProcess"

    def execute(self, data: FeatureSource, cql_filter: str = "INCLUDE") -> bytes:
        import io

        import pyarrow as pa

        from geomesa_tpu.core.arrow_io import arrow_schema, to_arrow

        r = data.get_features(Query(data.sft.name, cql_filter))
        if r.features is None or len(r.features) == 0:
            return b""
        rb = to_arrow(r.features)
        sink = io.BytesIO()
        with pa.ipc.new_stream(sink, rb.schema) as w:
            w.write_batch(rb)
        return sink.getvalue()


class BinConversionProcess:
    """Encode matching features as BIN records."""

    name = "BinConversionProcess"

    def execute(
        self, data: FeatureSource, track_attr: str, cql_filter: str = "INCLUDE"
    ) -> bytes:
        q = Query(
            data.sft.name, cql_filter, hints=QueryHints(bin_track=track_attr)
        )
        return data.get_features(q).bin_bytes
