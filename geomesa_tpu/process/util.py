"""Shared process helpers: window queries and batch-side filtering."""

from __future__ import annotations

from typing import Optional

import numpy as np

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.wkt import box
from geomesa_tpu.cql import ast, parse_cql
from geomesa_tpu.cql.extract import BBox
from geomesa_tpu.plan.query import Query


def filter_batch(batch: FeatureBatch, cql_filter: str) -> FeatureBatch:
    """Apply an ECQL filter to an in-memory batch (device mask + select)."""
    f = parse_cql(cql_filter)
    if isinstance(f, ast.Include):
        return batch
    import jax.numpy as jnp

    from geomesa_tpu.cql import compile_filter
    from geomesa_tpu.engine.device import to_device

    compiled = compile_filter(f, batch.sft)
    dev = to_device(batch, coord_dtype=jnp.float64)
    return batch.select(np.asarray(compiled.mask(dev, batch)))


def window_filter(sft, bbox: BBox, cql_filter: str = "INCLUDE") -> ast.Filter:
    """BBOX window ANDed with an optional ECQL filter, as an AST (shared
    by the materializing window_query and the planner kNN push-down)."""
    g = sft.default_geometry
    window = ast.SpatialPredicate(
        "BBOX", ast.Property(g.name),
        box(bbox.xmin, bbox.ymin, bbox.xmax, bbox.ymax),
    )
    base = parse_cql(cql_filter)
    return window if isinstance(base, ast.Include) else ast.And((window, base))


def window_query(
    source,  # FeatureSource
    bbox: BBox,
    cql_filter: str = "INCLUDE",
) -> Optional[FeatureBatch]:
    """BBOX-window query ANDed with an optional ECQL filter."""
    combined = window_filter(source.sft, bbox, cql_filter)
    return source.get_features(Query(source.sft.name, combined)).features


def candidates_for(
    data,  # FeatureSource | FeatureBatch
    bbox: BBox,
    cql_filter: str = "INCLUDE",
) -> Optional[FeatureBatch]:
    """Uniform candidate retrieval: window query for sources, filtered
    passthrough for materialized batches (the cql_filter applies in BOTH
    paths; the window does not constrain a materialized batch — the kernels
    are exact regardless)."""
    if isinstance(data, FeatureBatch):
        return filter_batch(data, cql_filter)
    return window_query(data, bbox, cql_filter)
