"""Analytics process library.

Parity: geomesa-process (geomesa-process-vector) [upstream, unverified] —
the WPS-exposed VectorProcess implementations, mirrored by name:

  KNearestNeighborSearchProcess, DensityProcess, TubeSelectProcess,
  ProximitySearchProcess, QueryProcess, SamplingProcess, StatsProcess,
  UniqueProcess, JoinProcess, Point2PointProcess, DateOffsetProcess,
  HashAttributeProcess, RouteSearchProcess, ArrowConversionProcess,
  BinConversionProcess

Each is a thin orchestration over plan/ + engine/ (SURVEY.md §7 step 7); the
registry maps process names to classes for CLI/WPS-style dispatch.
"""

from geomesa_tpu.process.knn import KNearestNeighborSearchProcess
from geomesa_tpu.process.density import DensityProcess
from geomesa_tpu.process.tube import (
    TubeSelectProcess,
    NoGapFill,
    LineGapFill,
    InterpolatedGapFill,
)
from geomesa_tpu.process.misc import (
    ArrowConversionProcess,
    BinConversionProcess,
    DateOffsetProcess,
    HashAttributeProcess,
    JoinProcess,
    Point2PointProcess,
    ProximitySearchProcess,
    QueryProcess,
    RouteSearchProcess,
    SamplingProcess,
    StatsProcess,
    UniqueProcess,
)

REGISTRY = {
    c.__name__: c
    for c in (
        KNearestNeighborSearchProcess, DensityProcess, TubeSelectProcess,
        ProximitySearchProcess, QueryProcess, SamplingProcess, StatsProcess,
        UniqueProcess, JoinProcess, Point2PointProcess, DateOffsetProcess,
        HashAttributeProcess, RouteSearchProcess, ArrowConversionProcess,
        BinConversionProcess,
    )
}

__all__ = list(REGISTRY) + ["REGISTRY", "NoGapFill", "LineGapFill", "InterpolatedGapFill"]
