"""TubeSelectProcess and tube builders.

Parity: geomesa-process tube/ (TubeSelectProcess, TubeBuilder: NoGapFill,
LineGapFill, InterpolatedGapFill) [upstream, unverified]. The builders turn
an input track (points with times) into tube samples host-side; the match
against the target layer runs as ONE fused device kernel (engine.tube)
instead of the reference's per-segment store queries (SURVEY.md C17).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.engine.geodesy import haversine_m_np
from geomesa_tpu.plan.datastore import FeatureSource
from geomesa_tpu.cql.extract import BBox


@dataclasses.dataclass
class Tube:
    x: np.ndarray
    y: np.ndarray
    t: np.ndarray  # epoch millis
    radius_m: float
    half_window_ms: int


class TubeBuilder:
    def build(
        self, track: FeatureBatch, radius_m: float, half_window_ms: int
    ) -> Tube:
        x, y, t = _track_arrays(track)
        return Tube(*self._samples(x, y, t), radius_m, half_window_ms)

    def _samples(self, x, y, t):
        raise NotImplementedError


class NoGapFill(TubeBuilder):
    """Buffer each input point with its own time (no interpolation)."""

    def _samples(self, x, y, t):
        return x, y, t


class LineGapFill(TubeBuilder):
    """Interpolate positions along lines between consecutive points; time
    takes the segment midpointwise linear interpolation too (upstream
    LineGapFill interpolates the geometry; sample spacing here is bounded
    by `max_sample_m`)."""

    def __init__(self, max_sample_m: float = 10_000.0):
        self.max_sample_m = max_sample_m

    def _samples(self, x, y, t):
        xs, ys, ts = [x[:1]], [y[:1]], [t[:1]]
        for i in range(len(x) - 1):
            d = float(haversine_m_np(x[i], y[i], x[i + 1], y[i + 1]))
            n = max(1, int(np.ceil(d / self.max_sample_m)))
            frac = np.linspace(0.0, 1.0, n + 1)[1:]
            xs.append(x[i] + frac * (x[i + 1] - x[i]))
            ys.append(y[i] + frac * (y[i + 1] - y[i]))
            ts.append((t[i] + frac * (t[i + 1] - t[i])).astype(np.int64))
        return np.concatenate(xs), np.concatenate(ys), np.concatenate(ts)


class InterpolatedGapFill(LineGapFill):
    """Same sampling; kept as a distinct name for parity with the upstream
    variant (which additionally smooths headings)."""


class TubeSelectProcess:
    name = "TubeSelectProcess"

    def execute(
        self,
        tube_features: FeatureBatch,
        data: "FeatureSource | FeatureBatch",
        fill: Optional[TubeBuilder] = None,
        buffer_m: float = 10_000.0,
        max_time_window_ms: int = 3_600_000,
        cql_filter: str = "INCLUDE",
    ) -> FeatureBatch:
        import jax.numpy as jnp

        from geomesa_tpu.engine.device import to_device
        from geomesa_tpu.engine.tube import tube_select_pruned

        from geomesa_tpu.process.util import candidates_for

        fill = fill or NoGapFill()
        tube = fill.build(tube_features, buffer_m, max_time_window_ms)
        bbox = BBox(
            float(tube.x.min()), float(tube.y.min()),
            float(tube.x.max()), float(tube.y.max()),
        ).buffer_degrees(buffer_m)
        candidates = candidates_for(data, bbox, cql_filter)
        if candidates is None or len(candidates) == 0:
            return tube_features.select(np.zeros(0, np.int64))

        dev = to_device(candidates, coord_dtype=jnp.float64)
        g = candidates.sft.default_geometry
        d = candidates.sft.default_dtg
        # tile-pruned corridor join (round 4): data tiles outside the
        # corridor's per-segment reach are never scanned; exact for any
        # order, fast when candidates arrive store(Z)-ordered
        mask, _cap = tube_select_pruned(
            dev[f"{g.name}__x"],
            dev[f"{g.name}__y"],
            dev[d.name],
            dev["__valid__"],
            jnp.asarray(tube.x),
            jnp.asarray(tube.y),
            jnp.asarray(tube.t),
            tube.radius_m,
            tube.half_window_ms,
        )
        return candidates.select(np.asarray(mask))


def _track_arrays(track: FeatureBatch):
    g = track.geometry
    d = track.dtg
    if d is None:
        raise ValueError("tube features need a date attribute")
    order = np.argsort(np.asarray(d))
    return (
        np.asarray(g.x)[order],
        np.asarray(g.y)[order],
        np.asarray(d)[order],
    )
