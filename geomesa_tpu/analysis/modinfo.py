"""Per-module AST index for gmtpu-lint.

One parse per file, shared by every rule: alias resolution (``jax``,
``jnp``, ``np``, ``time``, ``functools.partial``), the module's jitted
definitions (decorated functions, ``x = jax.jit(fn, ...)`` assignments,
``self.attr = jax.jit(...)``), parent links for lexical-scope questions
(is this call inside a ``for``?), and the ``# gt:`` waiver-comment map.

The index is deliberately name-based rather than import-graph-exact:
cross-module questions (GT05 liveness, GT04 device calls) match on bare
identifier names across the scanned universe. That trades a little
precision for zero import-time side effects — the linter never imports
the code it analyzes.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

_GT_DIRECTIVE = re.compile(r"#\s*gt:\s*(?P<body>.+)$")


@dataclass
class ClassInfo:
    """Per-class concurrency index for the GT07..GT12 rules: which
    attributes are locks, which conditions wrap which lock, what type
    each `self.x = ClassName(...)` field has, the method table, and the
    thread entry points (`threading.Thread(target=self.m)`) the class
    itself creates."""

    name: str
    node: "ast.ClassDef"
    line: int
    lock_attrs: Set[str] = field(default_factory=set)
    cond_attrs: Dict[str, str] = field(default_factory=dict)  # cond -> lock
    field_types: Dict[str, str] = field(default_factory=dict)
    methods: Dict[str, "ast.FunctionDef"] = field(default_factory=dict)
    thread_targets: Set[str] = field(default_factory=set)  # own methods


@dataclass
class JitDef:
    """A jitted callable defined in this module."""

    name: str                    # bound name: function name or attr name
    kind: str                    # "function" | "alias" | "attr"
    line: int
    static_names: Set[str] = field(default_factory=set)
    static_nums: Set[int] = field(default_factory=set)
    func: Optional[ast.FunctionDef] = None  # wrapped body, when resolvable
    params: Tuple[str, ...] = ()

    def static_params(self) -> Set[str]:
        out = set(self.static_names)
        for i in self.static_nums:
            if 0 <= i < len(self.params):
                out.add(self.params[i])
        return out


class ModInfo:
    """Parsed module + the indexes every rule consumes."""

    def __init__(self, path: str, source: str, relpath: str = ""):
        self.path = path
        self.relpath = relpath or path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._link_parents()
        # alias sets, each holding local names that mean the thing
        self.jax_aliases: Set[str] = set()
        self.jit_aliases: Set[str] = set()       # bare `jit` refs
        self.partial_aliases: Set[str] = set()   # bare `partial` refs
        self.functools_aliases: Set[str] = set()
        self.numpy_aliases: Set[str] = set()     # host numpy
        self.jnp_aliases: Set[str] = set()       # jax.numpy (device-safe)
        self.time_aliases: Set[str] = set()
        self.time_fn_aliases: Set[str] = set()   # bare perf_counter/time refs
        self.threading_aliases: Set[str] = set()
        self.lock_fn_aliases: Set[str] = set()   # bare Lock/RLock refs
        self.cond_fn_aliases: Set[str] = set()   # bare Condition refs
        self.thread_fn_aliases: Set[str] = set()  # bare Thread refs
        self._collect_aliases()
        self.functions: Dict[str, ast.FunctionDef] = {}
        self._collect_functions()
        self.jit_defs: List[JitDef] = []
        self._collect_jit_defs()
        # concurrency indexes (rules GT07..GT12)
        self.classes: Dict[str, ClassInfo] = {}
        self._collect_classes()
        self.locking_decorators: Dict[str, str] = {}  # name -> lock attr
        self._collect_locking_decorators()
        self.thread_targets: List[Tuple[Optional[str], str]] = []
        self._collect_thread_targets()
        self.waivers: Dict[int, Set[str]] = {}
        self._collect_waivers()

    # -- structure ---------------------------------------------------------

    def _link_parents(self) -> None:
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._gt_parent = node  # type: ignore[attr-defined]

    @staticmethod
    def parent(node: ast.AST) -> Optional[ast.AST]:
        return getattr(node, "_gt_parent", None)

    def ancestors(self, node: ast.AST):
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.FunctionDef]:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    # -- aliases -----------------------------------------------------------

    def _collect_aliases(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    bound = a.asname or a.name.split(".")[0]
                    if a.name == "jax":
                        self.jax_aliases.add(bound)
                    elif a.name in ("jax.numpy",):
                        self.jnp_aliases.add(a.asname or "jnp")
                    elif a.name == "numpy":
                        self.numpy_aliases.add(bound)
                    elif a.name == "functools":
                        self.functools_aliases.add(bound)
                    elif a.name == "time":
                        self.time_aliases.add(bound)
                    elif a.name == "threading":
                        self.threading_aliases.add(bound)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for a in node.names:
                    bound = a.asname or a.name
                    if mod == "jax" and a.name == "jit":
                        self.jit_aliases.add(bound)
                    elif mod == "jax" and a.name == "numpy":
                        self.jnp_aliases.add(bound)
                    elif mod == "functools" and a.name == "partial":
                        self.partial_aliases.add(bound)
                    elif mod == "time" and a.name in ("perf_counter", "time",
                                                      "monotonic"):
                        self.time_fn_aliases.add(bound)
                    elif mod == "threading" and a.name in ("Lock", "RLock"):
                        self.lock_fn_aliases.add(bound)
                    elif mod == "threading" and a.name == "Condition":
                        self.cond_fn_aliases.add(bound)
                    elif mod == "threading" and a.name == "Thread":
                        self.thread_fn_aliases.add(bound)

    # -- expression classifiers -------------------------------------------

    def is_jit_ref(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.jit_aliases
        if isinstance(node, ast.Attribute) and node.attr == "jit":
            return (isinstance(node.value, ast.Name)
                    and node.value.id in self.jax_aliases)
        return False

    def is_partial_ref(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.partial_aliases
        if isinstance(node, ast.Attribute) and node.attr == "partial":
            return (isinstance(node.value, ast.Name)
                    and node.value.id in self.functools_aliases)
        return False

    def is_numpy_ref(self, node: ast.AST) -> bool:
        return (isinstance(node, ast.Name)
                and node.id in self.numpy_aliases)

    def is_jnp_ref(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Name) and node.id in self.jnp_aliases

    def is_timer_call(self, node: ast.Call) -> bool:
        f = node.func
        if isinstance(f, ast.Name):
            return f.id in self.time_fn_aliases
        if isinstance(f, ast.Attribute):
            return (f.attr in ("perf_counter", "time", "monotonic")
                    and isinstance(f.value, ast.Name)
                    and f.value.id in self.time_aliases)
        return False

    def _threading_attr(self, node: ast.AST, names: Tuple[str, ...],
                        bare: Set[str]) -> bool:
        """True when `node` is `threading.<name>` (via any alias) or a
        bare imported name from `bare`."""
        if isinstance(node, ast.Name):
            return node.id in bare
        if isinstance(node, ast.Attribute) and node.attr in names:
            return (isinstance(node.value, ast.Name)
                    and node.value.id in self.threading_aliases)
        return False

    def is_lock_ctor(self, node: ast.AST) -> bool:
        """`threading.Lock()` / `threading.RLock()` (or imported names)."""
        return (isinstance(node, ast.Call)
                and self._threading_attr(node.func, ("Lock", "RLock"),
                                         self.lock_fn_aliases))

    def is_condition_ctor(self, node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and self._threading_attr(node.func, ("Condition",),
                                         self.cond_fn_aliases))

    def is_thread_ctor(self, node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and self._threading_attr(node.func, ("Thread",),
                                         self.thread_fn_aliases))

    # -- functions ---------------------------------------------------------

    def _collect_functions(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.FunctionDef):
                # last definition wins; good enough for lookup of the
                # local fn handed to jax.jit a few lines below its def
                self.functions[node.name] = node

    @staticmethod
    def func_params(fn: ast.FunctionDef) -> Tuple[str, ...]:
        names = [a.arg for a in fn.args.posonlyargs]
        names += [a.arg for a in fn.args.args]
        return tuple(names)

    # -- jit defs ----------------------------------------------------------

    @staticmethod
    def _const_strs(node: ast.AST) -> Set[str]:
        out: Set[str] = set()
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.add(node.value)
        elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for e in node.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    out.add(e.value)
        return out

    @staticmethod
    def _const_ints(node: ast.AST) -> Set[int]:
        out: Set[int] = set()
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            out.add(node.value)
        elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for e in node.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.add(e.value)
        return out

    def _statics_from_keywords(self, keywords) -> Tuple[Set[str], Set[int]]:
        names: Set[str] = set()
        nums: Set[int] = set()
        for kw in keywords or ():
            if kw.arg == "static_argnames":
                names |= self._const_strs(kw.value)
            elif kw.arg == "static_argnums":
                nums |= self._const_ints(kw.value)
        return names, nums

    def _jit_call_parts(self, call: ast.Call):
        """If `call` is jax.jit(fn, ...) return (fn_node, statics) else
        None. Handles `jit(fn)`, `jax.jit(fn, static_*=...)`."""
        if not self.is_jit_ref(call.func):
            return None
        fn_node = call.args[0] if call.args else None
        names, nums = self._statics_from_keywords(call.keywords)
        return fn_node, names, nums

    def _collect_jit_defs(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.FunctionDef):
                self._jit_from_decorators(node)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                self._jit_from_assign(node)

    def _jit_from_decorators(self, fn: ast.FunctionDef) -> None:
        for dec in fn.decorator_list:
            names: Set[str] = set()
            nums: Set[int] = set()
            hit = False
            if self.is_jit_ref(dec):
                hit = True
            elif isinstance(dec, ast.Call):
                if self.is_jit_ref(dec.func):  # @jax.jit(donate_argnums=..)
                    hit = True
                    names, nums = self._statics_from_keywords(dec.keywords)
                elif (self.is_partial_ref(dec.func) and dec.args
                      and self.is_jit_ref(dec.args[0])):
                    hit = True
                    names, nums = self._statics_from_keywords(dec.keywords)
            if hit:
                self.jit_defs.append(JitDef(
                    name=fn.name, kind="function", line=fn.lineno,
                    static_names=names, static_nums=nums, func=fn,
                    params=self.func_params(fn),
                ))
                return

    def _jit_from_assign(self, node: ast.Assign) -> None:
        if not isinstance(node.value, ast.Call):
            return
        parts = self._jit_call_parts(node.value)
        if parts is None:
            return
        fn_node, names, nums = parts
        target = node.targets[0]
        if isinstance(target, ast.Name):
            bound, kind = target.id, "alias"
        elif isinstance(target, ast.Attribute):
            bound, kind = target.attr, "attr"
        else:
            return
        func = None
        if isinstance(fn_node, ast.Name):
            func = self.functions.get(fn_node.id)
        jd = JitDef(name=bound, kind=kind, line=node.lineno,
                    static_names=names, static_nums=nums, func=func,
                    params=self.func_params(func) if func else ())
        self.jit_defs.append(jd)

    # -- concurrency indexes (GT07..GT12) ----------------------------------

    @staticmethod
    def _self_attr_name(node: ast.AST) -> Optional[str]:
        """`self.X` -> "X"."""
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        return None

    def _collect_classes(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            ci = ClassInfo(name=node.name, node=node, line=node.lineno)
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    ci.methods[item.name] = item
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    attr = self._self_attr_name(sub.targets[0])
                    if attr is None:
                        continue
                    if self.is_lock_ctor(sub.value):
                        ci.lock_attrs.add(attr)
                    elif self.is_condition_ctor(sub.value):
                        # Condition(self._lock) shares the lock's identity;
                        # bare Condition() owns a fresh (R)Lock
                        args = sub.value.args
                        tied = (self._self_attr_name(args[0])
                                if args else None)
                        ci.cond_attrs[attr] = tied or attr
                        if tied is None:
                            ci.lock_attrs.add(attr)
                    elif (isinstance(sub.value, ast.Call)
                          and isinstance(sub.value.func, ast.Name)
                          and sub.value.func.id not in (
                              "dict", "list", "set", "tuple", "deque",
                              "defaultdict", "OrderedDict", "Counter")):
                        ci.field_types[attr] = sub.value.func.id
                elif isinstance(sub, ast.Call) and self.is_thread_ctor(sub):
                    for kw in sub.keywords:
                        if kw.arg == "target":
                            t = self._self_attr_name(kw.value)
                            if t is not None:
                                ci.thread_targets.add(t)
            # conditions tied to an owned lock also guard it when held
            self.classes[node.name] = ci

    def _collect_locking_decorators(self) -> None:
        """A module-level `def _locked(fn)` whose nested wrapper body is
        `with self.<attr>: ...` is a locking decorator: methods carrying
        it are fully guarded by that lock attribute (the stats-manager /
        device-cache idiom)."""
        for node in self.tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            for sub in ast.walk(node):
                if not isinstance(sub, ast.With):
                    continue
                for item in sub.items:
                    attr = self._self_attr_name(item.context_expr)
                    if attr is not None:
                        self.locking_decorators[node.name] = attr
                        break

    def _collect_thread_targets(self) -> None:
        """(owning class or None, callable name) for every thread entry
        point created in this module: `Thread(target=f)`, Thread(target=
        self.m), and `pool.submit(f, ...)` / `pool.map(f, ...)` on
        executor-ish receivers."""
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            owner = None
            for anc in self.ancestors(node):
                if isinstance(anc, ast.ClassDef):
                    owner = anc.name
                    break
            if self.is_thread_ctor(node):
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    t = self._self_attr_name(kw.value)
                    if t is None and isinstance(kw.value, ast.Name):
                        t = kw.value.id
                    if t is not None:
                        self.thread_targets.append((owner, t))
            elif (isinstance(node.func, ast.Attribute)
                  and node.func.attr in ("submit", "map")
                  and isinstance(node.func.value, ast.Name)
                  and (node.func.value.id == "ex"
                       or any(s in node.func.value.id.lower()
                              for s in ("pool", "executor")))
                  and node.args):
                a = node.args[0]
                t = self._self_attr_name(a)
                if t is None and isinstance(a, ast.Name):
                    t = a.id
                if t is not None:
                    self.thread_targets.append((owner, t))

    # -- waiver comments ---------------------------------------------------

    def _collect_waivers(self) -> None:
        raw: Dict[int, Set[str]] = {}
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.source).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _GT_DIRECTIVE.search(tok.string)
                if not m:
                    continue
                items = {t.strip() for t in m.group("body").split(",")}
                raw.setdefault(tok.start[0], set()).update(
                    t for t in items if t)
        except tokenize.TokenError:
            pass
        # a directive on a comment-only line also covers the next code
        # line, cascading past further comment/blank lines and through
        # decorators (findings on a decorated def anchor at the `def`)
        self.waivers = {ln: set(ts) for ln, ts in raw.items()}
        for ln in sorted(raw):
            stripped = self.lines[ln - 1].lstrip() if ln <= len(self.lines) \
                else ""
            if not stripped.startswith("#"):
                continue  # inline directive: covers its own line only
            nxt = ln + 1
            while nxt <= len(self.lines):
                s = self.lines[nxt - 1].strip()
                self.waivers.setdefault(nxt, set()).update(raw[ln])
                if s and not s.startswith("#") and not s.startswith("@"):
                    break
                nxt += 1

    def waiver_tokens(self, line: int) -> Set[str]:
        return self.waivers.get(line, set())

    def is_waived(self, rule: str, line: int) -> bool:
        toks = self.waiver_tokens(line)
        if f"waive {rule}" in toks or "waive all" in toks:
            return True
        # rule-specific spellings
        if rule == "GT03" and "f64-refine" in toks:
            return True
        return False
