"""Runtime lockset harness: Eraser-style race detection for the serve path.

The static pass (rules GT07..GT12) reasons about what the AST shows;
this module watches what actually happens at runtime:

- `TrackedLock` wraps a real `threading.Lock`/`RLock` and records every
  acquisition into a per-thread held-stack plus a global lock-ORDER
  graph (lock A held while acquiring lock B adds edge A->B, keyed by
  each lock's creation site). An edge pair (A->B, B->A) is a lock-order
  inversion — the runtime analog of rule GT08.
- `note_access(key, write=)` implements the Eraser lockset refinement
  (Savage et al. 1997): the candidate lockset of `key` is the
  intersection of tracked locks held across all accesses; a key touched
  by >= 2 threads with >= 1 write whose candidate set is empty is a
  data-race report — the runtime analog of GT07/GT12.
- `trace_locks()` patches `threading.Lock`/`RLock` so every lock
  CREATED inside the context is tracked (existing locks are not).
  `gmtpu guard --races script.py` runs a whole script under it and
  exits nonzero on violations; the serve soak tests run their
  QueryService/DataStore construction inside the context so all serving
  locks are watched.

Caveats (documented in docs/ANALYSIS.md): locks are aggregated by
creation site, so two instances of one class share a graph node — a
site-level inversion can in principle be two disjoint instances; read
the stacks in the report before acting. Same-site self-edges are
ignored for that reason.
"""

from __future__ import annotations

import contextlib
import threading
import traceback
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock


_SKIP_BASENAMES = ("locksets.py", "threading.py")


def _creation_site() -> str:
    """file:line of the frame that created the lock, skipping this
    module and threading.py by exact BASENAME (a substring match would
    also skip e.g. test_analysis_locksets.py and collapse every lock in
    it onto one graph node)."""
    import os

    for frame in reversed(traceback.extract_stack()[:-2]):
        if os.path.basename(frame.filename) not in _SKIP_BASENAMES:
            return f"{frame.filename}:{frame.lineno}"
    return "<unknown>"


@dataclass
class OrderEdge:
    held: str
    acquired: str
    thread: str
    stack: str


@dataclass
class AccessState:
    threads: Set[str] = field(default_factory=set)
    writes: int = 0
    lockset: Optional[Set[str]] = None  # None until first access
    first_empty_stack: Optional[str] = None


class LockWatch:
    """Registry shared by every TrackedLock of one tracing session."""

    def __init__(self):
        self._reglock = _REAL_LOCK()
        self._held = threading.local()
        self._tid_counter = 0
        self.edges: Dict[Tuple[str, str], OrderEdge] = {}
        self.accesses: Dict[object, AccessState] = {}
        self.created: int = 0

    def _tid(self) -> str:
        """Stable per-thread label. NOT threading.get_ident(): the OS
        recycles idents, so two sequential threads would alias into one
        and hide a two-thread race; NOT current_thread().name either —
        that can allocate a _DummyThread during thread bootstrap whose
        Event is built from the PATCHED lock class and recurses here."""
        tid = getattr(self._held, "tid", None)
        if tid is None:
            with self._reglock:
                self._tid_counter += 1
                tid = self._held.tid = f"t{self._tid_counter}"
        return tid

    # -- held-stack bookkeeping (called by TrackedLock) --------------------

    def _stack(self) -> List[Tuple["TrackedLock", int]]:
        st = getattr(self._held, "stack", None)
        if st is None:
            st = self._held.stack = []
        return st

    def push(self, lock: "TrackedLock") -> None:
        st = self._stack()
        for i, (held, depth) in enumerate(st):
            if held is lock:  # reentrant re-acquire: no new edges
                st[i] = (held, depth + 1)
                return
        tname = self._tid()
        with self._reglock:
            for held, _depth in st:
                if held.name != lock.name:
                    self.edges.setdefault(
                        (held.name, lock.name),
                        OrderEdge(held.name, lock.name, tname,
                                  "".join(traceback.format_stack(limit=8))))
        st.append((lock, 1))

    def pop(self, lock: "TrackedLock") -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            held, depth = st[i]
            if held is lock:
                if depth > 1:
                    st[i] = (held, depth - 1)
                else:
                    del st[i]
                return

    def held_names(self) -> Set[str]:
        return {lock.name for lock, _d in self._stack()}

    # -- Eraser lockset state machine --------------------------------------

    def note_access(self, key: object, write: bool = True) -> None:
        held = self.held_names()
        tname = self._tid()
        with self._reglock:
            st = self.accesses.setdefault(key, AccessState())
            st.threads.add(tname)
            if write:
                st.writes += 1
            if st.lockset is None:
                st.lockset = set(held)
            else:
                st.lockset &= held
            if not st.lockset and st.first_empty_stack is None \
                    and len(st.threads) >= 2:
                st.first_empty_stack = "".join(
                    traceback.format_stack(limit=8))

    # -- reporting ----------------------------------------------------------

    def inversions(self, path_filter: Optional[str] = None
                   ) -> List[Tuple[OrderEdge, OrderEdge]]:
        with self._reglock:
            edges = dict(self.edges)
        out = []
        for (a, b), e in sorted(edges.items()):
            if a < b and (b, a) in edges:
                rev = edges[(b, a)]
                if path_filter and not (
                        path_filter in a and path_filter in b):
                    continue
                out.append((e, rev))
        return out

    def races(self) -> List[Tuple[object, AccessState]]:
        with self._reglock:
            items = list(self.accesses.items())
        return [(k, st) for k, st in items
                if st.lockset is not None and not st.lockset
                and len(st.threads) >= 2 and st.writes > 0]

    def report(self, path_filter: Optional[str] = None) -> dict:
        inv = self.inversions(path_filter)
        races = self.races()
        return {
            "locks_created": self.created,
            "order_edges": len(self.edges),
            "inversions": [
                {"first": f"{e.held} -> {e.acquired} [{e.thread}]",
                 "second": f"{r.held} -> {r.acquired} [{r.thread}]",
                 "stack_first": e.stack, "stack_second": r.stack}
                for e, r in inv
            ],
            "races": [
                {"key": repr(k), "threads": sorted(st.threads),
                 "writes": st.writes,
                 "stack": st.first_empty_stack or ""}
                for k, st in races
            ],
            "violations": len(inv) + len(races),
        }


class TrackedLock:
    """A threading.Lock/RLock wrapper that reports to a LockWatch. Works
    as a `with` target, inside `threading.Condition`, and via the
    `_release_save`/`_acquire_restore` protocol for RLocks (so a
    Condition built on an RLock keeps the held-stack balanced through
    `wait()`)."""

    def __init__(self, inner, watch: LockWatch,
                 name: Optional[str] = None):
        self._inner = inner
        self._watch = watch
        self.name = name or _creation_site()
        with watch._reglock:
            watch.created += 1

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._watch.push(self)
        return got

    def release(self) -> None:
        self._inner.release()
        self._watch.pop(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        return locked() if locked is not None else False

    # Condition protocol (_release_save/_acquire_restore/_is_owned):
    # resolved via __getattr__ so `hasattr` mirrors the INNER lock —
    # threading.Condition feature-detects these, and advertising them
    # over a plain Lock (which lacks them) would break Event/Condition
    # fallback paths. The RLock variants keep the held-stack balanced
    # when wait() temporarily releases a reentrant lock.
    def __getattr__(self, name: str):
        if name in ("_inner", "_watch"):
            raise AttributeError(name)
        if name == "_release_save":
            inner_fn = getattr(self._inner, "_release_save")

            def _release_save():
                state = inner_fn()
                self._watch.pop(self)
                return state

            return _release_save
        if name == "_acquire_restore":
            inner_fn = getattr(self._inner, "_acquire_restore")

            def _acquire_restore(state):
                inner_fn(state)
                self._watch.push(self)

            return _acquire_restore
        if name == "_is_owned":
            return getattr(self._inner, "_is_owned")
        # anything else (e.g. _at_fork_reinit after an os.fork) resolves
        # against the inner lock, so hasattr() mirrors its capabilities
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return f"TrackedLock({self.name})"


_active_watch: Optional[LockWatch] = None


def tracked_lock(name: Optional[str] = None,
                 reentrant: bool = False,
                 watch: Optional[LockWatch] = None) -> TrackedLock:
    """An explicitly-instrumented lock for code that opts in directly
    (fixtures, tests). Outside a trace_locks() context it reports into a
    fresh private watch."""
    w = watch or _active_watch or LockWatch()
    inner = _REAL_RLOCK() if reentrant else _REAL_LOCK()
    return TrackedLock(inner, w, name=name)


def note_access(key: object, write: bool = True) -> None:
    """Record an access to shared state `key` under the currently-held
    tracked locks (no-op outside a tracing context)."""
    if _active_watch is not None:
        _active_watch.note_access(key, write=write)


@contextlib.contextmanager
def trace_locks():
    """Patch threading.Lock/RLock so locks created inside the context
    are tracked; yields the LockWatch. Locks created BEFORE entry stay
    untracked — construct the objects under test inside the context."""
    global _active_watch
    if _active_watch is not None:
        # nested tracing shares the outer watch (idempotent)
        yield _active_watch
        return
    watch = LockWatch()

    def make_lock():
        return TrackedLock(_REAL_LOCK(), watch)

    def make_rlock():
        return TrackedLock(_REAL_RLOCK(), watch)

    _active_watch = watch
    threading.Lock = make_lock          # type: ignore[assignment]
    threading.RLock = make_rlock        # type: ignore[assignment]
    try:
        yield watch
    finally:
        threading.Lock = _REAL_LOCK     # type: ignore[assignment]
        threading.RLock = _REAL_RLOCK   # type: ignore[assignment]
        _active_watch = None
