"""gmtpu-lint orchestration: scan -> index -> rules -> report.

`lint_paths` is the programmatic entry point (the CLI, the CI gate, and
the tests all go through it). The scan set is what gets linted; the
*reference universe* for cross-module questions (GT05 liveness, GT04/GT01
jit-name resolution) additionally pulls in every other .py file under the
repo root (pyproject.toml discoverable above the scan path) — a jitted
kernel linted in isolation whose callers live in `plan/`, `tests/` or
`bench.py` is an API, not a corpse.
"""

from __future__ import annotations

import ast
import json
import os
import sys
from typing import Dict, Iterable, List, Optional, Set

from geomesa_tpu.analysis.model import RULES, SEVERITIES, Finding
from geomesa_tpu.analysis.modinfo import JitDef, ModInfo
from geomesa_tpu.analysis.rules import ALL_RULES
from geomesa_tpu.analysis.waivers import (
    DEFAULT_WAIVER_FILENAME, apply_file_waivers, check_rule_code,
    load_waiver_file)


class Project:
    """The cross-module context handed to every rule."""

    def __init__(self, modules: List[ModInfo], ref_modules: List[ModInfo]):
        self.modules = modules
        self.ref_modules = ref_modules
        self.jit_by_name: Dict[str, JitDef] = {}
        for m in modules:
            for jd in m.jit_defs:
                self.jit_by_name.setdefault(jd.name, jd)
        names: Set[str] = set(self.jit_by_name)
        for m in modules:
            m._gt_project_jit_names = names  # type: ignore[attr-defined]
        self._refs: Optional[Dict[str, int]] = None

    # -- GT05 reference universe ------------------------------------------

    def reference_count(self, name: str) -> int:
        if self._refs is None:
            self._refs = self._count_references()
        return self._refs.get(name, 0)

    def _count_references(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        wanted = set(self.jit_by_name)
        for m in self.modules + self.ref_modules:
            for n, c in module_reference_counts(m, wanted).items():
                counts[n] = counts.get(n, 0) + c
        return counts


def module_reference_counts(m: ModInfo, wanted: Set[str]) -> Dict[str, int]:
    """Per-module reference counts for `wanted` names (the GT05 liveness
    universe). A module-scoped function (not a Project method) so the
    incremental engine can cache one count dict per file and rebuild the
    project total from cache for unchanged files."""
    counts: Dict[str, int] = {}

    def bump(n: str) -> None:
        if n in wanted:
            counts[n] = counts.get(n, 0) + 1

    for node in ast.walk(m.tree):
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load):
            bump(node.id)
        elif isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load):
            bump(node.attr)
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                bump(a.name)
        elif (isinstance(node, ast.Assign)
              and len(node.targets) == 1
              and isinstance(node.targets[0], ast.Name)
              and node.targets[0].id == "__all__"
              and isinstance(node.value, (ast.List, ast.Tuple))):
            for e in node.value.elts:
                if isinstance(e, ast.Constant) and isinstance(
                        e.value, str):
                    bump(e.value)
    # a jitted def's own wrapping (`x = jax.jit(_fn)`) loads `_fn`,
    # not `x`; decorated defs are not Name loads — no self-counts to
    # subtract for the bound names themselves
    return counts


def _iter_py_files(path: str) -> Iterable[str]:
    if os.path.isfile(path):
        if path.endswith(".py"):
            yield path
        return
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs
                         if d not in ("__pycache__", ".git"))
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(root, f)


def find_repo_root(start: str) -> Optional[str]:
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        if os.path.exists(os.path.join(cur, "pyproject.toml")):
            return cur
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return None
        cur = nxt


def _load_module(path: str, base: Optional[str]) -> Optional[ModInfo]:
    try:
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        rel = os.path.relpath(path, base) if base else path
        return ModInfo(path, src, relpath=rel.replace(os.sep, "/"))
    except (SyntaxError, UnicodeDecodeError, OSError):
        return None


def build_project(paths: List[str],
                  extra_ref_paths: Optional[List[str]] = None,
                  repo_root: Optional[str] = None) -> Project:
    if repo_root is None:
        repo_root = find_repo_root(paths[0]) if paths else None
    modules: List[ModInfo] = []
    seen: Set[str] = set()
    for p in paths:
        for f in _iter_py_files(p):
            af = os.path.abspath(f)
            if af in seen:
                continue
            seen.add(af)
            m = _load_module(f, repo_root)
            if m is not None:
                modules.append(m)
    ref_paths: List[str] = list(extra_ref_paths or ())
    if repo_root and extra_ref_paths is None:
        # the rest of the repo (scan set deduped below via `seen`):
        # subset scans must still see callers outside the subset
        ref_paths.append(repo_root)
    refs: List[ModInfo] = []
    for p in ref_paths:
        for f in _iter_py_files(p):
            af = os.path.abspath(f)
            if af in seen:
                continue
            seen.add(af)
            m = _load_module(f, repo_root)
            if m is not None:
                refs.append(m)
    return Project(modules, refs)


def lint_paths(paths: List[str],
               rules: Optional[List[str]] = None,
               waiver_file: Optional[str] = None,
               extra_ref_paths: Optional[List[str]] = None,
               include_waived: bool = True) -> List[Finding]:
    """Run the linter; returns findings sorted by (path, line, rule).
    Waived findings are included with .waived=True (the gate ignores
    them; --format json surfaces them for audit)."""
    project = build_project(paths, extra_ref_paths=extra_ref_paths)
    if not project.modules:
        # a CWD-relative default path from the wrong directory must not
        # read as a clean pass: zero coverage is an error, not a green
        raise FileNotFoundError(
            f"gmtpu-lint: no .py files found under {paths!r}")
    selected = rules or sorted(ALL_RULES)
    findings: List[Finding] = []
    for mod in project.modules:
        _check_inline_waiver_tokens(mod)
        for code in selected:
            for f in ALL_RULES[code](mod, project):
                if mod.is_waived(f.rule, f.line):
                    f.waived = True
                    f.waived_by = f"inline:{mod.relpath}:{f.line}"
                findings.append(f)
    finalize_findings(findings, paths, waiver_file)
    if not include_waived:
        findings = [f for f in findings if not f.waived]
    return findings


def resolve_waiver_file(paths: List[str],
                        waiver_file: Optional[str]) -> Optional[str]:
    """An explicit waiver file wins; otherwise the repo-root default, if
    present."""
    if waiver_file is None:
        root = find_repo_root(paths[0]) if paths else None
        cand = os.path.join(root, DEFAULT_WAIVER_FILENAME) if root else None
        if cand and os.path.exists(cand):
            waiver_file = cand
    return waiver_file


def finalize_findings(findings: List[Finding], paths: List[str],
                      waiver_file: Optional[str]) -> List[Finding]:
    """The post-merge tail of the pipeline: file waivers, severity
    overrides, canonical sort. In-place; shared by the cold scan and the
    incremental engine so both paths render byte-identically."""
    entries, severities = [], {}
    waiver_file = resolve_waiver_file(paths, waiver_file)
    if waiver_file:
        entries, severities = load_waiver_file(waiver_file)
    apply_file_waivers(findings, entries)
    for f in findings:
        f.severity = severities.get(
            f.rule, RULES[f.rule].severity if f.rule in RULES else f.severity)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def _check_inline_waiver_tokens(mod: ModInfo) -> None:
    """A `# gt: waive GT99` typo must error, not silently never match."""
    for line, tokens in sorted(mod.waivers.items()):
        for tok in tokens:
            if tok.startswith("waive "):
                code = tok.split(None, 1)[1].strip()
                if code == "all":
                    continue
                check_rule_code(code, f"{mod.relpath}:{line}")


def render_text(findings: List[Finding], show_waived: bool = False) -> str:
    """Pass the FULL findings list (waived included): the summary line
    discloses the waived count either way; `show_waived` only controls
    whether the waived findings' own lines print."""
    active = [f for f in findings if not f.waived]
    waived = len(findings) - len(active)
    lines = [f.render() for f in (findings if show_waived else active)]
    lines.append(
        f"gmtpu-lint: {len(active)} finding(s)"
        + (f", {waived} waived" if waived else ""))
    return "\n".join(lines)


def render_json(findings: List[Finding]) -> str:
    return json.dumps({
        "findings": [f.to_dict() for f in findings],
        "active": sum(1 for f in findings if not f.waived),
        "waived": sum(1 for f in findings if f.waived),
    }, indent=2)


_SARIF_LEVEL = {"info": "note", "warn": "warning", "error": "error"}


def render_sarif(findings: List[Finding]) -> str:
    """SARIF 2.1.0 for CI annotation surfaces (GitHub code scanning
    etc.). Waived findings are emitted with an inSource suppression so
    dashboards show the audit trail without failing the run."""
    rules = [
        {
            "id": code,
            "name": code,
            "shortDescription": {"text": RULES[code].title},
            "defaultConfiguration": {
                "level": _SARIF_LEVEL[RULES[code].severity]},
        }
        for code in sorted(RULES)
    ]
    results = []
    for f in findings:
        r = {
            "ruleId": f.rule,
            "level": _SARIF_LEVEL.get(f.severity, "warning"),
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(1, f.line),
                               "startColumn": f.col + 1},
                },
            }],
        }
        chain = (f.extra or {}).get("chain") or []
        if chain:
            # the provenance chain (dataflow rules): CI annotation
            # surfaces walk from the sink to the leak's origin
            r["relatedLocations"] = [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": str(step.get("path", f.path))},
                    "region": {
                        "startLine": max(1, int(step.get("line", 1)))},
                },
                "message": {"text": str(step.get("note", ""))},
            } for step in chain if isinstance(step, dict)]
        if f.waived:
            r["suppressions"] = [{
                "kind": "inSource",
                "justification": f.waived_by,
            }]
        results.append(r)
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "gmtpu-lint",
                "informationUri":
                    "https://example.invalid/geomesa-tpu/docs/ANALYSIS.md",
                "rules": rules,
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2)


def exit_code(findings: List[Finding], fail_on: str) -> int:
    if fail_on == "never":
        return 0
    threshold = SEVERITIES.index(fail_on)
    for f in findings:
        if f.waived:
            continue
        if SEVERITIES.index(f.severity) >= threshold:
            return 1
    return 0


def changed_paths(scan_paths: List[str], ref: str) -> List[str]:
    """Resolve `gmtpu lint --changed[=REF]` to the changed .py files
    inside the scan scope: `git diff --name-only REF` plus untracked
    files. The scan SET narrows; the reference universe does not —
    `build_project` still pulls the whole repo in as ref modules, so
    the universe-backed rules (GT05 liveness, GT13/GT30 registration
    keys) keep their full context and a narrowed run never invents a
    false finding from missing context. The caller-graph passes
    (GT24-GT29, GT31) resolve within the scan set — a changed-only run
    is a fast pre-commit filter; the CI gate lints the full tree."""
    import subprocess
    root = find_repo_root(scan_paths[0]) if scan_paths else None
    root = root or os.getcwd()
    # The empty-tree hash: what the default ref degrades to on a repo
    # whose HEAD is unborn (initial commit), so the pre-commit hook
    # sample works on the very first commit instead of dying on
    # `git diff HEAD`. An explicit bad REF still errors.
    _EMPTY_TREE = "4b825dc642cb6eb9a060e54bf8d69288fbee4904"
    try:
        if ref == "HEAD":
            head = subprocess.run(
                ["git", "-C", root, "rev-parse", "--verify", "-q", "HEAD"],
                capture_output=True, text=True, timeout=30)
            if head.returncode != 0:
                ref = _EMPTY_TREE
        diff = subprocess.run(
            ["git", "-C", root, "diff", "--name-only", ref, "--"],
            capture_output=True, text=True, timeout=30)
        untracked = subprocess.run(
            ["git", "-C", root, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise RuntimeError(f"gmtpu-lint: --changed needs git: {e}")
    if diff.returncode != 0:
        raise RuntimeError(
            f"gmtpu-lint: git diff --name-only {ref} failed: "
            f"{diff.stderr.strip()}")
    scopes = [os.path.abspath(p) for p in scan_paths]
    out: List[str] = []
    names = diff.stdout.splitlines() + untracked.stdout.splitlines()
    for name in sorted(set(names)):
        if not name.endswith(".py"):
            continue
        af = os.path.abspath(os.path.join(root, name))
        if not os.path.exists(af):
            continue  # deleted files have nothing to lint
        if any(af == sc or af.startswith(sc + os.sep) for sc in scopes):
            out.append(af)
    return out


def run_cli(args) -> int:
    """Shared by `gmtpu lint` and `python -m geomesa_tpu.analysis`."""
    rules = None
    if getattr(args, "rules", None):
        rules = sorted({r.strip().upper() for r in args.rules.split(",")})
        unknown = [r for r in rules if r not in ALL_RULES]
        if unknown:
            raise ValueError(f"unknown rule(s): {', '.join(unknown)} "
                             f"(have {', '.join(sorted(ALL_RULES))})")
    if getattr(args, "spmd", False):
        # the SPMD pass subset (docs/ANALYSIS.md "Reading an SPMD
        # report"); composes with --rules as a union
        spmd_codes = [c for c in ("GT24", "GT25", "GT26", "GT27")
                      if c in ALL_RULES]
        rules = sorted(set(rules or []) | set(spmd_codes))
    if getattr(args, "dataflow", False):
        # the provenance dataflow pass subset (docs/ANALYSIS.md
        # "Reading a provenance report"); composes with --rules
        df_codes = [c for c in ("GT28", "GT29", "GT30", "GT31")
                    if c in ALL_RULES]
        rules = sorted(set(rules or []) | set(df_codes))
    paths = list(args.paths) or ["geomesa_tpu"]
    changed_ref = getattr(args, "changed", None)
    if changed_ref is not None:
        try:
            paths = changed_paths(paths, changed_ref)
        except RuntimeError as e:
            print(e, file=sys.stderr)
            return 2
        if not paths:
            print("gmtpu-lint: no changed .py files in scope",
                  file=sys.stderr)
            return 0
    lint_fn = lint_paths
    if getattr(args, "incremental", False):
        from geomesa_tpu.analysis.incremental import lint_paths_incremental
        lint_fn = lint_paths_incremental
    try:
        findings = lint_fn(
            paths,
            rules=rules,
            waiver_file=getattr(args, "waivers", None),
        )
    except (FileNotFoundError, ValueError) as e:
        # ValueError: malformed waiver file or a waiver naming an
        # unknown rule code — configuration errors exit 2, not a
        # silent green (or a traceback)
        print(e, file=sys.stderr)
        return 2
    fmt = getattr(args, "format", "text")
    if fmt == "json":
        print(render_json(findings))
    elif fmt == "sarif":
        print(render_sarif(findings))
    else:
        print(render_text(findings,
                          show_waived=getattr(args, "show_waived", False)))
    return exit_code(findings, getattr(args, "fail_on", "warn"))


def add_lint_arguments(p) -> None:
    p.add_argument("paths", nargs="*", default=["geomesa_tpu"],
                   help="files or directories to lint "
                        "(default: geomesa_tpu)")
    p.add_argument("--fail-on", dest="fail_on", default="warn",
                   choices=["never"] + list(SEVERITIES),
                   help="minimum severity that makes the exit code "
                        "nonzero (default: warn)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule codes to run "
                        "(default: all)")
    p.add_argument("--waivers", default=None,
                   help=f"waiver file (default: {DEFAULT_WAIVER_FILENAME} "
                        f"at the repo root, if present)")
    p.add_argument("--format", default="text",
                   choices=["text", "json", "sarif"],
                   help="output format (sarif: CI annotation surfaces)")
    p.add_argument("--show-waived", action="store_true",
                   help="include waived findings in text output")
    p.add_argument("--incremental", action="store_true",
                   help="use the content-hash lint cache "
                        "(.gmtpu-lintcache at the repo root): an "
                        "unchanged tree replays cached findings without "
                        "re-parsing; findings are byte-identical to a "
                        "cold scan")
    p.add_argument("--spmd", action="store_true",
                   help="run the interprocedural SPMD pass "
                        "(GT24-GT27; union with --rules)")
    p.add_argument("--dataflow", action="store_true",
                   help="run the interprocedural dtype/shape-"
                        "provenance pass (GT28-GT31; union with "
                        "--rules)")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="REF",
                   help="lint only files changed vs REF (git diff "
                        "--name-only, default HEAD) plus untracked "
                        "files; cross-file rules keep the whole-repo "
                        "reference universe")
