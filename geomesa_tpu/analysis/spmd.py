"""Interprocedural SPMD mesh-discipline pass: GT24..GT27.

The GT01..GT23 rules answer single-module questions (plus the lockset
harness's cross-module lock graph). The multi-host roadmap item
(jax.distributed + a host-spanning mesh) introduces a bug class none of
them can see: SPMD divergence. A collective issued under an axis name no
enclosing `shard_map`/`pjit` binds fails at trace time *on the path that
runs it* — which on a pod may be a path CPU CI never takes; two
processes branching differently into mismatched collective sequences
deadlock the whole pod silently; every host writing the same manifest
file corrupts shared state that single-process runs never contend on.

This pass builds a per-module *SPMD summary* (collective sites with
resolved axis names, shard_map/pjit wrap sites with their mesh axes and
spec shapes, Mesh constructions, process/env-conditioned branches,
persist-style side effects, and call/import edges), then a project-wide
index with a call graph over the summaries, and checks:

- **GT24** — a collective primitive (`psum`/`all_gather`/`ppermute`/
  `axis_index`/...) whose axis name is bound neither by an enclosing
  `shard_map`/`pjit`/`pmap` wrap nor by every calling context reaching
  the helper. `engine/knn_scan._shard_merge_topk` is the canonical safe
  shape: bare collectives in a module-level helper, every caller inside
  a wrapped body — the calling-context propagation keeps it clean.
- **GT25** — a branch conditioned on `jax.process_index()` /
  `jax.process_count()` / an `os.environ` read whose arms differ in
  collective-relevant effects (collectives issued directly or through
  callees, or `jax.config.update` mutations that change the compiled
  program), in a module reachable from a distributed entry point. The
  static pod-deadlock detector: CPU CI runs one process and can never
  take both sides.
- **GT26** — sharding-spec drift: `in_specs`/`out_specs`/
  `PartitionSpec`/`NamedSharding` naming a mesh axis the constructing
  mesh (or any mesh built in the project) does not define, or a literal
  `in_specs` tuple whose arity disagrees with the mapped function's
  positional parameters.
- **GT27** — a persist-style side effect (the tmp+`os.replace` atomic
  write idiom, port binds) on a multi-process-reachable path without a
  coordinator gate (`parallel.is_coordinator()` / `process_index()==0`):
  on a pod every host performs it against shared storage.

Summaries are plain-dict serializable (`ModuleSummary.to_dict` /
`from_dict`) so the incremental lint cache can persist them per file and
rebuild the cross-file index for unchanged files without re-walking
their ASTs (analysis/incremental.py).

Like every gmtpu-lint rule: pure AST, never imports the code under
analysis, and precision is a requirement — the gate runs --fail-on warn.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from geomesa_tpu.analysis.model import Finding
from geomesa_tpu.analysis.modinfo import ModInfo

# bump when the summary shape changes: cached summaries from an older
# engine must not feed the index (analysis/incremental.py keys on this)
SPMD_SCHEMA = 2

# jax.lax collective primitives and the argument position of axis_name
_COLLECTIVES: Dict[str, int] = {
    "psum": 1, "pmax": 1, "pmin": 1, "pmean": 1,
    "all_gather": 1, "ppermute": 1, "pshuffle": 1,
    "all_to_all": 1, "psum_scatter": 1,
    "axis_index": 0,
}

# callables that establish an axis-binding context for the mapped fn
_WRAPPERS = {"shard_map", "_shard_map", "pjit", "pmap"}

# project functions known to construct the default 1-D serving mesh are
# discovered from their own `Mesh(...)` returns; no hardcoded list here.

_PROCESS_READS = {"process_index", "process_count"}

_GT25_ENTRY_FILES = (
    "geomesa_tpu/parallel/launch.py",
    "geomesa_tpu/parallel/distributed.py",
)
_GT25_ENTRY_PREFIXES = ("geomesa_tpu/serve/",)

_GT27_PREFIXES = (
    "geomesa_tpu/parallel/", "geomesa_tpu/store/",
    "geomesa_tpu/compilecache/", "geomesa_tpu/serve/",
    "geomesa_tpu/telemetry/", "geomesa_tpu/approx/",
)

_GATE_TOKENS = {"is_coordinator", "process_index", "process_count"}


# ---------------------------------------------------------------------------
# per-module summary model (dict-serializable for the incremental cache)
# ---------------------------------------------------------------------------


@dataclass
class CollectiveSite:
    line: int
    col: int
    primitive: str
    axis: Optional[str]          # literal value, "ref:<mod>:<name>", None
    fn: str                      # enclosing function qname or "<module>"


@dataclass
class WrapSite:
    line: int
    mapped: Optional[str]        # qname of the mapped function, if known
    axes: Optional[List[str]]    # mesh axis names, None when unresolved
    spec_axes: List[Tuple[int, int, str]] = field(default_factory=list)
    in_arity: Optional[int] = None   # literal in_specs tuple length
    fn: str = "<module>"


@dataclass
class SpecSite:                  # bare NamedSharding(mesh, P(...)) sites
    line: int
    col: int
    axes: List[str]
    mesh_axes: Optional[List[str]]
    fn: str = "<module>"


@dataclass
class BranchSite:
    line: int
    col: int
    fn: str
    kind: str                    # "process" | "env"
    body_tokens: List[str]
    body_calls: List[str]
    orelse_tokens: List[str]
    orelse_calls: List[str]


@dataclass
class EffectSite:
    line: int
    col: int
    fn: str
    kind: str                    # "persist" | "bind"
    detail: str
    gated: bool


@dataclass
class FuncSummary:
    qname: str
    line: int
    params: List[str]
    has_vararg: bool
    bound_axes: List[str]        # axes bound over this function's body
    bound_unknown: bool          # wrapped, but mesh axes unresolvable
    calls: List[Tuple[str, bool]]    # (resolved callee, call-site gated)
    gate_entry: bool             # body opens with a coordinator guard


@dataclass
class ModuleSummary:
    schema: int
    relpath: str
    module: str                  # dotted name
    imports: List[str]           # project-internal dotted modules
    import_names: Dict[str, str]     # local name -> source dotted module
    axis_constants: Dict[str, str]   # NAME -> literal string value
    mesh_axes: List[List[str]]   # axis tuples of Mesh() constructions
    functions: Dict[str, FuncSummary]
    collectives: List[CollectiveSite]
    wraps: List[WrapSite]
    specs: List[SpecSite]
    branches: List[BranchSite]
    effects: List[EffectSite]

    def to_dict(self) -> dict:
        def enc(obj):
            if isinstance(obj, (CollectiveSite, WrapSite, SpecSite,
                                BranchSite, EffectSite, FuncSummary)):
                return {k: enc(v) for k, v in vars(obj).items()}
            if isinstance(obj, (list, tuple)):
                return [enc(v) for v in obj]
            if isinstance(obj, dict):
                return {k: enc(v) for k, v in obj.items()}
            return obj
        return enc(vars(self))

    @classmethod
    def from_dict(cls, d: dict) -> "ModuleSummary":
        d = dict(d)
        d["functions"] = {
            k: FuncSummary(**{**v, "calls": [tuple(c) for c in v["calls"]]})
            for k, v in d["functions"].items()}
        d["collectives"] = [CollectiveSite(**c) for c in d["collectives"]]
        d["wraps"] = [
            WrapSite(**{**w, "spec_axes": [tuple(s) for s in w["spec_axes"]]})
            for w in d["wraps"]]
        d["specs"] = [SpecSite(**s) for s in d["specs"]]
        d["branches"] = [BranchSite(**b) for b in d["branches"]]
        d["effects"] = [EffectSite(**e) for e in d["effects"]]
        return cls(**d)


# ---------------------------------------------------------------------------
# extraction helpers
# ---------------------------------------------------------------------------


def _dotted(node: ast.AST) -> Optional[str]:
    """`a.b.c` -> "a.b.c" for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _module_dotted(relpath: str) -> str:
    p = relpath.replace("\\", "/")
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


class _Extractor:
    """One walk over a ModInfo tree -> ModuleSummary."""

    def __init__(self, mod: ModInfo):
        self.mod = mod
        self.module = _module_dotted(mod.relpath)
        self.summary = ModuleSummary(
            schema=SPMD_SCHEMA, relpath=mod.relpath, module=self.module,
            imports=[], import_names={}, axis_constants={}, mesh_axes=[],
            functions={}, collectives=[], wraps=[], specs=[], branches=[],
            effects=[])
        self._qname_of: Dict[ast.AST, str] = {}
        self._class_of: Dict[ast.AST, str] = {}

    # -- name / axis resolution --------------------------------------------

    def _collect_imports(self) -> None:
        s = self.summary
        pkg_root = self.module.split(".")[0]
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name.split(".")[0] == pkg_root:
                        s.imports.append(a.name)
                        s.import_names[a.asname or a.name.split(".")[0]] = \
                            a.name
            elif isinstance(node, ast.ImportFrom):
                src = node.module or ""
                if node.level:
                    base = self.module.split(".")
                    if self.mod.relpath.endswith("__init__.py"):
                        base = base + [""]
                    base = base[: len(base) - node.level]
                    src = ".".join(base + ([src] if src else []))
                if src.split(".")[0] != pkg_root:
                    continue
                s.imports.append(src)
                for a in node.names:
                    s.import_names[a.asname or a.name] = src
                    # `from pkg import mod` pulls in pkg.mod when the
                    # name is a submodule; record the candidate edge —
                    # reachability ignores it if no such module exists
                    s.imports.append(f"{src}.{a.name}")
        s.imports = sorted(set(s.imports))

    def _collect_axis_constants(self) -> None:
        for node in self.mod.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                self.summary.axis_constants[node.targets[0].id] = \
                    node.value.value

    def _axis_value(self, node: ast.AST) -> Optional[str]:
        """A mesh-axis expression -> literal string, a cross-module
        "ref:<module>:<name>" marker, or None (unresolvable)."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            if node.id in self.summary.axis_constants:
                return self.summary.axis_constants[node.id]
            src = self.summary.import_names.get(node.id)
            if src:
                return f"ref:{src}:{node.id}"
        if isinstance(node, ast.Attribute):
            base = _terminal(node.value)
            src = self.summary.import_names.get(base or "")
            if src:
                return f"ref:{src}:{node.attr}"
        return None

    def _axes_tuple(self, node: ast.AST) -> Optional[List[str]]:
        if isinstance(node, (ast.Tuple, ast.List)):
            out = []
            for e in node.elts:
                v = self._axis_value(e)
                if v is None:
                    return None
                out.append(v)
            return out
        v = self._axis_value(node)
        return [v] if v is not None else None

    # -- function table -----------------------------------------------------

    def _collect_functions(self) -> None:
        def visit(node: ast.AST, prefix: str, cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    q = f"{prefix}{child.name}"
                    self._qname_of[child] = q
                    if cls:
                        self._class_of[child] = cls
                    a = child.args
                    params = [p.arg for p in a.posonlyargs + a.args]
                    if params and params[0] in ("self", "cls"):
                        params = params[1:]
                    self.summary.functions[q] = FuncSummary(
                        qname=q, line=child.lineno, params=params,
                        has_vararg=a.vararg is not None, bound_axes=[],
                        bound_unknown=False, calls=[], gate_entry=False)
                    visit(child, q + ".", cls)
                elif isinstance(child, ast.ClassDef):
                    visit(child, f"{child.name}.", child.name)
                else:
                    visit(child, prefix, cls)
        visit(self.mod.tree, "", None)

    def _enclosing_qname(self, node: ast.AST) -> str:
        for anc in self.mod.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return self._qname_of.get(anc, "<module>")
        return "<module>"

    def _resolve_callee(self, call: ast.Call) -> Optional[str]:
        """A call -> "<relpath-local qname>", "<module>:<name>" for a
        cross-module target, or None. Methods resolve `self.x()` to the
        enclosing class's `Cls.x`."""
        f = call.func
        if isinstance(f, ast.Name):
            # innermost local def shadowing wins; fall back to module fn
            for anc in self.mod.ancestors(call):
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    q = self._qname_of.get(anc)
                    if q and f"{q}.{f.id}" in self.summary.functions:
                        return f"{q}.{f.id}"
            if f.id in self.summary.functions:
                return f.id
            src = self.summary.import_names.get(f.id)
            if src:
                return f"{src}:{f.id}"
            return None
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name):
                if f.value.id == "self":
                    for anc in self.mod.ancestors(call):
                        if isinstance(anc, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            cls = self._class_of.get(anc)
                            if cls and f"{cls}.{f.attr}" in \
                                    self.summary.functions:
                                return f"{cls}.{f.attr}"
                    return None
                src = self.summary.import_names.get(f.value.id)
                if src:
                    return f"{src}:{f.attr}"
        return None

    # -- binding contexts ---------------------------------------------------

    def _wrapper_call(self, call: ast.Call) -> Optional[ast.Call]:
        """shard_map(f, ...) / partial(shard_map, ...) -> the call whose
        keywords carry mesh/in_specs/out_specs, else None."""
        name = _terminal(call.func)
        if name in _WRAPPERS:
            return call
        if self.mod.is_partial_ref(call.func) and call.args:
            if _terminal(call.args[0]) in _WRAPPERS:
                return call
        return None

    def _mesh_axes_of_expr(self, node: ast.AST,
                           scope: ast.AST) -> Optional[List[str]]:
        """Resolve a mesh expression to its axis-name tuple: a direct
        `Mesh(..., (axes,))` call, a call to a project constructor that
        returns one, or a local `mesh = <either>` assignment in scope."""
        if isinstance(node, ast.Call):
            if _terminal(node.func) == "Mesh":
                axes_arg = None
                if len(node.args) >= 2:
                    axes_arg = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        axes_arg = kw.value
                if axes_arg is not None:
                    return self._axes_tuple(axes_arg)
                return None
            callee = self._resolve_callee(node)
            if callee:
                return [f"ctor:{callee}"]
            return None
        if isinstance(node, ast.Name):
            for n in ast.walk(scope):
                if (isinstance(n, ast.Assign) and len(n.targets) == 1
                        and isinstance(n.targets[0], ast.Name)
                        and n.targets[0].id == node.id):
                    return self._mesh_axes_of_expr(n.value, scope)
        return None

    def _record_wrap(self, call: ast.Call, mapped: Optional[str],
                     scope: ast.AST, fn_q: str) -> WrapSite:
        axes: Optional[List[str]] = None
        spec_axes: List[Tuple[int, int, str]] = []
        in_arity: Optional[int] = None
        for kw in call.keywords:
            if kw.arg == "mesh":
                axes = self._mesh_axes_of_expr(kw.value, scope)
            elif kw.arg in ("in_specs", "out_specs"):
                node = kw.value
                if kw.arg == "in_specs" and isinstance(
                        node, (ast.Tuple, ast.List)):
                    in_arity = len(node.elts)
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) and _terminal(sub.func) in (
                            "P", "PartitionSpec"):
                        for a in sub.args:
                            v = self._axis_value(a)
                            if v is not None:
                                spec_axes.append(
                                    (sub.lineno, sub.col_offset, v))
        # pmap binds via axis_name=
        if _terminal(call.func) == "pmap":
            for kw in call.keywords:
                if kw.arg == "axis_name":
                    v = self._axis_value(kw.value)
                    axes = [v] if v is not None else None
        ws = WrapSite(line=call.lineno, mapped=mapped, axes=axes,
                      spec_axes=spec_axes, in_arity=in_arity, fn=fn_q)
        self.summary.wraps.append(ws)
        return ws

    def _collect_bindings(self) -> None:
        s = self.summary
        for node in ast.walk(self.mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        w = self._wrapper_call(dec)
                        if w is None:
                            continue
                        q = self._qname_of[node]
                        scope = self.mod.parent(node) or self.mod.tree
                        ws = self._record_wrap(w, q, scope, q)
                        self._bind(q, ws)
            elif isinstance(node, ast.Call):
                w = self._wrapper_call(node)
                if w is None or w is not node:
                    continue
                # skip the partial(...) decorator form handled above
                par = self.mod.parent(node)
                if isinstance(par, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and node in par.decorator_list:
                    continue
                # call form: shard_map(fn, mesh=..., ...)
                mapped = None
                args = node.args
                if self.mod.is_partial_ref(node.func):
                    args = node.args[1:]
                if args:
                    cand = _terminal(args[0])
                    if cand:
                        fn_q = self._enclosing_qname(node)
                        base = "" if fn_q == "<module>" else fn_q + "."
                        if f"{base}{cand}" in s.functions:
                            mapped = f"{base}{cand}"
                        elif cand in s.functions:
                            mapped = cand
                scope = (self.mod.enclosing_function(node)
                         or self.mod.tree)
                ws = self._record_wrap(node, mapped, scope,
                                       self._enclosing_qname(node))
                if mapped:
                    self._bind(mapped, ws)

    def _bind(self, qname: str, ws: WrapSite) -> None:
        f = self.summary.functions.get(qname)
        if f is None:
            return
        if ws.axes is None:
            f.bound_unknown = True
        else:
            for a in ws.axes:
                if a not in f.bound_axes:
                    f.bound_axes.append(a)

    # -- collectives, meshes, specs -----------------------------------------

    def _collect_collectives(self) -> None:
        for node in ast.walk(self.mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _terminal(node.func)
            if name not in _COLLECTIVES:
                continue
            if isinstance(node.func, ast.Attribute):
                base = _dotted(node.func.value)
                if base is None or base.split(".")[-1] != "lax":
                    continue
            else:  # bare name must come from jax.lax
                src = self.summary.import_names.get(name, "")
                if not src.endswith("lax"):
                    continue
            pos = _COLLECTIVES[name]
            axis_node = None
            if len(node.args) > pos:
                axis_node = node.args[pos]
            for kw in node.keywords:
                if kw.arg == "axis_name":
                    axis_node = kw.value
            axis = self._axis_value(axis_node) if axis_node is not None \
                else None
            self.summary.collectives.append(CollectiveSite(
                line=node.lineno, col=node.col_offset, primitive=name,
                axis=axis, fn=self._enclosing_qname(node)))

    def _collect_meshes_and_specs(self) -> None:
        for node in ast.walk(self.mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _terminal(node.func)
            if name == "Mesh":
                axes_arg = node.args[1] if len(node.args) >= 2 else None
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        axes_arg = kw.value
                axes = self._axes_tuple(axes_arg) if axes_arg is not None \
                    else None
                if axes:
                    self.summary.mesh_axes.append(axes)
            elif name == "NamedSharding" and len(node.args) >= 2:
                axes: List[str] = []
                for sub in ast.walk(node.args[1]):
                    if isinstance(sub, ast.Call) and _terminal(sub.func) in (
                            "P", "PartitionSpec"):
                        for a in sub.args:
                            v = self._axis_value(a)
                            if v is not None:
                                axes.append(v)
                if axes:
                    scope = (self.mod.enclosing_function(node)
                             or self.mod.tree)
                    self.summary.specs.append(SpecSite(
                        line=node.lineno, col=node.col_offset, axes=axes,
                        mesh_axes=self._mesh_axes_of_expr(
                            node.args[0], scope),
                        fn=self._enclosing_qname(node)))

    # -- process/env branches (GT25) ----------------------------------------

    def _env_tainted(self, scope: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for n in ast.walk(scope):
            if (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and self._branch_kind_of_expr(n.value, set())):
                out.add(n.targets[0].id)
        return out

    def _branch_kind_of_expr(self, test: ast.AST,
                             tainted: Set[str]) -> Optional[str]:
        for n in ast.walk(test):
            if isinstance(n, ast.Call):
                t = _terminal(n.func)
                if t in _PROCESS_READS:
                    return "process"
                if t in ("get", "getenv"):
                    d = _dotted(n.func) or ""
                    if "environ" in d or d.endswith("os.getenv") \
                            or d == "getenv":
                        return "env"
            elif isinstance(n, ast.Subscript):
                d = _dotted(n.value) or ""
                if d.split(".")[-1] == "environ":
                    return "env"
            elif isinstance(n, ast.Name) and n.id in tainted:
                return "env"
        return None

    def _arm_signature(self, stmts: List[ast.stmt]) -> Tuple[List[str],
                                                             List[str]]:
        tokens: List[str] = []
        calls: List[str] = []
        for st in stmts:
            for n in ast.walk(st):
                if not isinstance(n, ast.Call):
                    continue
                t = _terminal(n.func)
                if t in _COLLECTIVES:
                    d = _dotted(n.func) or t
                    if "lax" in d.split(".") or \
                            self.summary.import_names.get(
                                t, "").endswith("lax"):
                        pos = _COLLECTIVES[t]
                        axis_node = (n.args[pos]
                                     if len(n.args) > pos else None)
                        for kw in n.keywords:
                            if kw.arg == "axis_name":
                                axis_node = kw.value
                        ax = (self._axis_value(axis_node)
                              if axis_node is not None else None)
                        tokens.append(f"coll:{t}:{ax}")
                        continue
                if t == "update":
                    d = _dotted(n.func) or ""
                    if "config" in d.split("."):
                        tokens.append("config:update")
                        continue
                resolved = self._resolve_callee(n)
                if resolved:
                    calls.append(resolved)
        return sorted(tokens), sorted(calls)

    def _collect_branches(self) -> None:
        taint_cache: Dict[ast.AST, Set[str]] = {}
        for node in ast.walk(self.mod.tree):
            if not isinstance(node, ast.If):
                continue
            scope = self.mod.enclosing_function(node) or self.mod.tree
            if scope not in taint_cache:
                taint_cache[scope] = self._env_tainted(scope)
            kind = self._branch_kind_of_expr(node.test, taint_cache[scope])
            if kind is None:
                continue
            bt, bc = self._arm_signature(node.body)
            ot, oc = self._arm_signature(node.orelse)
            self.summary.branches.append(BranchSite(
                line=node.lineno, col=node.col_offset,
                fn=self._enclosing_qname(node), kind=kind,
                body_tokens=bt, body_calls=bc,
                orelse_tokens=ot, orelse_calls=oc))

    # -- side effects + coordinator gates (GT27) ----------------------------

    def _is_gate_test(self, test: ast.AST) -> bool:
        for n in ast.walk(test):
            if isinstance(n, (ast.Call, ast.Attribute, ast.Name)):
                t = _terminal(n if not isinstance(n, ast.Call) else n.func)
                if t in _GATE_TOKENS:
                    return True
        return False

    def _site_gated(self, node: ast.AST) -> bool:
        for anc in self.mod.ancestors(node):
            if isinstance(anc, ast.If) and self._is_gate_test(anc.test):
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if self._fn_gate_entry(anc):
                    return True
        return False

    def _fn_gate_entry(self, fn: ast.AST) -> bool:
        """An opening `if not is_coordinator(): return` guard gates the
        whole body."""
        for st in list(getattr(fn, "body", ()))[:5]:
            if (isinstance(st, ast.If) and self._is_gate_test(st.test)
                    and any(isinstance(x, (ast.Return, ast.Raise))
                            for x in st.body)):
                return True
        return False

    def _collect_effects(self) -> None:
        for node in ast.walk(self.mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _terminal(node.func)
            kind = detail = None
            if name in ("replace", "rename") and isinstance(
                    node.func, ast.Attribute):
                base = _dotted(node.func.value) or ""
                if base.split(".")[-1] == "os":
                    kind, detail = "persist", f"os.{name}"
            elif name in ("HTTPServer", "ThreadingHTTPServer",
                          "TCPServer"):
                kind, detail = "bind", name
            elif name == "bind" and len(node.args) == 1 and isinstance(
                    node.args[0], ast.Tuple):
                kind, detail = "bind", "socket.bind"
            if kind is None:
                continue
            self.summary.effects.append(EffectSite(
                line=node.lineno, col=node.col_offset,
                fn=self._enclosing_qname(node), kind=kind, detail=detail,
                gated=self._site_gated(node)))

    # -- call edges ----------------------------------------------------------

    def _collect_calls(self) -> None:
        for node in ast.walk(self.mod.tree):
            if not isinstance(node, ast.Call):
                continue
            target = self._resolve_callee(node)
            if target is None:
                continue
            fn_q = self._enclosing_qname(node)
            f = self.summary.functions.get(fn_q)
            if f is not None:
                f.calls.append((target, self._site_gated(node)))

    def run(self) -> ModuleSummary:
        self._collect_imports()
        self._collect_axis_constants()
        self._collect_functions()
        for fn_node, q in self._qname_of.items():
            self.summary.functions[q].gate_entry = \
                self._fn_gate_entry(fn_node)
        self._collect_bindings()
        self._collect_collectives()
        self._collect_meshes_and_specs()
        self._collect_branches()
        self._collect_effects()
        self._collect_calls()
        return self.summary


def extract_summary(mod: ModInfo) -> ModuleSummary:
    return _Extractor(mod).run()


# ---------------------------------------------------------------------------
# project index
# ---------------------------------------------------------------------------


class SpmdIndex:
    """Cross-module SPMD context built from per-module summaries. The
    incremental engine feeds cached summaries for unchanged files via
    `project._gt_spmd_summaries`; a cold scan extracts them all."""

    def __init__(self, summaries: List[ModuleSummary]):
        self.by_module: Dict[str, ModuleSummary] = {
            s.module: s for s in summaries}
        self.by_relpath: Dict[str, ModuleSummary] = {
            s.relpath: s for s in summaries}
        # project axis universe (literal axis names from Mesh() sites)
        self.project_axes: Set[str] = set()
        for s in summaries:
            for axes in s.mesh_axes:
                for a in axes:
                    if not a.startswith(("ref:", "ctor:")):
                        self.project_axes.add(a)
        # mesh-constructor functions: qname -> axes (functions whose
        # summary module records a Mesh() construction inside them —
        # approximated per module; precise enough for default_mesh/
        # global_mesh style one-liners)
        self.ctor_axes: Dict[str, List[str]] = {}
        for s in summaries:
            if len(s.mesh_axes) >= 1:
                axes0 = s.mesh_axes[0]
                same = all(m == axes0 for m in s.mesh_axes)
                if same:
                    for q in s.functions:
                        self.ctor_axes[f"{s.module}:{q}"] = axes0
                        self.ctor_axes[q] = axes0
        # reverse call graph over global ids "module:qname"
        self.callers: Dict[str, List[Tuple[str, str, bool]]] = {}
        for s in summaries:
            for q, f in s.functions.items():
                for target, gated in f.calls:
                    gid = self._global_id(s, target)
                    if gid is not None:
                        self.callers.setdefault(gid, []).append(
                            (s.module, q, gated))
        self._bound_memo: Dict[Tuple[str, str], bool] = {}
        self._coll_memo: Dict[str, Optional[Set[str]]] = {}
        self._reachable: Optional[Set[str]] = None

    # -- id & axis helpers ---------------------------------------------------

    def _global_id(self, summary: ModuleSummary,
                   target: str) -> Optional[str]:
        """Resolve a summary-local call target to "module:qname"."""
        if ":" in target:
            mod_name, name = target.rsplit(":", 1)
            dst = self.by_module.get(mod_name)
            if dst is None:
                return None
            if name in dst.functions:
                return f"{dst.module}:{name}"
            # package __init__ re-export: follow one hop
            src2 = dst.import_names.get(name)
            if src2:
                dst2 = self.by_module.get(src2)
                if dst2 and name in dst2.functions:
                    return f"{dst2.module}:{name}"
            return None
        if target in summary.functions:
            return f"{summary.module}:{target}"
        return None

    def resolve_axis(self, axis: Optional[str]) -> Optional[str]:
        """Follow "ref:<module>:<name>" markers to a literal axis."""
        seen = 0
        while axis is not None and axis.startswith("ref:") and seen < 5:
            _, mod_name, name = axis.split(":", 2)
            dst = self.by_module.get(mod_name)
            if dst is None:
                return None
            if name in dst.axis_constants:
                return dst.axis_constants[name]
            src = dst.import_names.get(name)
            if src is None:
                return None
            axis = f"ref:{src}:{name}"
            seen += 1
        if axis is not None and axis.startswith(("ref:", "ctor:")):
            return None
        return axis

    def resolve_mesh_axes(self,
                          axes: Optional[List[str]]) -> Optional[List[str]]:
        if axes is None:
            return None
        out: List[str] = []
        for a in axes:
            if a.startswith("ctor:"):
                ct = self.ctor_axes.get(a[5:])
                if ct is None:
                    return None
                for c in ct:
                    r = self.resolve_axis(c)
                    if r is None:
                        return None
                    out.append(r)
                continue
            r = self.resolve_axis(a)
            if r is None:
                return None
            out.append(r)
        return out

    # -- GT24 context propagation -------------------------------------------

    def func_bound(self, module: str, qname: str, axis: str,
                   _stack: Optional[Set[str]] = None) -> bool:
        """True when `axis` is bound for every path reaching this
        function: an enclosing wrap binds it, or all in-project callers
        are themselves bound. No callers at all -> unbound."""
        gid = f"{module}:{qname}"
        key = (gid, axis)
        if key in self._bound_memo:
            return self._bound_memo[key]
        stack = _stack or set()
        if gid in stack:
            return True  # cycle: optimistic, avoids self-FP
        s = self.by_module.get(module)
        f = s.functions.get(qname) if s else None
        if f is None:
            return False
        if f.bound_unknown:
            self._bound_memo[key] = True
            return True
        resolved = self.resolve_mesh_axes(f.bound_axes)
        if resolved is None and f.bound_axes:
            # a wrap binds this function but its axes can't be resolved
            # (opaque ctor, cross-module miss): optimistic, like
            # bound_unknown — GT24 only flags provably-unbound axes
            self._bound_memo[key] = True
            return True
        bound = set(resolved or ())
        if axis in bound:
            self._bound_memo[key] = True
            return True
        # nested defs inherit the enclosing function's binding (a def
        # inside a wrapped body executes under the wrap)
        if "." in qname:
            outer = qname.rsplit(".", 1)[0]
            if s and outer in s.functions and self.func_bound(
                    module, outer, axis, stack | {gid}):
                self._bound_memo[key] = True
                return True
        callers = self.callers.get(gid, ())
        if not callers:
            self._bound_memo[key] = False
            return False
        ok = all(self.func_bound(cm, cq, axis, stack | {gid})
                 for cm, cq, _ in callers)
        self._bound_memo[key] = ok
        return ok

    # -- GT25 transitive collective effects ----------------------------------

    def collective_tokens(self, gid: str,
                          depth: int = 4) -> Set[str]:
        if gid in self._coll_memo:
            return self._coll_memo[gid] or set()
        self._coll_memo[gid] = None  # cycle guard
        out: Set[str] = set()
        mod_name, qname = gid.split(":", 1)
        s = self.by_module.get(mod_name)
        if s is not None and qname in s.functions:
            for c in s.collectives:
                if c.fn == qname or c.fn.startswith(qname + "."):
                    out.add(f"coll:{c.primitive}:"
                            f"{self.resolve_axis(c.axis)}")
            if depth > 0:
                for target, _ in s.functions[qname].calls:
                    sub = self._global_id(s, target)
                    if sub is not None:
                        out |= self.collective_tokens(sub, depth - 1)
        self._coll_memo[gid] = out
        return out

    def arm_effective(self, summary: ModuleSummary, tokens: List[str],
                      calls: List[str]) -> Set[str]:
        out = set()
        for t in tokens:
            if t.startswith("coll:"):
                parts = t.split(":", 2)
                out.add(f"coll:{parts[1]}:"
                        f"{self.resolve_axis(parts[2]) or parts[2]}")
            else:
                out.add(t)
        for c in calls:
            gid = self._global_id(summary, c)
            if gid is not None:
                out |= self.collective_tokens(gid)
        return out

    # -- GT25/GT27 reachability ----------------------------------------------

    def reachable_modules(self) -> Set[str]:
        """Modules importable (transitively) from the distributed entry
        points — the code that runs inside a multi-process program."""
        if self._reachable is not None:
            return self._reachable
        entries = []
        for s in self.by_relpath.values():
            rel = s.relpath.replace("\\", "/")
            if rel in _GT25_ENTRY_FILES or rel.startswith(
                    _GT25_ENTRY_PREFIXES):
                entries.append(s.module)
        seen: Set[str] = set()
        work = list(entries)
        while work:
            m = work.pop()
            if m in seen:
                continue
            seen.add(m)
            s = self.by_module.get(m)
            if s is None:
                continue
            for imp in s.imports:
                if imp not in seen:
                    work.append(imp)
                # `import geomesa_tpu.parallel.launch` also runs the
                # package __init__ chain
                parts = imp.split(".")
                for i in range(1, len(parts)):
                    pkg = ".".join(parts[:i])
                    if pkg not in seen:
                        work.append(pkg)
        self._reachable = seen
        return seen

    def caller_gated(self, module: str, qname: str,
                     _depth: int = 2) -> bool:
        """All in-project call sites of this function are coordinator-
        gated (one level of interprocedural gate propagation)."""
        gid = f"{module}:{qname}"
        callers = self.callers.get(gid, ())
        if not callers:
            return False
        for cm, cq, gated in callers:
            if gated:
                continue
            cs = self.by_module.get(cm)
            cf = cs.functions.get(cq) if cs else None
            if cf is not None and cf.gate_entry:
                continue
            if _depth > 0 and self.caller_gated(cm, cq, _depth - 1):
                continue
            return False
        return True


def spmd_index(project) -> SpmdIndex:
    idx = getattr(project, "_gt_spmd", None)
    if idx is None:
        cached: Dict[str, ModuleSummary] = getattr(
            project, "_gt_spmd_summaries", None) or {}
        summaries = []
        for m in project.modules:
            s = cached.get(m.relpath)
            if s is None or s.schema != SPMD_SCHEMA:
                s = extract_summary(m)
            summaries.append(s)
        idx = project._gt_spmd = SpmdIndex(summaries)
        project._gt_spmd_summaries = {
            s.relpath: s for s in summaries}
    return idx


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def _finding(rule: str, mod: ModInfo, line: int, col: int,
             msg: str) -> Finding:
    return Finding(rule=rule, path=mod.relpath, line=line, col=col,
                   message=msg)


def gt24(mod: ModInfo, project) -> Iterator[Finding]:
    """Collective whose axis name no enclosing or calling-context wrap
    binds. Axis names that do not resolve statically (passed as
    parameters) are skipped — conservative, no false positives on
    axis-generic helpers like jaxcompat.pcast."""
    idx = spmd_index(project)
    s = idx.by_relpath.get(mod.relpath)
    if s is None:
        return
    seen: Set[Tuple[int, str]] = set()
    for c in s.collectives:
        axis = idx.resolve_axis(c.axis)
        if axis is None:
            continue
        if c.fn == "<module>":
            yield _finding(
                "GT24", mod, c.line, c.col,
                f"collective jax.lax.{c.primitive} over axis {axis!r} at "
                f"module level: no shard_map/pjit context can bind it")
            continue
        if idx.func_bound(s.module, c.fn, axis):
            continue
        key = (c.line, c.primitive)
        if key in seen:
            continue
        seen.add(key)
        yield _finding(
            "GT24", mod, c.line, c.col,
            f"collective jax.lax.{c.primitive} over axis {axis!r} in "
            f"{c.fn!r} is not bound by any enclosing shard_map/pjit wrap "
            f"or calling context — traces only under a mesh that binds "
            f"{axis!r}; on a pod this fails (or hangs) at first dispatch")


def gt25(mod: ModInfo, project) -> Iterator[Finding]:
    """Process-divergent control flow on a distributed-reachable path:
    the two arms of a process_index()/env branch disagree on collective-
    relevant effects (collectives issued, or jax.config.update calls
    that reshape every compiled program). One process takes each side;
    the collectives stop lining up; the pod deadlocks — silently, since
    single-process CPU CI only ever sees one arm."""
    idx = spmd_index(project)
    s = idx.by_relpath.get(mod.relpath)
    if s is None or s.module not in idx.reachable_modules():
        return
    for b in s.branches:
        body = idx.arm_effective(s, b.body_tokens, b.body_calls)
        orelse = idx.arm_effective(s, b.orelse_tokens, b.orelse_calls)
        if body == orelse:
            continue
        diff = sorted(body.symmetric_difference(orelse))
        src = ("jax.process_index()/process_count()"
               if b.kind == "process" else "an os.environ read")
        yield _finding(
            "GT25", mod, b.line, b.col,
            f"branch conditioned on {src} reaches different collective-"
            f"relevant effects per arm ({', '.join(diff)}): processes "
            f"taking different sides issue mismatched collective "
            f"sequences (or compile divergent programs) — a silent "
            f"multi-host deadlock CPU CI cannot reproduce")


def gt26(mod: ModInfo, project) -> Iterator[Finding]:
    """Sharding-spec drift: a spec axis name the constructing mesh (or,
    when the mesh is not statically resolvable, ANY project mesh) does
    not define, or a literal in_specs tuple whose arity disagrees with
    the mapped function's positional parameter count."""
    idx = spmd_index(project)
    s = idx.by_relpath.get(mod.relpath)
    if s is None:
        return
    for w in s.wraps:
        mesh_axes = idx.resolve_mesh_axes(w.axes)
        for line, col, raw in w.spec_axes:
            axis = idx.resolve_axis(raw)
            if axis is None:
                continue
            if mesh_axes is not None:
                if axis not in mesh_axes:
                    yield _finding(
                        "GT26", mod, line, col,
                        f"spec names axis {axis!r} but the wrap's mesh "
                        f"binds {mesh_axes!r}")
            elif idx.project_axes and axis not in idx.project_axes:
                yield _finding(
                    "GT26", mod, line, col,
                    f"spec names axis {axis!r}; no mesh constructed "
                    f"anywhere in the project defines that axis "
                    f"(project axes: {sorted(idx.project_axes)!r})")
        if w.in_arity is not None and w.mapped is not None:
            f = s.functions.get(w.mapped)
            if f is not None and not f.has_vararg and \
                    len(f.params) != w.in_arity:
                yield _finding(
                    "GT26", mod, w.line, 0,
                    f"in_specs has {w.in_arity} entr"
                    f"{'y' if w.in_arity == 1 else 'ies'} but mapped "
                    f"function {w.mapped!r} takes {len(f.params)} "
                    f"positional parameter(s)")
    for sp in s.specs:
        mesh_axes = idx.resolve_mesh_axes(sp.mesh_axes)
        for raw in sp.axes:
            axis = idx.resolve_axis(raw)
            if axis is None:
                continue
            if mesh_axes is not None:
                if axis not in mesh_axes:
                    yield _finding(
                        "GT26", mod, sp.line, sp.col,
                        f"NamedSharding spec names axis {axis!r} but its "
                        f"mesh binds {mesh_axes!r}")
            elif idx.project_axes and axis not in idx.project_axes:
                yield _finding(
                    "GT26", mod, sp.line, sp.col,
                    f"NamedSharding spec names axis {axis!r}; no project "
                    f"mesh defines it "
                    f"(project axes: {sorted(idx.project_axes)!r})")


def gt27(mod: ModInfo, project) -> Iterator[Finding]:
    """Process-local side effect on a multi-process-reachable path with
    no coordinator gate. Scope: the persist idiom (tmp + os.replace /
    os.rename) and port binds, in the subsystems the multi-host runtime
    actually enters (parallel/, store/, compilecache/, serve/,
    telemetry/, approx/). Fix: gate on parallel.is_coordinator() (a
    single-process no-op), or waive with the reason the write is
    host-local by design (e.g. per-partition ingest under
    process_partitions())."""
    rel = mod.relpath.replace("\\", "/")
    if not rel.startswith(_GT27_PREFIXES):
        return
    idx = spmd_index(project)
    s = idx.by_relpath.get(mod.relpath)
    if s is None:
        return
    for e in s.effects:
        if e.gated:
            continue
        if e.fn != "<module>":
            f = s.functions.get(e.fn)
            if f is not None and f.gate_entry:
                continue
            if idx.caller_gated(s.module, e.fn):
                continue
        what = ("port bind" if e.kind == "bind"
                else f"atomic persist ({e.detail})")
        yield _finding(
            "GT27", mod, e.line, e.col,
            f"{what} in {e.fn!r} has no coordinator gate: every process "
            f"of a multi-host run performs it against shared storage — "
            f"gate with parallel.is_coordinator() (single-process no-op) "
            f"or waive as host-local by design")


SPMD_RULES = {"GT24": gt24, "GT25": gt25, "GT26": gt26, "GT27": gt27}
