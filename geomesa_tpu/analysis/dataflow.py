"""Interprocedural dtype/shape-provenance dataflow pass: GT28..GT31.

The serve stack's two hardest invariants are enforced at runtime only
(JitTracker recompile counters, f64 parity tests): **zero recompiles on
the hot path** — every array reaching a jit/AOT/ring dispatch must have
a *bucketed* shape (pad_to / next_pow2 / stack_queries / a registry
bucket key), never a raw request-determined one — and **bit-exact f64
answers over f32 kernels** — final distances come from the canonical
host-side f64 recompute over the *original* f64 inputs, never from
upcasting an already-rounded f32 value. Both break invisibly on CPU CI:
a raw shape only storms the compile cache under real traffic, and an
f32→f64 launder only drifts an ulp.

This pass is the static closer. It runs an abstract interpreter over
each module (pure AST, shared `modinfo`/`spmd` project index — the code
under analysis is never imported) assigning every array-producing
expression a provenance value:

- shape origin: ``raw`` (len(), np.asarray over wire payloads,
  np.frombuffer, np.concatenate of request lists) vs ``bucketed``
  (next_pow2 / pad_to / stack_queries / registry bucket keys);
- dtype origin: ``f64`` (exact), ``f32`` (cast — sticky: upcasting
  later does NOT clear it), ``weak`` (python literals);
- transfer origin: ``host`` (a jax.device_get result).

Provenance propagates through assignments, tuple/dict packing, staging
seams (slot writes `self._slots[i] = x`, batcher stacks), and calls:
per-function summaries record parameter-passthrough return provenance
(``param:<name>`` / ``call:<target>`` markers), and a project index —
built on the SPMD extractor's import/call-resolution machinery and the
same caller-propagation discipline as `SpmdIndex.func_bound` — resolves
the markers across module boundaries, summary-based and depth-bounded.

Rules riding the lattice:

- **GT28** — a raw (unbucketed) dynamic shape reaching a jit/AOT/ring
  dispatch in serve//plan//subscribe//engine/ scope: the static
  recompile-storm detector.
- **GT29** — an f32-cast value flowing into an exact-f64 consumer (an
  `.astype(float64)` / `np.asarray(x, np.float64)` upcast, or a callee
  parameter named `*_f64`) without passing the canonical f64 recompute:
  upcasting rounded f32 restores nothing — the value keeps its sticky
  ``f32`` tag and the report's provenance chain walks back to the cast.
- **GT30** — an AOT/ring registry lookup whose literal key names a
  variant (`@serve` / `@ring<depth>` / `@mesh...`) no
  `registry.register`/`serve_variant`/`ring_variant`/`mesh_variant`
  site in the project (scan set *or* reference universe) can produce —
  GT13 made interprocedural: the warmup manifest can never warm that
  caller; first traffic pays a KeyError or an inline compile.
- **GT31** — a device→host→device bounce: a `jax.device_get` result
  transitively re-entering `device_put` or a dispatch — two transfers
  where zero were needed.

Findings carry their provenance chain in `Finding.extra["chain"]`
(`[{path, line, note}, ...]`), rendered as SARIF `relatedLocations` so
a CI annotation walks from the sink to the leak's origin.

Summaries are plain-dict serializable (`ModuleFlow.to_dict`/`from_dict`)
so the incremental cache persists them per file like the SPMD
summaries, and the cross-file index rebuilds for unchanged files
without re-walking their ASTs (analysis/incremental.py).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from geomesa_tpu.analysis.model import Finding
from geomesa_tpu.analysis.modinfo import ModInfo
from geomesa_tpu.analysis.spmd import (
    _Extractor as _SpmdExtractor, _dotted, _terminal)

# bump when the summary shape changes: cached summaries from an older
# engine must not feed the index (analysis/incremental.py keys on this)
DATAFLOW_SCHEMA = 1

# hot-path scope for the shape/transfer rules (GT28/GT31): the serving
# pipeline. One-shot scripts and tests dispatch raw shapes legitimately.
_HOT_PREFIXES = ("geomesa_tpu/serve/", "geomesa_tpu/plan/",
                 "geomesa_tpu/subscribe/", "geomesa_tpu/engine/")

# shape bucketers: calls that quantize a dynamic extent onto the small
# static set the warmup manifests cover
_BUCKET_FNS = {"next_pow2", "_next_pow2", "pad_to", "stack_queries",
               "capacity_bucket", "bucket_capacity", "round_up_pow2"}

# numpy/jnp constructors whose result shape is the (dynamic) input's
_RAW_MAKERS = {"asarray", "array", "frombuffer", "fromiter",
               "ascontiguousarray", "concatenate", "stack",
               "column_stack", "vstack", "hstack"}

# constructors whose shape comes from their first (extent) argument
_EXTENT_MAKERS = {"zeros", "ones", "full", "empty", "arange"}

# provenance-preserving builtins/ufuncs (shape math over extents)
_PASSTHROUGH_FNS = {"int", "max", "min", "abs", "round", "float"}

_REG_APIS = ("register", "serve_variant", "ring_variant", "mesh_variant")


def _terminal_name(target: str) -> str:
    """Tail identifier of a resolved callee ('pkg/mod:a.b' -> 'b')."""
    return target.rsplit(":", 1)[-1].rsplit(".", 1)[-1]


def _is_registry_recv(node: ast.AST) -> bool:
    """`registry.compile(...)` receivers: the shared ExecutableRegistry
    and its conventional aliases — NOT `re.compile` / builtins."""
    t = _terminal(node)
    return bool(t) and (t == "registry" or t.endswith("registry")
                        or t in ("aot", "reg", "_reg"))


def _dtype_tag(node: Optional[ast.AST]) -> Optional[str]:
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        name = node.value
    else:
        name = (_dotted(node) or _terminal(node) or "").split(".")[-1]
    if name in ("float64", "double", "f64"):
        return "f64"
    if name in ("float32", "float16", "bfloat16", "f32"):
        return "f32"
    return None


# ---------------------------------------------------------------------------
# per-module summary model (dict-serializable for the incremental cache)
# ---------------------------------------------------------------------------

# A provenance value is serialized as `[tags, chain]`: `tags` a sorted
# list of markers ("raw"/"bucketed"/"f32"/"f64"/"weak"/"host"/
# "aot-handle"/"param:<name>"/"call:<target>"/"regname:<key>"), `chain`
# a list of `[line, note]` origin steps (capped — a report needs the
# leak site, not a trace).


@dataclass
class FlowSite:
    """A consumer site the rules examine: a resolved call with tagged
    arguments, an AOT compile/call, a device_put, or an f64 upcast."""
    line: int
    col: int
    fn: str                      # enclosing function qname or "<module>"
    kind: str                    # "call"|"aot_compile"|"aot_call"|
    #                              "device_put"|"f64cast"
    target: str = ""             # resolved callee (summary-local) or ""
    terminal: str = ""           # terminal callee name (jit_by_name key)
    name: str = ""               # literal registry key for aot_compile
    args: List[list] = field(default_factory=list)
    kwargs: Dict[str, list] = field(default_factory=dict)


@dataclass
class FuncFlow:
    qname: str
    line: int
    params: List[str] = field(default_factory=list)
    returns: List[str] = field(default_factory=list)
    ret_chain: List[list] = field(default_factory=list)


@dataclass
class ModuleFlow:
    schema: int
    relpath: str
    module: str
    import_names: Dict[str, str] = field(default_factory=dict)
    functions: Dict[str, FuncFlow] = field(default_factory=dict)
    sites: List[FlowSite] = field(default_factory=list)
    regs: List[list] = field(default_factory=list)
    #      [api, name|None, depth|None, line]

    def to_dict(self) -> dict:
        def enc(obj):
            if isinstance(obj, (FlowSite, FuncFlow)):
                return {k: enc(v) for k, v in vars(obj).items()}
            if isinstance(obj, (list, tuple)):
                return [enc(v) for v in obj]
            if isinstance(obj, dict):
                return {k: enc(v) for k, v in obj.items()}
            return obj
        return enc(vars(self))

    @classmethod
    def from_dict(cls, d: dict) -> "ModuleFlow":
        d = dict(d)
        d["functions"] = {k: FuncFlow(**v)
                          for k, v in d["functions"].items()}
        d["sites"] = [FlowSite(**s) for s in d["sites"]]
        return cls(**d)


# ---------------------------------------------------------------------------
# extraction: one abstract-interpretation walk per module
# ---------------------------------------------------------------------------

_Val = Tuple[Set[str], List[list]]

_CHAIN_CAP = 5


def _val(tags: Set[str], chain: List[list]) -> _Val:
    return tags, chain[:_CHAIN_CAP]


def _union(vals) -> _Val:
    tags: Set[str] = set()
    chain: List[list] = []
    for t, c in vals:
        tags |= t
        for step in c:
            if step not in chain:
                chain.append(step)
    return _val(tags, chain)


def collect_registrations(tree: ast.AST) -> List[list]:
    """Registry key registrations from a raw AST: `[api, name, depth,
    line]` rows (name/depth None when not statically literal), plus an
    `install_defaults` wildcard row. Shared by the extractor and the
    rule-time reference-universe sweep (GT30 must see registrations in
    modules outside the scan set — the GT05 discipline)."""
    consts: Dict[str, str] = {}
    body = getattr(tree, "body", ())
    for node in body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            consts[node.targets[0].id] = node.value.value
    out: List[list] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        term = _terminal(node.func)
        if term == "install_defaults":
            out.append(["install_defaults", None, None, node.lineno])
            continue
        if term not in _REG_APIS or not isinstance(node.func,
                                                   ast.Attribute):
            continue
        name = None
        if node.args:
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                name = a0.value
            elif isinstance(a0, ast.Name):
                name = consts.get(a0.id)
        depth = None
        if term == "ring_variant" and len(node.args) >= 2:
            d = node.args[1]
            if isinstance(d, ast.Constant) and isinstance(d.value, int):
                depth = d.value
        out.append([term, name, depth, node.lineno])
    return out


class _FlowExtractor:
    """Two abstract-interpretation passes over one module (the second
    pass sees class-attribute provenance collected by the first, so
    staging seams like `self._slots[i] = qx` propagate across methods);
    sites are recorded on the final pass only."""

    def __init__(self, mod: ModInfo):
        self.mod = mod
        self.base = _SpmdExtractor(mod)
        self.base._collect_imports()
        self.base._collect_axis_constants()
        self.base._collect_functions()
        self.flow = ModuleFlow(
            schema=DATAFLOW_SCHEMA, relpath=mod.relpath,
            module=self.base.module,
            import_names=dict(self.base.summary.import_names))
        self._class_attrs: Dict[Tuple[str, str], _Val] = {}
        self._module_env: Dict[str, _Val] = {}
        self._record = False
        self._cur_fn = "<module>"
        self._cur_cls: Optional[str] = None
        self._cur_ret: Optional[_Val] = None

    # -- driver -------------------------------------------------------------

    def run(self) -> ModuleFlow:
        for record in (False, True):
            self._record = record
            self.flow.sites = []
            self.flow.regs = collect_registrations(self.mod.tree)
            self.flow.functions = {}
            self._cur_fn, self._cur_cls = "<module>", None
            self._module_env = {}
            self._cur_ret = None
            self._flow_body(self.mod.tree.body, self._module_env)
            for fn_node, q in self.base._qname_of.items():
                self._flow_function(fn_node, q)
        return self.flow

    def _flow_function(self, fn_node: ast.AST, qname: str) -> None:
        self._cur_fn = qname
        self._cur_cls = self.base._class_of.get(fn_node)
        a = fn_node.args
        params = [p.arg for p in a.posonlyargs + a.args]
        if params and params[0] in ("self", "cls"):
            params = params[1:]
        kwonly = [p.arg for p in a.kwonlyargs]
        env: Dict[str, _Val] = {
            p: ({f"param:{p}"}, []) for p in params + kwonly}
        # closure captures: a nested def reading an enclosing function's
        # parameter gets a marker (not "untagged", which would make a
        # downstream np.asarray default to raw). The marker resolves
        # against the NESTED function's callers — which never bind it —
        # so it joins to empty: conservative no-fire, matching the
        # analysis's no-callers policy for unknowable provenance.
        outer = self.mod.enclosing_function(fn_node)
        while outer is not None:
            oa = outer.args
            for p in oa.posonlyargs + oa.args + oa.kwonlyargs:
                if p.arg not in env and p.arg not in ("self", "cls"):
                    env[p.arg] = ({f"param:{p.arg}"}, [])
            outer = self.mod.enclosing_function(outer)
        self._cur_ret = (set(), [])
        self._flow_body(fn_node.body, env)
        if self._record:
            tags, chain = self._cur_ret
            self.flow.functions[qname] = FuncFlow(
                qname=qname, line=fn_node.lineno,
                params=params + kwonly,
                returns=sorted(tags), ret_chain=chain[:_CHAIN_CAP])
        self._cur_ret = None

    # -- statements ----------------------------------------------------------

    def _flow_body(self, stmts, env: Dict[str, _Val]) -> None:
        for st in stmts:
            self._flow_stmt(st, env)

    def _flow_stmt(self, st: ast.stmt, env: Dict[str, _Val]) -> None:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return  # own entry in the function table / class walk
        if isinstance(st, ast.Assign):
            v = self._eval(st.value, env)
            for t in st.targets:
                self._assign(t, v, env)
        elif isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._assign(st.target, self._eval(st.value, env), env)
        elif isinstance(st, ast.AugAssign):
            v = self._eval(st.value, env)
            key = self._target_key(st.target)
            if key is not None:
                env[key] = _union([env.get(key, (set(), [])), v])
        elif isinstance(st, ast.Return):
            if st.value is not None:
                v = self._eval(st.value, env)
                if self._cur_ret is not None:
                    self._cur_ret = _union([self._cur_ret, v])
        elif isinstance(st, ast.Expr):
            self._eval(st.value, env)
        elif isinstance(st, ast.If):
            self._eval(st.test, env)
            self._flow_body(st.body, env)
            self._flow_body(st.orelse, env)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            it = self._eval(st.iter, env)
            self._assign(st.target, it, env)
            self._flow_body(st.body, env)
            self._flow_body(st.orelse, env)
        elif isinstance(st, ast.While):
            self._eval(st.test, env)
            self._flow_body(st.body, env)
            self._flow_body(st.orelse, env)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                v = self._eval(item.context_expr, env)
                if item.optional_vars is not None:
                    self._assign(item.optional_vars, v, env)
            self._flow_body(st.body, env)
        elif isinstance(st, ast.Try):
            self._flow_body(st.body, env)
            for h in st.handlers:
                self._flow_body(h.body, env)
            self._flow_body(st.orelse, env)
            self._flow_body(st.finalbody, env)
        elif isinstance(st, ast.Raise) and st.exc is not None:
            self._eval(st.exc, env)

    def _target_key(self, t: ast.AST) -> Optional[str]:
        if isinstance(t, ast.Name):
            return t.id
        if isinstance(t, ast.Attribute):
            return _dotted(t)
        return None

    def _assign(self, t: ast.AST, v: _Val, env: Dict[str, _Val]) -> None:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._assign(e, v, env)
            return
        if isinstance(t, ast.Starred):
            self._assign(t.value, v, env)
            return
        if isinstance(t, ast.Subscript):
            # staging seam: a slot write (`slots[i] = qx`,
            # `self._ring[slot] = staged`) taints the container
            key = self._target_key(t.value)
            if key is not None:
                env[key] = _union([env.get(key, (set(), [])), v])
            return
        key = self._target_key(t)
        if key is None:
            return
        env[key] = v
        if (isinstance(t, ast.Attribute) and self._cur_cls
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"):
            ck = (self._cur_cls, t.attr)
            self._class_attrs[ck] = _union(
                [self._class_attrs.get(ck, (set(), [])), v])

    # -- expressions ---------------------------------------------------------

    def _eval(self, node: ast.AST, env: Dict[str, _Val]) -> _Val:
        if isinstance(node, ast.Name):
            if node.id in env:
                return env[node.id]
            return self._module_env.get(node.id, (set(), []))
        if isinstance(node, ast.Attribute):
            d = _dotted(node)
            if d is not None and d in env:
                return env[d]
            if (self._cur_cls and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                ca = self._class_attrs.get((self._cur_cls, node.attr))
                if ca is not None:
                    return ca
            recv = self._eval(node.value, env)
            if node.attr == "shape":
                return (recv[0] & {"raw", "bucketed"}, recv[1])
            return recv
        if isinstance(node, ast.Call):
            return self._eval_call(node, env)
        if isinstance(node, ast.Constant):
            if isinstance(node.value, float):
                return ({"weak"}, [])
            return (set(), [])
        if isinstance(node, ast.BinOp):
            return _union([self._eval(node.left, env),
                           self._eval(node.right, env)])
        if isinstance(node, ast.BoolOp):
            return _union([self._eval(v, env) for v in node.values])
        if isinstance(node, (ast.UnaryOp, ast.Await, ast.Starred)):
            inner = getattr(node, "operand", None) or node.value
            return self._eval(inner, env)
        if isinstance(node, ast.Compare):
            return _union([self._eval(node.left, env)]
                          + [self._eval(c, env) for c in node.comparators])
        if isinstance(node, ast.Subscript):
            v = self._eval(node.value, env)
            self._eval(node.slice, env)
            return v
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return _union([self._eval(e, env) for e in node.elts])
        if isinstance(node, ast.Dict):
            return _union([self._eval(v, env)
                           for v in node.values if v is not None])
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            for g in node.generators:
                self._eval(g.iter, env)
            return self._eval(node.elt, env)
        if isinstance(node, ast.DictComp):
            for g in node.generators:
                self._eval(g.iter, env)
            return self._eval(node.value, env)
        if isinstance(node, ast.IfExp):
            self._eval(node.test, env)
            return _union([self._eval(node.body, env),
                           self._eval(node.orelse, env)])
        if isinstance(node, ast.NamedExpr):
            v = self._eval(node.value, env)
            self._assign(node.target, v, env)
            return v
        return (set(), [])

    def _np_like(self, func: ast.AST) -> bool:
        if not isinstance(func, ast.Attribute):
            return False
        if self.mod.is_numpy_ref(func.value) or \
                self.mod.is_jnp_ref(func.value):
            return True
        base = _terminal(func.value)
        return base in ("np", "numpy", "jnp")

    def _apply_dtype(self, tags: Set[str], chain: List[list],
                     dt: Optional[str], line: int) -> None:
        if dt == "f64":
            tags.add("f64")
        elif dt == "f32":
            tags.discard("f64")
            tags.add("f32")
            chain.append([line, "f32 cast"])

    def _site(self, node: ast.Call, kind: str, args: List[_Val],
              kwargs: Dict[str, _Val], target: str = "",
              terminal: str = "", name: str = "") -> None:
        if not self._record:
            return
        self.flow.sites.append(FlowSite(
            line=node.lineno, col=node.col_offset, fn=self._cur_fn,
            kind=kind, target=target, terminal=terminal, name=name,
            args=[[sorted(t), c] for t, c in args],
            kwargs={k: [sorted(t), c] for k, (t, c) in kwargs.items()}))

    def _eval_call(self, node: ast.Call, env: Dict[str, _Val]) -> _Val:
        line = node.lineno
        args: List[_Val] = []
        for a in node.args:
            args.append(self._eval(
                a.value if isinstance(a, ast.Starred) else a, env))
        kwargs: Dict[str, _Val] = {}
        for kw in node.keywords:
            v = self._eval(kw.value, env)
            if kw.arg:
                kwargs[kw.arg] = v
        term = _terminal(node.func) or ""
        dt_kw = None
        for kw in node.keywords:
            if kw.arg == "dtype":
                dt_kw = _dtype_tag(kw.value)

        # registry registrations + variant constructors (GT30 universe).
        # Variant constructors return the composed key: tag it so a
        # later `registry.compile(vname, ...)` resolves the literal.
        if term in _REG_APIS and isinstance(node.func, ast.Attribute):
            name = None
            if node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Constant) and isinstance(
                        a0.value, str):
                    name = a0.value
                elif isinstance(a0, ast.Name):
                    name = self.base.summary.axis_constants.get(a0.id)
                if name is None and args:
                    for t in args[0][0]:
                        if t.startswith("regname:"):
                            name = t[len("regname:"):]
            if term == "register" or name is None:
                return (set(), [])
            if term == "serve_variant":
                return ({f"regname:{name}@serve"}, [])
            if term == "mesh_variant":
                return ({f"regname:{name}@mesh*"}, [])
            depth = "*"
            if len(node.args) >= 2:
                d1 = node.args[1]
                if isinstance(d1, ast.Constant) and isinstance(
                        d1.value, int):
                    depth = str(d1.value)
            return ({f"regname:{name}@ring{depth}*"}, [])

        # AOT registry lookups / handle dispatches
        if (term == "compile" and isinstance(node.func, ast.Attribute)
                and _is_registry_recv(node.func.value)):
            name = ""
            if node.args:
                a0 = node.args[0]
                if isinstance(a0, ast.Constant) and isinstance(
                        a0.value, str):
                    name = a0.value
                else:
                    for t in args[0][0]:
                        if t.startswith("regname:"):
                            name = t[len("regname:"):]
            self._site(node, "aot_compile", args, kwargs,
                       terminal=term, name=name)
            return ({"aot-handle"}, [])
        if term == "call" and isinstance(node.func, ast.Attribute):
            recv = self._eval(node.func.value, env)
            if "aot-handle" in recv[0]:
                self._site(node, "aot_call", args, kwargs, terminal=term)
                return (set(), [])

        # transfers
        if term == "device_get":
            tags, chain = _union(args + list(kwargs.values()))
            tags = set(tags) | {"host"}
            chain = chain + [[line, "host copy: jax.device_get"]]
            return _val(tags, chain)
        if term in ("device_put", "to_device"):
            self._site(node, "device_put", args, kwargs, terminal=term)
            tags, chain = _union(args)
            return _val(set(tags) - {"host"}, chain)

        # dtype casts
        if term == "astype" and isinstance(node.func, ast.Attribute):
            recv = self._eval(node.func.value, env)
            dt = _dtype_tag(node.args[0]) if node.args else dt_kw
            tags, chain = set(recv[0]), list(recv[1])
            if dt == "f64":
                self._site(node, "f64cast", [recv], {}, terminal=term)
                tags.add("f64")
                chain.append([line, "f64 upcast"])
            else:
                self._apply_dtype(tags, chain, dt, line)
            return _val(tags, chain)
        if term in ("float64", "float32") and self._np_like(node.func):
            inner = _union(args)
            tags, chain = set(inner[0]), list(inner[1])
            if term == "float64":
                if args:
                    self._site(node, "f64cast", [args[0]], {},
                               terminal=term)
                tags.add("f64")
                chain.append([line, "f64 upcast"])
            else:
                self._apply_dtype(tags, chain, "f32", line)
            return _val(tags, chain)

        # shape producers
        if term == "len":
            return ({"raw"}, [[line, "raw dynamic size: len(...)"]])
        bucket_name = term if term in _BUCKET_FNS else ""
        if not bucket_name:
            # `from utils.padding import next_pow2 as _np2` style aliases:
            # recognize the bucket helper by its resolved definition name
            resolved = self.base._resolve_callee(node) or ""
            tail = _terminal_name(resolved)
            if tail in _BUCKET_FNS:
                bucket_name = tail
        if bucket_name:
            inner = _union(args)
            tags = {"bucketed"} | (inner[0] & {"host"})
            if bucket_name == "stack_queries":
                tags.add("f64")  # batcher stacks cast to np.float64
            return _val(tags, [[line, f"bucketed: {bucket_name}(...)"]])
        if term == "ShapeDtypeStruct":
            shape = args[0] if args else (set(), [])
            tags = shape[0] & {"raw", "bucketed"}
            chain = list(shape[1])
            dt = dt_kw or (_dtype_tag(node.args[1])
                           if len(node.args) >= 2 else None)
            self._apply_dtype(tags, chain, dt, line)
            return _val(tags, chain)
        if self._np_like(node.func) and term in _RAW_MAKERS:
            inner = _union(args)
            shape = inner[0] & {"raw", "bucketed"}
            markers = {t for t in inner[0]
                       if t.startswith(("param:", "call:"))}
            # Default-raw only for genuinely local unknowns (wire payload
            # decodes, recv buffers): a marker-carrying input defers its
            # shape verdict to caller/callee resolution — store batches
            # are capacity-bucketed at ingest and must not read as raw
            # just because the pad site is out of interprocedural reach.
            # frombuffer/fromiter are extent-from-bytes: always raw.
            always_raw = term in ("frombuffer", "fromiter")
            tags = set(shape)
            if always_raw or (not shape and not markers):
                tags.add("raw")
            tags |= markers
            tags |= inner[0] & {"host", "f32", "f64"}
            chain = list(inner[1])
            if "raw" in tags and "raw" not in shape:
                chain.append([line, f"raw shape: {term}(...)"])
            dt = dt_kw
            if dt is None and term in ("asarray", "array") and \
                    len(node.args) >= 2:
                dt = _dtype_tag(node.args[1])
            if dt == "f64":
                self._site(node, "f64cast", [inner], {}, terminal=term)
                tags.add("f64")
                chain.append([line, "f64 upcast"])
            else:
                self._apply_dtype(tags, chain, dt, line)
            return _val(tags, chain)
        if self._np_like(node.func) and term in _EXTENT_MAKERS:
            extent = args[0] if args else (set(), [])
            tags = {t for t in extent[0]
                    if t in ("raw", "bucketed")
                    or t.startswith(("param:", "call:"))}
            chain = list(extent[1])
            dt = dt_kw
            if dt is None and term == "full" and len(node.args) >= 3:
                dt = _dtype_tag(node.args[2])
            self._apply_dtype(tags, chain, dt, line)
            return _val(tags, chain)
        if term in _PASSTHROUGH_FNS:
            return _union(args)

        # generic calls: record when any argument carries provenance
        # (the interprocedural edges param-resolution walks), return a
        # summary marker for resolved project callees
        target = self.base._resolve_callee(node) or ""
        tagged = any(v[0] for v in args) or \
            any(v[0] for v in kwargs.values())
        if tagged and (target or term):
            self._site(node, "call", args, kwargs, target=target,
                       terminal=term)
        if target:
            return ({f"call:{target}"}, [])
        vals = list(args) + list(kwargs.values())
        if isinstance(node.func, ast.Attribute):
            vals.append(self._eval(node.func.value, env))
        return _union(vals)


def extract_flow(mod: ModInfo) -> ModuleFlow:
    return _FlowExtractor(mod).run()


# ---------------------------------------------------------------------------
# project index
# ---------------------------------------------------------------------------


class DataflowIndex:
    """Cross-module provenance context built from per-module flow
    summaries. The incremental engine feeds cached summaries for
    unchanged files via `project._gt_dataflow_summaries`; a cold scan
    extracts them all. Marker resolution is summary-based and
    depth-bounded, the same caller-propagation discipline as
    `SpmdIndex.func_bound`."""

    MAX_DEPTH = 4

    def __init__(self, flows: List[ModuleFlow]):
        self.by_module: Dict[str, ModuleFlow] = {
            f.module: f for f in flows}
        self.by_relpath: Dict[str, ModuleFlow] = {
            f.relpath: f for f in flows}
        self.calls_to: Dict[str, List[Tuple[ModuleFlow, FlowSite]]] = {}
        for fl in flows:
            for site in fl.sites:
                if not site.target:
                    continue
                gid = self._global_id(fl, site.target)
                if gid is not None:
                    self.calls_to.setdefault(gid, []).append((fl, site))
        self._param_memo: Dict[Tuple[str, str],
                               Tuple[Set[str], List[dict]]] = {}
        self._ret_memo: Dict[str, Tuple[Set[str], List[dict]]] = {}

    def _global_id(self, fl: ModuleFlow,
                   target: str) -> Optional[str]:
        """Resolve a summary-local call target to "module:qname"
        (mirrors SpmdIndex._global_id, one __init__ re-export hop)."""
        if ":" in target:
            mod_name, name = target.rsplit(":", 1)
            dst = self.by_module.get(mod_name)
            if dst is None:
                return None
            if name in dst.functions:
                return f"{dst.module}:{name}"
            src2 = dst.import_names.get(name)
            if src2:
                dst2 = self.by_module.get(src2)
                if dst2 and name in dst2.functions:
                    return f"{dst2.module}:{name}"
            return None
        if target in fl.functions:
            return f"{fl.module}:{target}"
        return None

    def _func(self, gid: str) -> Optional[Tuple[ModuleFlow, FuncFlow]]:
        mod_name, qname = gid.split(":", 1)
        fl = self.by_module.get(mod_name)
        if fl is None:
            return None
        ff = fl.functions.get(qname)
        return (fl, ff) if ff is not None else None

    # -- marker resolution ----------------------------------------------------

    def resolve(self, fl: ModuleFlow, fn_q: str, tags, chain,
                depth: Optional[int] = None,
                _stack: Optional[frozenset] = None,
                ) -> Tuple[Set[str], List[dict]]:
        """Resolve `param:`/`call:` markers of a value computed inside
        `fl`:`fn_q` to concrete provenance tags + a cross-file chain of
        {path, line, note} steps."""
        depth = self.MAX_DEPTH if depth is None else depth
        stack = _stack or frozenset()
        out: Set[str] = set()
        steps = [{"path": fl.relpath, "line": int(c[0]),
                  "note": str(c[1])} for c in chain]
        if depth <= 0:
            return out, steps[:2 * _CHAIN_CAP]
        for t in sorted(tags):
            if t.startswith("call:"):
                gid = self._global_id(fl, t[len("call:"):])
                if gid is not None and ("ret", gid) not in stack:
                    rt, rs = self.return_tags(
                        gid, depth - 1, stack | {("ret", gid)})
                    out |= rt
                    steps += rs
            elif t.startswith("param:"):
                pt, ps = self.param_tags(
                    fl.module, fn_q, t[len("param:"):], depth - 1, stack)
                out |= pt
                steps += ps
            else:
                out.add(t)
        return out, steps[:2 * _CHAIN_CAP]

    def param_tags(self, module: str, qname: str, pname: str,
                   depth: int, stack: frozenset,
                   ) -> Tuple[Set[str], List[dict]]:
        """Provenance of a parameter = join over every in-project call
        site's matching argument (context-insensitive; no call sites ->
        unresolved -> empty, conservative no-fire)."""
        gid = f"{module}:{qname}"
        key = (gid, pname)
        if key in self._param_memo:
            return self._param_memo[key]
        if depth <= 0 or key in stack:
            return set(), []
        got = self._func(gid)
        if got is None:
            return set(), []
        fl, ff = got
        if pname not in ff.params:
            return set(), []
        pos = ff.params.index(pname)
        out: Set[str] = set()
        steps: List[dict] = []
        for cfl, site in self.calls_to.get(gid, ()):
            val = site.kwargs.get(pname)
            if val is None and pos < len(site.args):
                val = site.args[pos]
            if val is None:
                continue
            t, s = self.resolve(cfl, site.fn, set(val[0]), val[1],
                                depth - 1, stack | {key})
            if t - out:
                out |= t
                steps = s + [{"path": cfl.relpath, "line": site.line,
                              "note": f"passed into {qname}"
                                      f"({pname}=...) here"}]
        self._param_memo[key] = (out, steps)
        return out, steps

    def return_tags(self, gid: str, depth: int, stack: frozenset,
                    ) -> Tuple[Set[str], List[dict]]:
        if gid in self._ret_memo:
            return self._ret_memo[gid]
        got = self._func(gid)
        if got is None:
            return set(), []
        fl, ff = got
        out, steps = self.resolve(fl, ff.qname, set(ff.returns),
                                  ff.ret_chain, depth, stack)
        self._ret_memo[gid] = (out, steps)
        return out, steps

    def site_val(self, fl: ModuleFlow, site: FlowSite,
                 val: list) -> Tuple[Set[str], List[dict]]:
        return self.resolve(fl, site.fn, set(val[0]), val[1])

    # -- dispatch classification ---------------------------------------------

    def is_dispatch(self, site: FlowSite, project) -> bool:
        if site.kind in ("aot_compile", "aot_call"):
            return True
        if site.kind != "call":
            return False
        jits = getattr(project, "jit_by_name", {})
        if site.terminal and site.terminal in jits:
            return True
        if site.target:
            tail = site.target.rsplit(":", 1)[-1].rsplit(".", 1)[-1]
            return tail in jits
        return False


def dataflow_index(project) -> DataflowIndex:
    """Memoized on the project — the dataflow and SPMD engines share
    one `build_project` pass (and this index is built at most once per
    lint run; the incremental cache feeds summaries for unchanged
    files)."""
    idx = getattr(project, "_gt_dataflow", None)
    if idx is None:
        cached: Dict[str, ModuleFlow] = getattr(
            project, "_gt_dataflow_summaries", None) or {}
        flows = []
        for m in project.modules:
            f = cached.get(m.relpath)
            if f is None or f.schema != DATAFLOW_SCHEMA:
                f = extract_flow(m)
            flows.append(f)
        idx = project._gt_dataflow = DataflowIndex(flows)
        project._gt_dataflow_summaries = {
            f.relpath: f for f in flows}
    return idx


# ---------------------------------------------------------------------------
# GT30 registration universe (scan set + reference universe)
# ---------------------------------------------------------------------------


class _RegUniverse:
    def __init__(self, rows: List[list]):
        self.names: Set[str] = set()
        self.serve: Set[str] = set()
        self.ring: Set[Tuple[str, Optional[int]]] = set()
        self.mesh: Set[str] = set()
        self.dyn_register = False
        self.dyn_serve = False
        self.dyn_ring = False
        self.dyn_mesh = False
        for api, name, depth, _line in rows:
            if api == "install_defaults":
                self.dyn_register = True
                continue
            if name is None:
                if api == "register":
                    self.dyn_register = True
                elif api == "serve_variant":
                    self.dyn_serve = True
                elif api == "ring_variant":
                    self.dyn_ring = True
                elif api == "mesh_variant":
                    self.dyn_mesh = True
                continue
            self.names.add(name)
            if api == "serve_variant":
                self.serve.add(name)
            elif api == "ring_variant":
                self.ring.add((name, depth))
            elif api == "mesh_variant":
                self.mesh.add(name)


def registration_universe(project) -> _RegUniverse:
    uni = getattr(project, "_gt_dataflow_regs", None)
    if uni is None:
        idx = dataflow_index(project)
        rows: List[list] = []
        for rel in sorted(idx.by_relpath):
            rows.extend(idx.by_relpath[rel].regs)
        for m in project.ref_modules:
            rows.extend(collect_registrations(m.tree))
        uni = project._gt_dataflow_regs = _RegUniverse(rows)
    return uni


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def _finding(rule: str, mod: ModInfo, line: int, col: int, msg: str,
             chain: Optional[List[dict]] = None) -> Finding:
    f = Finding(rule=rule, path=mod.relpath, line=line, col=col,
                message=msg)
    if chain:
        f.extra["chain"] = chain
    return f


def _hot(mod: ModInfo) -> bool:
    return mod.relpath.replace("\\", "/").startswith(_HOT_PREFIXES)


def _mods_by_relpath(project) -> dict:
    cached = getattr(project, "_gt_df_modmap", None)
    if cached is None:
        cached = {}
        for m in list(project.modules) + list(project.ref_modules):
            cached.setdefault(m.relpath, m)
        project._gt_df_modmap = cached
    return cached


def _chain_waived(project, rule: str, chain) -> bool:
    """Origin waivers: a `# gt: waive GTnn` on ANY step of the
    provenance chain (e.g. the len() origin, or the caller boundary
    that passes the raw value in) suppresses the downstream dispatch
    finding. Waive where the shape is born — one directive at the
    origin instead of one per library dispatch it reaches."""
    mods = _mods_by_relpath(project)
    for step in chain or []:
        m = mods.get(step.get("path"))
        if m is not None and m.is_waived(rule, step.get("line", 0)):
            return True
    return False


def _site_vals(site: FlowSite):
    start = 1 if site.kind == "aot_compile" else 0
    for i, val in enumerate(site.args[start:], start):
        yield f"arg {i}", val
    for k, val in sorted(site.kwargs.items()):
        yield f"{k}=", val


def gt28(mod: ModInfo, project) -> Iterator[Finding]:
    """Raw (unbucketed) dynamic shape reaching a jit/AOT/ring dispatch
    on the hot path: every distinct raw extent compiles a fresh
    executable — the recompile storm the warmup manifests exist to
    prevent. Fix: quantize through pad_to/next_pow2/stack_queries
    before the dispatch (results are sliced back; the kernel shape set
    stays the manifest's)."""
    if not _hot(mod):
        return
    idx = dataflow_index(project)
    s = idx.by_relpath.get(mod.relpath)
    if s is None:
        return
    for site in s.sites:
        if not idx.is_dispatch(site, project):
            continue
        for label, val in _site_vals(site):
            tags, chain = idx.site_val(s, site, val)
            if "raw" in tags and "bucketed" not in tags:
                if _chain_waived(project, "GT28", chain):
                    continue
                what = site.name or site.terminal or site.target
                yield _finding(
                    "GT28", mod, site.line, site.col,
                    f"raw (unbucketed) dynamic shape reaches dispatch "
                    f"{what!r} ({label}) in {site.fn!r}: every distinct "
                    f"extent compiles a fresh executable under traffic "
                    f"— pad through pad_to/next_pow2/stack_queries so "
                    f"the shape set stays the warmup manifest's",
                    chain=chain)
                break


def gt29(mod: ModInfo, project) -> Iterator[Finding]:
    """f32-cast value flowing into an exact-f64 consumer. The f32 tag is
    sticky: `.astype(float64)` / `np.asarray(x, np.float64)` over an
    already-rounded f32 value restores nothing — the canonical recompute
    (`_canonical_dists`) must run over the ORIGINAL f64 inputs. Fires at
    the laundering upcast and at callee parameters named `*_f64`."""
    idx = dataflow_index(project)
    s = idx.by_relpath.get(mod.relpath)
    if s is None:
        return
    for site in s.sites:
        if site.kind == "f64cast":
            if not site.args:
                continue
            tags, chain = idx.site_val(s, site, site.args[0])
            if "f32" in tags and "f64" not in tags \
                    and not _chain_waived(project, "GT29", chain):
                yield _finding(
                    "GT29", mod, site.line, site.col,
                    f"f64 upcast of an f32-cast value in {site.fn!r}: "
                    f"the input was already rounded to f32 — upcasting "
                    f"does not restore exactness; run the canonical f64 "
                    f"recompute over the original f64 inputs instead",
                    chain=chain)
            continue
        if site.kind not in ("call", "aot_compile", "aot_call"):
            continue
        gid = idx._global_id(s, site.target) if site.target else None
        got = idx._func(gid) if gid else None
        if got is None:
            continue
        _, ff = got
        for pos, val in enumerate(site.args):
            pname = ff.params[pos] if pos < len(ff.params) else ""
            if not pname.endswith("f64"):
                continue
            tags, chain = idx.site_val(s, site, val)
            if "f32" in tags and "f64" not in tags \
                    and not _chain_waived(project, "GT29", chain):
                yield _finding(
                    "GT29", mod, site.line, site.col,
                    f"f32-cast value passed as exact-f64 parameter "
                    f"{pname!r} of {ff.qname!r}: the consumer assumes "
                    f"full f64 precision but the value was rounded to "
                    f"f32 upstream — feed the original f64 input (or "
                    f"its canonical recompute)",
                    chain=chain)
        for pname, val in sorted(site.kwargs.items()):
            if not pname.endswith("f64"):
                continue
            tags, chain = idx.site_val(s, site, val)
            if "f32" in tags and "f64" not in tags \
                    and not _chain_waived(project, "GT29", chain):
                yield _finding(
                    "GT29", mod, site.line, site.col,
                    f"f32-cast value passed as exact-f64 parameter "
                    f"{pname!r} of {ff.qname!r}: upcasting rounded f32 "
                    f"does not restore exactness — feed the original "
                    f"f64 input (or its canonical recompute)",
                    chain=chain)


def _check_key(name: str, uni: _RegUniverse) -> Optional[str]:
    """None when some registration site can produce `name`; else a
    human-readable reason it is unmatchable."""
    parts = name.split("@")
    prefix = parts[0]
    if not parts[1:]:
        if uni.dyn_register or name in uni.names:
            return None
        return (f"base key {name!r} is registered nowhere "
                f"(no registry.register site names it)")
    for m in parts[1:]:
        if m == "serve":
            if not (uni.dyn_serve or prefix in uni.serve):
                return (f"no serve_variant registration exists for "
                        f"base {prefix!r}")
        elif m.startswith("ring"):
            spec = m[len("ring"):].split("+", 1)[0]
            try:
                depth = int(spec)
            except ValueError:
                return None  # dynamic depth spelled literally: skip
            if not (uni.dyn_ring or (prefix, depth) in uni.ring
                    or (prefix, None) in uni.ring):
                return (f"no ring_variant registration for base "
                        f"{prefix!r} at depth {depth}")
        elif m.startswith("mesh"):
            if not (uni.dyn_mesh or prefix in uni.mesh):
                return (f"no mesh_variant registration exists for "
                        f"base {prefix!r}")
        else:
            return None  # unknown marker: out of contract, skip
        prefix = f"{prefix}@{m}"
    return None


def gt30(mod: ModInfo, project) -> Iterator[Finding]:
    """AOT/ring registry lookup whose literal key no registration site
    in the project (scan set or reference universe) can produce — GT13
    made interprocedural. The warmup manifest can never warm this
    caller: first traffic pays a KeyError or an inline compile. Keys
    composed from variant-constructor returns are definitionally
    registered and are skipped; dynamic registrations (install_defaults
    sweeps, computed names) wildcard their key space."""
    idx = dataflow_index(project)
    s = idx.by_relpath.get(mod.relpath)
    if s is None:
        return
    uni = registration_universe(project)
    for site in s.sites:
        if site.kind != "aot_compile" or not site.name:
            continue
        if "*" in site.name:
            continue  # composed from a variant-constructor return
        reason = _check_key(site.name, uni)
        if reason is not None:
            yield _finding(
                "GT30", mod, site.line, site.col,
                f"registry lookup {site.name!r} in {site.fn!r} can "
                f"match no registration key shape in the project: "
                f"{reason} — the warmup manifest can never warm this "
                f"call site (KeyError or inline compile under traffic)")


def gt31(mod: ModInfo, project) -> Iterator[Finding]:
    """device→host→device bounce: a jax.device_get result transitively
    re-entering device_put or a dispatch on the hot path — two
    transfers (plus a host sync) where zero were needed. Keep the value
    on device: reuse the device reference (the launch holds it), or
    donate/alias through the stager."""
    if not _hot(mod):
        return
    idx = dataflow_index(project)
    s = idx.by_relpath.get(mod.relpath)
    if s is None:
        return
    for site in s.sites:
        is_put = site.kind == "device_put"
        if not is_put and not idx.is_dispatch(site, project):
            continue
        for label, val in _site_vals(site):
            tags, chain = idx.site_val(s, site, val)
            if "host" in tags:
                if _chain_waived(project, "GT31", chain):
                    continue
                what = ("jax.device_put" if is_put else
                        site.name or site.terminal or site.target)
                yield _finding(
                    "GT31", mod, site.line, site.col,
                    f"device→host→device bounce in {site.fn!r}: a "
                    f"jax.device_get result re-enters the device "
                    f"through {what!r} ({label}) — keep the device "
                    f"reference (the launch still holds it) instead of "
                    f"paying a round-trip transfer plus a host sync",
                    chain=chain)
                break


DATAFLOW_RULES = {"GT28": gt28, "GT29": gt29, "GT30": gt30,
                  "GT31": gt31}
