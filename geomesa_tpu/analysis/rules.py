"""GT01..GT06 rule implementations.

Each rule is a generator ``rule(mod, project) -> Iterator[Finding]``.
Rules never import the code under analysis; everything is answered from
the per-module AST index (`ModInfo`) and the cross-module name universe
(`Project`). Precision notes live next to each rule — the gate runs with
--fail-on warn, so a rule that cries wolf on the shipped tree is a bug
here, not in the tree.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from geomesa_tpu.analysis.model import Finding
from geomesa_tpu.analysis.modinfo import JitDef, ModInfo

_SYNC_ATTRS = {"block_until_ready", "item", "tolist"}
_SYNC_NP_FNS = {"asarray", "array"}
_HOST_CAST_BUILTINS = {"float", "int", "bool"}
_VALID_WORD = "valid"


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _finding(rule: str, mod: ModInfo, node: ast.AST, msg: str) -> Finding:
    return Finding(rule=rule, path=mod.relpath,
                   line=getattr(node, "lineno", 0),
                   col=getattr(node, "col_offset", 0), message=msg)


def _callee_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _map_call_args(call: ast.Call, jd: JitDef):
    """Yield (param_name_or_None, value_node) for each argument."""
    for i, a in enumerate(call.args):
        name = jd.params[i] if i < len(jd.params) else None
        yield name, i, a
    for kw in call.keywords:
        yield kw.arg, None, kw.value


# -- GT01: retrace storms ---------------------------------------------------


def gt01(mod: ModInfo, project) -> Iterator[Finding]:
    """Static jit arguments fed loop-varying or unhashable values.

    (a) a `for` loop variable passed to a static param retraces every
    iteration; (b) a list/set/dict literal passed to a static param is
    unhashable and fails (or, for tuples of arrays, retraces per call).
    `while` grow-loops (pow2 capacity style) are deliberately exempt —
    they bound their own retrace count.
    """
    for call in ast.walk(mod.tree):
        if not isinstance(call, ast.Call):
            continue
        callee = _callee_name(call)
        jd = project.jit_by_name.get(callee) if callee else None
        if jd is None:
            continue
        statics = jd.static_params()
        loop_vars = _enclosing_for_targets(mod, call)
        for pname, pos, value in _map_call_args(call, jd):
            is_static = (pname in statics) or (pos is not None
                                               and pos in jd.static_nums)
            if not is_static:
                continue
            if isinstance(value, (ast.List, ast.Set, ast.Dict)):
                yield _finding(
                    "GT01", mod, value,
                    f"unhashable {type(value).__name__.lower()} literal "
                    f"passed to static argument "
                    f"{pname or pos!r} of jitted {callee!r}")
            elif isinstance(value, ast.Name) and value.id in loop_vars:
                yield _finding(
                    "GT01", mod, call,
                    f"loop variable {value.id!r} passed to static argument "
                    f"{pname or pos!r} of jitted {callee!r}: retraces every "
                    f"iteration")


def _enclosing_for_targets(mod: ModInfo, node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
        if isinstance(anc, ast.For):
            out |= {n.id for n in ast.walk(anc.target)
                    if isinstance(n, ast.Name)}
    return out


# -- GT02: implicit host transfers inside jit scope -------------------------


def gt02(mod: ModInfo, project) -> Iterator[Finding]:
    """Host operations on traced values inside a jitted function body:
    `np.asarray`/`np.array` on a tracer, `float()`/`int()`/`bool()`
    or `.item()`/`.tolist()` on a tracer, and Python `for` loops
    iterating a tracer. Static params are excluded (they are Python
    values at trace time)."""
    for jd in mod.jit_defs:
        if jd.func is None:
            continue
        tracers = set(jd.params) - jd.static_params()
        if not tracers:
            continue
        for node in ast.walk(jd.func):
            if isinstance(node, ast.Call):
                hit = _gt02_call_hit(mod, node, tracers)
                if hit:
                    yield _finding("GT02", mod, node,
                                   f"{hit} on traced value inside jitted "
                                   f"{jd.name!r}: forces a device->host "
                                   f"transfer per call")
            elif isinstance(node, ast.For):
                if _names_in(node.iter) & tracers:
                    yield _finding(
                        "GT02", mod, node,
                        f"host `for` loop over traced value in jitted "
                        f"{jd.name!r}: unrolls/transfers instead of "
                        f"staying on device")


def _gt02_call_hit(mod: ModInfo, call: ast.Call,
                   tracers: Set[str]) -> Optional[str]:
    f = call.func
    args_names = set()
    for a in call.args:
        args_names |= _names_in(a)
    if not (args_names & tracers):
        # .item() takes no args; check the receiver instead
        if (isinstance(f, ast.Attribute) and f.attr in ("item", "tolist")
                and _names_in(f.value) & tracers):
            return f".{f.attr}()"
        return None
    if isinstance(f, ast.Attribute) and mod.is_numpy_ref(f.value):
        if f.attr in _SYNC_NP_FNS | {"frombuffer", "copy"}:
            return f"np.{f.attr}()"
    if isinstance(f, ast.Name) and f.id in _HOST_CAST_BUILTINS:
        return f"{f.id}()"
    return None


# -- GT03: dtype drift ------------------------------------------------------


def gt03(mod: ModInfo, project) -> Iterator[Finding]:
    """float64 indicators inside jitted bodies or module-local helpers
    transitively called from them — the f32 kernel paths. An explicit
    `# gt: f64-refine` comment on the line (or the line above) waives
    the deliberate refine arithmetic."""
    kernel_fns = _f32_kernel_functions(mod)
    seen: Set[int] = set()
    for fn in kernel_fns:
        for node in ast.walk(fn):
            hit = _f64_indicator(mod, node)
            if hit is None or node.lineno in seen:
                continue
            seen.add(node.lineno)
            yield _finding(
                "GT03", mod, node,
                f"{hit} reachable from f32 kernel path {fn.name!r} "
                f"(waive deliberate refinement with '# gt: f64-refine')")


def _f32_kernel_functions(mod: ModInfo) -> List[ast.FunctionDef]:
    roots = [jd.func for jd in mod.jit_defs if jd.func is not None]
    out: List[ast.FunctionDef] = []
    seen: Set[str] = set()
    queue = list(roots)
    while queue:
        fn = queue.pop()
        if fn.name in seen:
            continue
        seen.add(fn.name)
        out.append(fn)
        for call in ast.walk(fn):
            if isinstance(call, ast.Call):
                name = _callee_name(call)
                target = mod.functions.get(name) if name else None
                if target is not None and target.name not in seen:
                    queue.append(target)
    return out


def _f64_indicator(mod: ModInfo, node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and node.attr == "float64":
        if isinstance(node.value, ast.Name) and (
                mod.is_numpy_ref(node.value) or mod.is_jnp_ref(node.value)):
            return f"{node.value.id}.float64"
    if (isinstance(node, ast.Constant) and isinstance(node.value, str)
            and node.value == "float64"):
        return "'float64' literal"
    return None


# -- GT04: unsynced timing --------------------------------------------------


def gt04(mod: ModInfo, project) -> Iterator[Finding]:
    """A timestamp pair bracketing a device dispatch with no sync
    (`block_until_ready`, `jax.device_get`, `np.asarray`/`np.array`,
    `.item()`, `float()`/`int()`) between the dispatch and the closing
    timestamp measures dispatch, not compute. Events are collected in
    source order per function; nested defs are separate scopes."""
    order = {"device": 0, "sync": 1, "timer": 2}
    for fn in _all_functions(mod):
        # same-line ordering: a sync wrapping a device call on one line
        # (`np.asarray(kern(x))`) synchronizes it, so device events sort
        # before syncs; timers close the line
        events = sorted(_gt04_events(mod, fn),
                        key=lambda e: (e[1], order[e[0]]))
        saw_timer = False
        pending: Optional[Tuple[int, str]] = None
        for kind, line, detail in events:
            if kind == "timer":
                if saw_timer and pending is not None:
                    yield Finding(
                        rule="GT04", path=mod.relpath, line=line, col=0,
                        message=(f"timestamp at line {line} closes a timing "
                                 f"window over device call {pending[1]!r} "
                                 f"(line {pending[0]}) with no "
                                 f"block_until_ready/sync in between"))
                saw_timer = True
                pending = None
            elif kind == "device" and saw_timer:
                pending = (line, detail)
            elif kind == "sync":
                pending = None


def _all_functions(mod: ModInfo):
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _gt04_events(mod: ModInfo, fn: ast.FunctionDef):
    own_nested = {n for n in ast.walk(fn)
                  if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                    ast.Lambda))
                  and n is not fn}
    skip: Set[int] = set()
    for n in own_nested:
        for sub in ast.walk(n):
            skip.add(id(sub))
    for node in ast.walk(fn):
        if id(node) in skip or not isinstance(node, ast.Call):
            continue
        line = node.lineno
        if mod.is_timer_call(node):
            yield ("timer", line, "")
            continue
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in _SYNC_ATTRS or f.attr == "device_get":
                yield ("sync", line, f.attr)
                continue
            if (f.attr in _SYNC_NP_FNS and isinstance(f.value, ast.Name)
                    and mod.is_numpy_ref(f.value)):
                yield ("sync", line, f"np.{f.attr}")
                continue
        if isinstance(f, ast.Name) and f.id in _HOST_CAST_BUILTINS:
            yield ("sync", line, f.id)
            continue
        callee = _callee_name(node)
        if callee is None:
            continue
        if callee.lstrip("_").startswith("sync"):
            # sync()/_sync() wrapper idiom (bench.py, scripts/_util.py)
            yield ("sync", line, callee)
            continue
        resolved = _resolve_local_def(mod, node, callee)
        if resolved is not None:
            def_node, is_jit = resolved
            if is_jit:
                yield ("device", line, callee)
            elif isinstance(def_node, ast.FunctionDef) and _body_syncs(
                    mod, def_node):
                yield ("sync", line, callee)
            # plain local call without syncs: neither device nor sync —
            # that function is linted on its own
            continue
        if callee in project_jit_names(mod):
            yield ("device", line, callee)


def _local_defs(mod: ModInfo):
    """name -> [(def_node, enclosing_function|None, is_jit)] for every
    function def and jit-binding assignment in the module, with the
    scope each lives in. Cached per module."""
    cache = getattr(mod, "_gt_local_defs", None)
    if cache is not None:
        return cache
    defs = {}
    jitted_fn_nodes = {id(jd.func) for jd in mod.jit_defs
                       if jd.kind == "function" and jd.func is not None}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.FunctionDef):
            defs.setdefault(node.name, []).append(
                (node, mod.enclosing_function(node),
                 id(node) in jitted_fn_nodes))
        elif (isinstance(node, ast.Assign) and len(node.targets) == 1
              and isinstance(node.value, ast.Call)
              and mod._jit_call_parts(node.value) is not None):
            t = node.targets[0]
            name = t.id if isinstance(t, ast.Name) else (
                t.attr if isinstance(t, ast.Attribute) else None)
            if name is not None:
                defs.setdefault(name, []).append(
                    (node, mod.enclosing_function(node), True))
    mod._gt_local_defs = defs  # type: ignore[attr-defined]
    return defs


def _resolve_local_def(mod: ModInfo, call: ast.Call, name: str):
    """Resolve `name` at this call site to the nearest definition in the
    call's lexical scope chain (innermost wins; within a scope, the
    last definition at or before the call line). Returns
    (def_node, is_jit) or None. This is what keeps a nested plain
    `run()` closure distinct from a module-level `run = jax.jit(...)`
    three functions away (bench.py's shape)."""
    cands = _local_defs(mod).get(name)
    if not cands:
        return None
    chain = [a for a in mod.ancestors(call)
             if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))]
    chain.append(None)  # module scope last
    for scope in chain:
        scoped = sorted((c for c in cands if c[1] is scope),
                        key=lambda c: c[0].lineno)
        if not scoped:
            continue
        pick = None
        for c in scoped:
            if c[0].lineno <= call.lineno:
                pick = c
        pick = pick or scoped[-1]
        return pick[0], pick[2]
    return None


def _body_syncs(mod: ModInfo, fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and (
                f.attr in _SYNC_ATTRS or f.attr == "device_get"):
            return True
        if isinstance(f, ast.Attribute) and f.attr in _SYNC_NP_FNS and \
                isinstance(f.value, ast.Name) and mod.is_numpy_ref(f.value):
            return True
        if isinstance(f, ast.Name) and f.id in _HOST_CAST_BUILTINS:
            return True
    return False


def project_jit_names(mod: ModInfo) -> Set[str]:
    # populated by the linter before rules run (project-wide jit names);
    # falling back to the module's own defs keeps ModInfo usable alone
    names = getattr(mod, "_gt_project_jit_names", None)
    if names is not None:
        return names
    return {jd.name for jd in mod.jit_defs}


# -- GT05: dead jit entry points --------------------------------------------


def gt05(mod: ModInfo, project) -> Iterator[Finding]:
    """A jitted definition nobody references is a stale entry point:
    it keeps a compile cache alive and rots silently when signatures
    drift. References are counted across the scan roots plus the repo's
    tests/bench/scripts (name loads, attribute loads, import aliases,
    and __all__ exports)."""
    for jd in mod.jit_defs:
        refs = project.reference_count(jd.name)
        if refs == 0:
            yield Finding(
                rule="GT05", path=mod.relpath, line=jd.line, col=0,
                message=(f"jitted callable {jd.name!r} has no call sites "
                         f"anywhere in the scanned tree: dead entry point"))


# -- GT06: inconsistent mask plumbing ---------------------------------------


def gt06(mod: ModInfo, project) -> Iterator[Finding]:
    """Within one function, sibling call sites of the same callee whose
    results are mask-combined: if one site ANDs a validity token
    (`*.valid`, `valid`, `row_valid`, ...) into its result and another
    does not, an invalid row can be resurrected on the second path —
    the planner cache-branch band-scatter bug, generalized."""
    for fn in _all_functions(mod):
        sites = _gt06_sites(mod, fn)
        by_callee = {}
        for site in sites:
            by_callee.setdefault(site["callee"], []).append(site)
        for callee, group in by_callee.items():
            if len(group) < 2:
                continue
            with_valid = [s for s in group if s["valid"]]
            without = [s for s in group if not s["valid"]]
            if not with_valid or not without:
                continue
            for s in without:
                yield Finding(
                    rule="GT06", path=mod.relpath,
                    line=s["line"], col=s["col"],
                    message=(f"call site of {callee!r} does not AND a "
                             f"validity mask into its result, but its "
                             f"sibling at line {with_valid[0]['line']} "
                             f"does: invalid rows can leak through this "
                             f"branch"))


def _gt06_sites(mod: ModInfo, fn: ast.FunctionDef):
    """Call sites inside `fn` whose results are bound to names: each gets
    a signature `valid` = does any `&`-combination of a bound name, in
    this site's block (the call statement and its following siblings),
    involve a validity token."""
    sites = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call):
            continue
        callee = _callee_name(node.value)
        if callee is None:
            continue
        bound: Set[str] = set()
        for t in node.targets:
            if isinstance(t, ast.Name):
                bound.add(t.id)
            elif isinstance(t, ast.Tuple):
                bound |= {e.id for e in t.elts if isinstance(e, ast.Name)}
        if not bound:
            continue
        region = _region_after(mod, fn, node)
        sites.append({
            "callee": callee, "line": node.value.lineno,
            "col": node.value.col_offset,
            "valid": _valid_anded(region, bound),
        })
    return sites


def _region_after(mod: ModInfo, fn: ast.FunctionDef,
                  stmt: ast.stmt) -> List[ast.stmt]:
    """The statement list containing `stmt`, from `stmt` onward — the
    site's block scope (masking applied in an unrelated earlier branch
    must not vouch for this one)."""
    # find the ancestor statement whose parent holds a stmt list with it
    target = stmt
    parent = mod.parent(target)
    while parent is not None and not isinstance(parent, (
            ast.FunctionDef, ast.AsyncFunctionDef, ast.If, ast.For,
            ast.While, ast.With, ast.Try, ast.Module)):
        target = parent
        parent = mod.parent(target)
    if parent is None:
        return [stmt]
    for blockname in ("body", "orelse", "finalbody"):
        block = getattr(parent, blockname, None)
        if isinstance(block, list) and target in block:
            i = block.index(target)
            return block[i:]
    return [stmt]


def _valid_anded(region: List[ast.stmt], bound: Set[str]) -> bool:
    for stmt in region:
        for node in ast.walk(stmt):
            expr = None
            if isinstance(node, ast.BinOp) and isinstance(node.op,
                                                          ast.BitAnd):
                expr = node
            elif isinstance(node, ast.AugAssign) and isinstance(
                    node.op, ast.BitAnd):
                expr = node
            if expr is None:
                continue
            names = _names_in(expr)
            if not (names & bound):
                continue
            if _has_valid_token(expr):
                return True
    return False


def _has_valid_token(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        ident = None
        if isinstance(sub, ast.Name):
            ident = sub.id
        elif isinstance(sub, ast.Attribute):
            ident = sub.attr
        if ident is not None and _VALID_WORD in ident.lower():
            return True
    return False


# GT13 scope: the serving and planning layers — the paths a live request
# rides. Kernel modules (engine/) define their jits once at module level
# where the ExecutableRegistry's default sweep and the warmup manifests
# see them; a jax.jit created inside serve/ or plan/ is invisible to
# both, so its compile happens inline under traffic.
_GT13_PREFIXES = ("geomesa_tpu/serve/", "geomesa_tpu/plan/")


def gt13(mod: ModInfo, project) -> Iterator[Finding]:
    """GT13: jax.jit call sites on the serve/plan hot path.

    Flags every `jax.jit` use (decorator, `functools.partial(jax.jit,
    ...)` decorator, or direct call) in modules under the serve/plan
    prefixes. Precision: the rule is path-scoped, so engine kernels and
    the compilecache's own registry wrapper never fire; deliberate
    sites waive inline (`# gt: waive GT13`) like every other rule."""
    path = mod.relpath.replace("\\", "/")
    if not any(p in path for p in _GT13_PREFIXES):
        return
    seen: Set[int] = set()
    for node in ast.walk(mod.tree):
        hit = None
        if isinstance(node, ast.Call) and mod.is_jit_ref(node.func):
            hit = node
        elif (isinstance(node, ast.Call) and mod.is_partial_ref(node.func)
              and node.args and mod.is_jit_ref(node.args[0])):
            hit = node
        elif isinstance(node, ast.Attribute) and mod.is_jit_ref(node):
            hit = node  # @jax.jit decorator / bare jax.jit reference
        elif (isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load)
              and mod.is_jit_ref(node)):
            hit = node  # @jit decorator via a from-import alias
        if hit is None or hit.lineno in seen:
            continue
        seen.add(hit.lineno)
        yield _finding(
            "GT13", mod, hit,
            "jax.jit on the serve/plan hot path bypasses the "
            "compilecache ExecutableRegistry: warmup manifests cannot "
            "pre-compile it, so it stalls a live request. Define the "
            "kernel in engine/ (the registry's default sweep) or "
            "register it explicitly; waive deliberate sites.")


# GT14 scope: the dependency-boundary layers the recovery fabric
# (geomesa_tpu.faults) covers. A swallowed exception there hides a
# failure the retry/breaker/quarantine machinery should have typed; an
# unbounded retry loop is the exact shape faults.retry_call exists to
# replace (bounded attempts, full-jitter backoff, deadline-aware).
_GT14_PREFIXES = ("geomesa_tpu/store/", "geomesa_tpu/kafka/",
                  "geomesa_tpu/serve/")

_GT14_BROAD = {"Exception", "BaseException"}


def _gt14_swallows(handler: ast.ExceptHandler) -> bool:
    """True when the handler body discards the error: only pass /
    ellipsis statements. A body that logs, responds, returns a value or
    re-raises is handling, not swallowing; `continue` is the retry
    shape — the while-True branch owns that (flagging it here too would
    double-report every retry loop)."""
    for stmt in handler.body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(
                stmt.value, ast.Constant) and stmt.value.value is Ellipsis:
            continue
        return False
    return True


def _gt14_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    names = []
    if isinstance(t, ast.Tuple):
        names = [e for e in t.elts]
    else:
        names = [t]
    for n in names:
        ident = n.id if isinstance(n, ast.Name) else (
            n.attr if isinstance(n, ast.Attribute) else None)
        if ident in _GT14_BROAD:
            return True
    return False


def gt14(mod: ModInfo, project) -> Iterator[Finding]:
    """GT14: silent error swallows + unbounded retry loops at the
    storage/Kafka/serve dependency boundaries.

    (a) `except:` / `except Exception:` whose body only passes —
    the failure vanishes instead of surfacing typed (or feeding the
    breaker/quarantine fabric). (b) a `while True:` loop with NO
    break/return anywhere in its body and no raise on its exception
    paths, wrapping a try whose handler swallows around at least one
    call — the retry-forever shape that ignores deadlines and retries
    permanent errors. Both waivable inline for the documented deliberate
    cases (the shipped tree is clean modulo those)."""
    path = mod.relpath.replace("\\", "/")
    if not any(p in path for p in _GT14_PREFIXES):
        return
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ExceptHandler):
            if _gt14_broad(node) and _gt14_swallows(node):
                yield _finding(
                    "GT14", mod, node,
                    "broad except swallows the error (body is only "
                    "pass): failures at a dependency boundary must "
                    "surface typed or feed the recovery fabric; waive "
                    "deliberate degrade paths inline")
        elif isinstance(node, ast.While):
            if not (isinstance(node.test, ast.Constant)
                    and bool(node.test.value)):
                continue
            if _gt14_has_exit(node):
                continue  # the loop has a non-exceptional exit
            body_nodes = list(_gt14_loop_nodes(node))
            for t in (n for n in body_nodes if isinstance(n, ast.Try)):
                has_io_call = any(isinstance(n, ast.Call)
                                  for s in t.body for n in ast.walk(s))
                swallowing = [h for h in t.handlers
                              if not any(isinstance(n, ast.Raise)
                                         for s in h.body
                                         for n in ast.walk(s))]
                if has_io_call and swallowing:
                    yield _finding(
                        "GT14", mod, node,
                        "unbounded `while True` retry loop: no "
                        "break/return and the except path swallows — "
                        "this retries forever past any deadline; use "
                        "faults.retry_call (bounded, jittered, "
                        "deadline-aware)")
                    break


def _gt14_loop_nodes(loop: ast.While):
    """Walk the loop body, not descending into nested function defs
    (their control flow is not the loop's)."""
    stack = list(loop.body)
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        yield n
        for child in ast.iter_child_nodes(n):
            stack.append(child)


def _gt14_has_exit(loop: ast.While) -> bool:
    """True when the loop can exit non-exceptionally: a `return`
    anywhere in its body (returns leave the whole function, nested
    loops included), or a `break` belonging to THIS loop — a break
    inside a nested while/for's BODY exits only that inner loop and
    must not vouch for the outer one, but a break in a nested loop's
    `else:` clause targets the ENCLOSING loop (Python's for/else) and
    counts. Nested function defs are skipped."""
    stack = [(n, False) for n in loop.body]
    while stack:
        n, nested = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        if isinstance(n, ast.Return):
            return True
        if isinstance(n, ast.Break) and not nested:
            return True
        if isinstance(n, (ast.While, ast.For, ast.AsyncFor)):
            for child in n.body:
                stack.append((child, True))
            for child in n.orelse:  # for/else break targets OUR loop
                stack.append((child, nested))
            continue
        for child in ast.iter_child_nodes(n):
            stack.append((child, nested))
    return False


# GT15 scope: the layers whose timings feed spans, ServeEvents and the
# latency histograms — serve/, engine/ and the telemetry package
# itself. `time.time()` is wall clock: NTP steps it backward and slews
# it, so a duration measured with it can be negative or skewed; every
# span/latency in these layers must use perf_counter/monotonic. The
# second hazard is a tracer span opened without `with`: _LiveSpan only
# records (and pops the parent stack) on __exit__, so a bare
# `TRACER.span(...)` call leaks an unbalanced open span.
_GT15_PREFIXES = ("geomesa_tpu/serve/", "geomesa_tpu/engine/",
                  "geomesa_tpu/telemetry/")


def _gt15_is_time_time(mod: ModInfo, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if (isinstance(f, ast.Attribute) and f.attr == "time"
            and isinstance(f.value, ast.Name) and f.value.id == "time"):
        return True  # time.time()
    if isinstance(f, ast.Name) and f.id == "time":
        # bare time() — only when `from time import time` is in scope
        for imp in ast.walk(mod.tree):
            if isinstance(imp, ast.ImportFrom) and imp.module == "time":
                if any(a.name == "time" for a in imp.names):
                    return True
    return False


def _gt15_scopes(mod: ModInfo):
    """(scope_node, body_nodes) per function plus the module level, each
    excluding nested function bodies — a name assigned in one function
    never aliases the same-named local of another."""
    fns = list(_all_functions(mod))
    own = {id(f) for f in fns}
    for scope in [mod.tree] + fns:
        nodes = []
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            n = stack.pop()
            if id(n) in own:
                # a (possibly directly-seeded) nested/top-level def is
                # its OWN scope: never leak its body into this one — a
                # module-level `t0 = time.time()` timestamp must not
                # pair with an unrelated `x - t0` in some function
                continue
            nodes.append(n)
            for child in ast.iter_child_nodes(n):
                stack.append(child)
        yield scope, nodes


def gt15(mod: ModInfo, project) -> Iterator[Finding]:
    """GT15: wall-clock durations + un-scoped spans (telemetry layers).

    (a) `time.time()` whose result feeds a subtraction — directly
    (`time.time() - t0`) or via a name later used as a `-` operand in
    the same scope. Plain timestamping (`event.ts = time.time()`) is
    fine: the wall clock is the right clock for *when*, never for *how
    long*. (b) a `.span(...)` call that is not the context expression
    of a `with` item (or an `enter_context(...)` argument)."""
    path = mod.relpath.replace("\\", "/")
    if not any(p in path for p in _GT15_PREFIXES):
        return
    flagged: Set[int] = set()
    for _scope, nodes in _gt15_scopes(mod):
        timed_names: dict = {}  # name -> time.time() call line
        subs = []
        for n in nodes:
            if (isinstance(n, ast.Assign) and len(n.targets) == 1
                    and isinstance(n.targets[0], ast.Name)
                    and _gt15_is_time_time(mod, n.value)):
                timed_names[n.targets[0].id] = n.value.lineno
            elif isinstance(n, ast.BinOp) and isinstance(n.op, ast.Sub):
                subs.append(n)
        for sub in subs:
            for operand in (sub.left, sub.right):
                for c in ast.walk(operand):
                    if _gt15_is_time_time(mod, c) and \
                            c.lineno not in flagged:
                        flagged.add(c.lineno)
                        yield _finding(
                            "GT15", mod, c,
                            "time.time() used in a subtraction: wall "
                            "clock measures *when*, not *how long* — "
                            "use time.perf_counter()/monotonic() for "
                            "durations")
            names = _names_in(sub)
            for name in sorted(names & set(timed_names)):
                line = timed_names[name]
                if line in flagged:
                    continue
                flagged.add(line)
                yield Finding(
                    rule="GT15", path=mod.relpath, line=line, col=0,
                    message=(f"time.time() result {name!r} measures a "
                             f"duration (subtracted at line "
                             f"{sub.lineno}): wall clock is not "
                             f"monotonic — use perf_counter/monotonic"))
    for node in ast.walk(mod.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "span"):
            continue
        parent = mod.parent(node)
        if isinstance(parent, ast.withitem) and parent.context_expr is node:
            continue
        if (isinstance(parent, ast.Call)
                and isinstance(parent.func, ast.Attribute)
                and parent.func.attr == "enter_context"):
            continue
        yield _finding(
            "GT15", mod, node,
            "tracer span opened outside a `with` block: spans record "
            "only on __exit__, so this leaks an unbalanced open span "
            "(wrap in `with TRACER.span(...)`, or waive a deliberate "
            "manual open)")


# GT16 scope: the pipelined dispatch path (serve/pipeline.py). The
# pipeline's whole point is that prepare/transfer/launch return before
# the device finishes — window N+1's host work overlaps window N's
# kernel. A blocking call inside those stages (block_until_ready, a
# future .result(), an explicit jax.device_get host read) re-serializes
# exactly the host gap the pipeline exists to remove, and it does so
# silently: results stay correct, only the overlap dies. Blocking is
# the COMPLETER's job (the sync stage). Waivable inline for documented
# deliberate syncs; the shipped tree is clean.
_GT16_PATH = "geomesa_tpu/serve/pipeline.py"
_GT16_STAGE_MARKERS = ("prepare", "transfer", "launch")
_GT16_STAGE_NAMES = {"submit"}
_GT16_BLOCKING = {
    "block_until_ready": "device sync",
    "result": "future wait",
    "device_get": "host read",
}


def _gt16_stage_functions(mod: ModInfo):
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        name = node.name.lstrip("_")
        if name in _GT16_STAGE_NAMES or any(
                m in name for m in _GT16_STAGE_MARKERS):
            yield node


def gt16(mod: ModInfo, project) -> Iterator[Finding]:
    """GT16: blocking calls inside pipeline prepare/transfer/launch
    stages.

    Flags `.block_until_ready()`, `.result()` (futures; `set_result` is
    a resolve, not a wait, and is not matched) and `jax.device_get` /
    bare `device_get` calls lexically inside the stage functions of
    serve/pipeline.py (names containing prepare/transfer/launch, plus
    `submit`). `np.asarray` on a device array blocks too but is
    indistinguishable statically from legitimate host stacking — use
    the explicit `jax.device_get` spelling for intentional reads so
    this rule can see them (and waive)."""
    path = mod.relpath.replace("\\", "/")
    if _GT16_PATH not in path:
        return
    seen: Set[int] = set()
    for fn in _gt16_stage_functions(mod):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            ident = None
            if isinstance(f, ast.Attribute):
                ident = f.attr
            elif isinstance(f, ast.Name):
                ident = f.id if f.id == "device_get" else None
            what = _GT16_BLOCKING.get(ident or "")
            if what is None or node.lineno in seen:
                continue
            seen.add(node.lineno)
            yield _finding(
                "GT16", mod, node,
                f"blocking call ({ident}: {what}) inside pipeline stage "
                f"{fn.name!r}: prepare/transfer/launch must return "
                f"before the device finishes — window overlap dies "
                f"silently otherwise. Move the wait to the completer's "
                f"sync stage, or waive a documented deliberate sync")


# GT17 scope: subscription listener/callback bodies under subscribe/
# and kafka/. KafkaFeatureCache listeners are invoked during the
# store's poll fold — with the store RLock held — and the subscribe
# evaluator's delta listener runs on EVERY folded message. A blocking
# call there (file I/O, a future .result(), a device sync/transfer, a
# sleep) stalls the fold for every topic consumer behind the lock and
# re-introduces exactly the emit-under-lock hazard the _emit snapshot
# discipline removed. Listeners BUFFER; the post-fold pump (outside
# the lock) evaluates. Two detection axes: functions whose names mark
# them as listener/callback bodies (contains "listener"/"callback",
# or an `on_*` prefix), and local functions passed by name to
# add_listener(...)/add_fold_hook(...).
_GT17_PREFIXES = ("geomesa_tpu/subscribe/", "geomesa_tpu/kafka/")
_GT17_NAME_MARKERS = ("listener", "callback")
_GT17_NAME_PREFIXES = ("on_",)
_GT17_REGISTER_CALLS = {"add_listener", "add_fold_hook"}
_GT17_BLOCKING = {
    "open": "file I/O",
    "result": "future wait",
    "block_until_ready": "device sync",
    "device_get": "host read",
    "device_put": "device transfer",
    "to_device": "device transfer",
    "sleep": "sleep",
    "poll": "broker poll (re-entrant fold)",
}


def _gt17_listener_functions(mod: ModInfo):
    """Functions that are listener/callback bodies: marker-named defs
    plus defs whose NAME is passed to a listener-registration call."""
    registered: Set[str] = set()
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _GT17_REGISTER_CALLS):
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    registered.add(arg.id)
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        name = node.name.lstrip("_")
        if (name in registered or node.name in registered
                or any(m in name for m in _GT17_NAME_MARKERS)
                or any(name.startswith(p) for p in _GT17_NAME_PREFIXES)):
            yield node


def gt17(mod: ModInfo, project) -> Iterator[Finding]:
    """GT17: blocking calls inside subscription listener/callback
    bodies (subscribe//kafka/ scope).

    Flags `open(...)`, `.result()` (future wait), `.block_until_ready()`,
    `jax.device_get`/`device_put`, `to_device(...)`, `.sleep(...)` and
    `.poll(...)` (a listener re-entering the fold) lexically inside
    listener-shaped functions: names containing listener/callback, an
    `on_*` prefix, or local defs registered via `add_listener`/
    `add_fold_hook`. The listener contract is buffer-only — evaluation
    and device work belong in the post-fold pump, which the store runs
    OUTSIDE its lock. Waivable inline (`# gt: waive GT17`) for a
    documented deliberate block."""
    path = mod.relpath.replace("\\", "/")
    if not any(p in path for p in _GT17_PREFIXES):
        return
    seen: Set[int] = set()
    for fn in _gt17_listener_functions(mod):
        # the registration-site walk sees nested defs too, so a
        # listener factory's inner closure is covered either way
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute):
                ident = f.attr
            elif isinstance(f, ast.Name):
                ident = f.id
            else:
                continue
            what = _GT17_BLOCKING.get(ident)
            if what is None or node.lineno in seen:
                continue
            seen.add(node.lineno)
            yield _finding(
                "GT17", mod, node,
                f"blocking call ({ident}: {what}) inside subscription "
                f"listener/callback {fn.name!r}: listeners run inside "
                f"the Kafka fold (store lock held) and must only "
                f"buffer — move the work to the post-fold pump "
                f"(subscribe/evaluator.py), or waive a documented "
                f"deliberate block")


# GT18 scope: the serve and plan layers (serve/, plan/). The sharded
# serving contract (docs/SERVING.md "Sharded serving") is that data
# placement happens ONCE, declaratively, via NamedSharding — the mesh
# superbatch upload, the stager's replicated slots, the planner's
# row-sharding re-pins. Hand-rolled per-device placement — a loop
# device_put-ing slices onto each chip, or `jax.devices()[i]` indexing
# to pick a chip ad hoc — bypasses that: XLA can no longer fuse the
# transfer, ownership stops matching the DeviceCacheManager's recorded
# tile map, and the AOT executables' parameter shardings stop matching
# the data (a silent per-dispatch reshard). The shard-affinity route
# picks its chip from the superbatch's OWNERSHIP map (mesh.devices),
# which this rule deliberately does not match. Waivable inline for a
# documented deliberate placement; the shipped tree is clean.
_GT18_PREFIXES = ("geomesa_tpu/serve/", "geomesa_tpu/plan/")
_GT18_DEVICES_FNS = {"devices", "local_devices"}
_GT18_TRANSFER_FNS = {"device_put", "to_device"}


def _gt18_devices_call(node: ast.AST) -> bool:
    """True for `jax.devices()` / `jax.local_devices()` (attribute or
    bare-name call form — `from jax import devices` included)."""
    if not isinstance(node, ast.Call):
        return False
    name = _callee_name(node)
    return name in _GT18_DEVICES_FNS


def gt18(mod: ModInfo, project) -> Iterator[Finding]:
    """GT18: per-device placement that bypasses NamedSharding
    (serve//plan/ scope).

    Two shapes: (a) a `for`/`while` loop over a device list (the
    iterable mentions `jax.devices()`/`local_devices()` or an alias
    assigned from one, or the loop target is named `dev`/`device`)
    whose body calls `device_put`/`to_device` — the per-chip upload
    loop `parallel.mesh.shard_device_batch` exists to replace; and
    (b) subscripting a `jax.devices()`/`local_devices()` call or an
    alias of one (`jax.devices()[0]`, `devs = jax.devices();
    devs[i]`) — ad-hoc chip selection that ignores the mesh and the
    cache's tile-ownership map. Both waivable inline
    (`# gt: waive GT18`) for a documented deliberate placement."""
    path = mod.relpath.replace("\\", "/")
    if not any(p in path for p in _GT18_PREFIXES):
        return
    # alias forms: names assigned (anywhere in the module) from a
    # devices() call — `devs = jax.devices()` — tracked by name; the
    # serve/plan modules are small enough that scope-insensitive
    # aliasing stays precise (no false positives on the shipped tree)
    aliases: Set[str] = set()
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Assign)
                and _gt18_devices_call(node.value)):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    aliases.add(t.id)
        elif (isinstance(node, (ast.AnnAssign, ast.NamedExpr))
                and node.value is not None
                and _gt18_devices_call(node.value)):
            t = node.target
            if isinstance(t, ast.Name):
                aliases.add(t.id)

    def mentions_devices(expr: ast.AST) -> bool:
        for n in ast.walk(expr):
            if _gt18_devices_call(n):
                return True
            if isinstance(n, ast.Name) and n.id in aliases:
                return True
        return False

    seen: Set[int] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.For):
            targets = _names_in(node.target)
            dev_loop = (mentions_devices(node.iter)
                        or targets & {"dev", "device"})
            if not dev_loop:
                continue
            for inner in ast.walk(node):
                if (isinstance(inner, ast.Call)
                        and _callee_name(inner) in _GT18_TRANSFER_FNS
                        and inner.lineno not in seen):
                    seen.add(inner.lineno)
                    yield _finding(
                        "GT18", mod, inner,
                        f"per-device {_callee_name(inner)} loop: "
                        f"placement belongs to ONE NamedSharding "
                        f"device_put (parallel.mesh.shard_device_batch "
                        f"/ store.cache mesh superbatch) — a per-chip "
                        f"upload loop bypasses the recorded tile "
                        f"ownership and cannot fuse; waive a "
                        f"documented deliberate placement")
        elif isinstance(node, ast.Subscript):
            v = node.value
            # jax.devices()[i] directly, or an alias devs[i]
            direct = _gt18_devices_call(v)
            aliased = isinstance(v, ast.Name) and v.id in aliases
            if (direct or aliased) and node.lineno not in seen:
                seen.add(node.lineno)
                yield _finding(
                    "GT18", mod, node,
                    "jax.devices()[i] indexing: ad-hoc chip selection "
                    "ignores the serving mesh and the device cache's "
                    "tile-ownership map — place data with NamedSharding "
                    "over parallel.mesh (shard-affinity routes read "
                    "ownership from the superbatch); waive a documented "
                    "deliberate selection")


# GT19 scope: the serve and telemetry layers — the modules that emit
# the Prometheus series dashboards and the SLO engine scrape. The
# metrics registry keys series by name + sorted labels, so two call
# sites emitting ONE family with DIFFERENT label-key sets silently
# fork it: `serve.requests{kind,status}` here, `serve.requests{kind}`
# there renders one family with incompatible schemas — strict scrapers
# reject it, PromQL joins on the missing label silently drop samples,
# and the unlabeled twin shadows the labeled series in sum() without
# anyone deciding that. The fix is always to pick ONE label schema per
# family (or a new family name); the rule points at every minority
# call site.
_GT19_PREFIXES = ("geomesa_tpu/serve/", "geomesa_tpu/telemetry/")
_GT19_EMITTERS = {
    # registry method -> keyword params that are NOT labels
    "counter": {"inc"},
    "gauge": {"value"},
    "histogram": set(),
    "timer": set(),
}


def _gt19_sites(mod: ModInfo):
    """(family, label-key frozenset, call node) for every literal-name
    metric emission in `mod`. Dynamic names (f-strings — the per-
    breaker gauges) and **splat label dicts are skipped: their label
    schema is not statically comparable."""
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute)
                and f.attr in _GT19_EMITTERS
                and isinstance(f.value, ast.Name)
                and f.value.id == "metrics"):
            continue
        if not node.args:
            continue
        name = node.args[0]
        if not (isinstance(name, ast.Constant)
                and isinstance(name.value, str)):
            continue
        non_labels = _GT19_EMITTERS[f.attr]
        if any(kw.arg is None for kw in node.keywords):
            continue  # **labels splat: schema unknowable statically
        labels = frozenset(kw.arg for kw in node.keywords
                           if kw.arg not in non_labels)
        yield name.value, labels, node


def gt19(mod: ModInfo, project) -> Iterator[Finding]:
    """GT19: one metric family, different label-key sets across call
    sites (serve//telemetry/ scope).

    The family index is built once per lint run over every in-scope
    scanned module (cached on the project; fixture runs with
    project=None index just the module under test). For a family whose
    sites disagree, the MAJORITY label set (ties: the first site in
    path/line order) is taken as the schema and every other site is
    flagged. Waivable inline (`# gt: waive GT19`) for a documented
    deliberate fork."""
    path = mod.relpath.replace("\\", "/")
    if not any(p in path for p in _GT19_PREFIXES):
        return
    if project is not None:
        index = getattr(project, "_gt19_index", None)
        if index is None:
            index = {}
            for m in project.modules:
                mp = m.relpath.replace("\\", "/")
                if not any(p in mp for p in _GT19_PREFIXES):
                    continue
                for fam, labels, node in _gt19_sites(m):
                    index.setdefault(fam, []).append(
                        (mp, node.lineno, labels))
            project._gt19_index = index  # type: ignore[attr-defined]
    else:
        index = {}
        for fam, labels, node in _gt19_sites(mod):
            index.setdefault(fam, []).append(
                (path, node.lineno, labels))
    for fam, labels, node in _gt19_sites(mod):
        sites = index.get(fam, ())
        schemas = {ls for _, _, ls in sites}
        if len(schemas) <= 1:
            continue
        # majority schema; ties break to the first site in file order
        counts: dict = {}
        for _, _, ls in sites:
            counts[ls] = counts.get(ls, 0) + 1
        best = max(counts.values())
        winners = [ls for ls in counts if counts[ls] == best]
        if len(winners) == 1:
            schema = winners[0]
        else:
            schema = next(ls for _, _, ls in sorted(sites)
                          if ls in winners)
        if labels == schema:
            continue
        others = sorted({f"{p}:{ln}" for p, ln, ls in sites
                         if ls == schema})
        yield _finding(
            "GT19", mod, node,
            f"metric family {fam!r} emitted with labels "
            f"{{{', '.join(sorted(labels)) or 'none'}}} here but "
            f"{{{', '.join(sorted(schema)) or 'none'}}} at "
            f"{', '.join(others[:3])}: the series forks and "
            f"scrapes/joins break — pick one label schema per family "
            f"(or a distinct family name), or waive a documented "
            f"deliberate fork")


# GT20 scope: the fleet tier + the wire protocol it multiplexes. One
# unbounded blocking socket call in the router wedges EVERY client
# behind one dead replica (the reader thread never returns, pendings
# never redistribute); in a replica it wedges drain. The fleet/wire.py
# discipline is: every socket carries a timeout (settimeout, or
# create_connection(timeout=...)), reads poll with a stop event.
_GT20_PREFIXES = ("geomesa_tpu/fleet/", "geomesa_tpu/serve/protocol.py")
_GT20_BLOCKING = {"connect", "recv", "recv_into", "accept"}


def _gt20_recv_name(node: ast.AST) -> Optional[str]:
    """Dotted receiver of an attribute chain (`self._sock` for
    `self._sock.recv(...)`), or None when not statically nameable."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _gt20_create_connection(call: ast.Call) -> bool:
    """True for socket.create_connection(...) / create_connection(...)."""
    f = call.func
    return ((isinstance(f, ast.Attribute)
             and f.attr == "create_connection")
            or (isinstance(f, ast.Name)
                and f.id == "create_connection"))


def gt20(mod: ModInfo, project) -> Iterator[Finding]:
    """GT20: socket connect/recv without a timeout (fleet scope).

    Flags (a) `X.connect(...)` / `X.recv(...)` / `X.recv_into` /
    `X.accept(...)` where no `X.settimeout(...)` appears for the same
    dotted receiver anywhere in the module (cross-method: a socket
    configured in __init__ and read in a loop is fine), and (b)
    `socket.create_connection(addr)` without a timeout (second
    positional or `timeout=` keyword). A module that calls
    `socket.setdefaulttimeout(...)` is exempt wholesale — the global
    default bounds every socket it creates. Waivable inline
    (`# gt: waive GT20`) for a documented deliberate block."""
    path = mod.relpath.replace("\\", "/")
    if not any(p in path for p in _GT20_PREFIXES):
        return
    safe: Set[str] = set()
    default_timeout = False
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "settimeout":
            name = _gt20_recv_name(f.value)
            if name is not None:
                safe.add(name)
        elif (isinstance(f, ast.Attribute)
                and f.attr == "setdefaulttimeout"):
            default_timeout = True
    if default_timeout:
        return
    # names bound from a bounded create_connection are safe receivers
    for node in ast.walk(mod.tree):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _gt20_create_connection(node.value)
                and (len(node.value.args) >= 2
                     or any(kw.arg == "timeout"
                            for kw in node.value.keywords))):
            for t in node.targets:
                name = _gt20_recv_name(t)
                if name is not None:
                    safe.add(name)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if _gt20_create_connection(node):
            if (len(node.args) < 2
                    and not any(kw.arg == "timeout"
                                for kw in node.keywords)):
                yield _finding(
                    "GT20", mod, node,
                    "socket.create_connection without a timeout: an "
                    "unreachable replica blocks the caller forever — "
                    "pass timeout= (fleet/wire.connect_json does)")
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute)
                and f.attr in _GT20_BLOCKING):
            continue
        name = _gt20_recv_name(f.value)
        if name is not None and name in safe:
            continue
        yield _finding(
            "GT20", mod, node,
            f"socket .{f.attr}() with no settimeout() on "
            f"{name or 'its receiver'} anywhere in this module: an "
            f"unbounded blocking call in the fleet tier wedges the "
            f"whole router behind one dead peer — call settimeout() "
            f"(poll + stop event, see fleet/wire.py), or waive a "
            f"documented deliberate block")


# GT21 scope: the layers that mint or consult result-cache keys. The
# cache contract (geomesa_tpu.approx.cache) keys on the CANONICAL CQL
# (ast.to_cql of the parsed filter); a site keying on raw filter TEXT
# silently forks the key space — equivalent spellings ("a=1 AND b=2" vs
# "a = 1  AND  b = 2") miss each other and a dashboard fleet's repeated
# queries become a cache-miss storm.
_GT21_PREFIXES = ("geomesa_tpu/serve/", "geomesa_tpu/approx/",
                  "geomesa_tpu/plan/")

# receivers that look like a result cache (dotted tail, lowercased)
_GT21_CACHE_NAMES = ("result_cache", "results_cache", "rcache")

_GT21_KEY_FNS = ("result_key", "cache_key")


def _gt21_raw_cql(node: ast.AST) -> Optional[ast.AST]:
    """First subexpression that reads RAW filter text: `<x>.cql`,
    `<x>["cql"]`, or `<x>.get("cql", ...)`."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "cql":
            return sub
        if isinstance(sub, ast.Subscript):
            sl = sub.slice
            if isinstance(sl, ast.Constant) and sl.value == "cql":
                return sub
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "get" and sub.args
                and isinstance(sub.args[0], ast.Constant)
                and sub.args[0].value == "cql"):
            return sub
    return None


def _gt21_recv_tail(expr: ast.AST) -> str:
    if isinstance(expr, ast.Attribute):
        return expr.attr.lower()
    if isinstance(expr, ast.Name):
        return expr.id.lower()
    return ""


def gt21(mod: ModInfo, project) -> Iterator[Finding]:
    """GT21: result-cache insertion/lookup sites keying on raw CQL
    text instead of the canonical form.

    Flags (a) calls to a cache-key builder (`result_key` /
    `cache_key`, bare or dotted) whose arguments read raw filter text
    (`<x>.cql`, `<x>["cql"]`, `<x>.get("cql")`), and (b) `.get()` /
    `.put()` / `.peek()` on a result-cache-shaped receiver
    (`*result_cache*`, `rcache`) whose key arguments do. The clean
    form passes the Query OBJECT (the builder canonicalizes through
    the AST) or `ast.to_cql(query.filter_ast)`. Waivable inline
    (`# gt: waive GT21`) for a documented deliberate raw-text key."""
    path = mod.relpath.replace("\\", "/")
    if not any(p in path for p in _GT21_PREFIXES):
        return
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        fname = (f.attr if isinstance(f, ast.Attribute)
                 else f.id if isinstance(f, ast.Name) else "")
        hit = None
        if fname in _GT21_KEY_FNS:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                hit = _gt21_raw_cql(arg)
                if hit is not None:
                    break
        elif (fname in ("get", "put", "peek")
                and isinstance(f, ast.Attribute)
                and any(n in _gt21_recv_tail(f.value)
                        for n in _GT21_CACHE_NAMES)):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                hit = _gt21_raw_cql(arg)
                if hit is not None:
                    break
        if hit is None:
            continue
        yield _finding(
            "GT21", mod, node,
            "result-cache key built from RAW CQL text: equivalent "
            "filter spellings fork the key space into a cache-miss "
            "storm. Pass the Query object to approx.cache.result_key "
            "(it canonicalizes via ast.to_cql), or canonicalize "
            "explicitly with ast.to_cql(query.filter_ast); waive a "
            "documented deliberate raw-text key.")


# GT22 scope: the wire-encode layers — where bulk payloads (execute
# results, push frames) are serialized onto connections. The columnar
# wire (serve/columnar.py) exists precisely so the hot path never pays
# a Python dict + json.dumps PER ROW / PER SUBSCRIBER; this rule keeps
# the pattern from creeping back (docs/SERVING.md "Columnar wire").
_GT22_PREFIXES = ("geomesa_tpu/serve/", "geomesa_tpu/subscribe/")


def _gt22_is_dumps(call: ast.Call) -> bool:
    """True for json.dumps(...) / dumps(...)."""
    f = call.func
    return ((isinstance(f, ast.Attribute) and f.attr == "dumps")
            or (isinstance(f, ast.Name) and f.id == "dumps"))


def gt22(mod: ModInfo, project) -> Iterator[Finding]:
    """GT22: per-row serialization in a wire-encode loop.

    Flags, inside `geomesa_tpu/serve/` and `geomesa_tpu/subscribe/`:
    (a) a `json.dumps(...)` call lexically inside a `for`/`while`
    body — serializing row-by-row (or frame-by-frame per subscriber)
    is the N-encodes pattern the PushMux/columnar framing removed:
    encode ONCE outside the loop, or route through
    `serve.columnar.PushMux`; and (b) a dict comprehension nested
    inside a `for`/`while` body or as the element of a list/generator
    comprehension — materializing one Python dict per feature on the
    encode path (the columnar codecs keep rows in column buffers).
    Function/class boundaries reset the loop context: a helper that
    dumps once per CALL is fine even when its callers loop. Waivable
    inline (`# gt: waive GT22`) for a documented deliberate per-row
    encode (e.g. the bounded JSON fallback)."""
    path = mod.relpath.replace("\\", "/")
    if not any(p in path for p in _GT22_PREFIXES):
        return

    findings: List[Finding] = []

    def walk(node: ast.AST, in_loop: bool, in_comp: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                # new lexical scope: its body runs once per CALL, not
                # once per iteration of an enclosing loop
                walk(child, False, False)
                continue
            loop_here = in_loop or isinstance(child, (ast.For,
                                                      ast.While))
            comp_here = in_comp or isinstance(child, (ast.ListComp,
                                                      ast.GeneratorExp,
                                                      ast.SetComp))
            if (isinstance(child, ast.Call) and in_loop
                    and _gt22_is_dumps(child)):
                findings.append(_finding(
                    "GT22", mod, child,
                    "json.dumps inside a loop on the wire-encode "
                    "path: N rows (or N subscribers) pay N encodes — "
                    "encode ONCE outside the loop, ship the bulk "
                    "payload as a columnar frame "
                    "(serve/columnar.py), or fan push frames through "
                    "PushMux; waive a documented deliberate per-row "
                    "encode"))
            elif isinstance(child, ast.DictComp) and (in_loop
                                                      or in_comp):
                findings.append(_finding(
                    "GT22", mod, child,
                    "dict comprehension per loop iteration on the "
                    "wire-encode path: one Python dict per feature "
                    "is the row-materialization the columnar wire "
                    "removes — keep rows in column buffers "
                    "(serve/columnar.py codecs) and build dicts only "
                    "at the decode edge; waive a documented "
                    "deliberate per-row build"))
            walk(child, loop_here, comp_here)

    walk(mod.tree, False, False)
    yield from findings


# GT23 scope: the persistent serve loop's feed seam (serve/ringloop.py).
# The ring's whole point is that per-window Python work is ONLY a slot
# write + one pre-compiled dispatch — the harvest read belongs to the
# completer, and the slot write goes through QueryStager's designated
# staging path (which carries the retry fabric, the transfer fault site
# and the depth-R rotation contract). A blocking host sync inside the
# feed scope re-serializes the loop exactly like GT16's hazard, and a
# NAKED per-window device_put/to_device there bypasses the ring —
# un-rotated, un-metered, un-donated buffers that silently turn the
# ring back into per-window transfers. Same shape as GT16, extended
# with the transfer calls.
_GT23_PATH = "geomesa_tpu/serve/ringloop.py"
_GT23_MARKERS = ("feed", "slot")
_GT23_BLOCKING = {
    "block_until_ready": "device sync",
    "result": "future wait",
    "device_get": "host read",
    "device_put": "per-window device transfer (use the ring stager)",
    "to_device": "per-window device transfer (use the ring stager)",
}


def _gt23_feed_functions(mod: ModInfo):
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        name = node.name.lstrip("_")
        if any(m in name for m in _GT23_MARKERS):
            yield node


def gt23(mod: ModInfo, project) -> Iterator[Finding]:
    """GT23: blocking host sync or naked per-window transfer inside the
    ring feed loop scope.

    Flags `.block_until_ready()`, `.result()` (futures; `set_result`
    is a resolve and is not matched), `jax.device_get` / bare
    `device_get`, and `device_put` / `to_device` calls lexically inside
    the feed-scope functions of serve/ringloop.py (names containing
    feed/slot). The slot write must go through the stager's staging
    path (`.stage(...)` — retry fabric, fault site, depth-R rotation);
    blocking belongs to the completer's harvest. Waivable inline
    (`# gt: waive GT23`) for a documented deliberate call."""
    path = mod.relpath.replace("\\", "/")
    if _GT23_PATH not in path:
        return
    seen: Set[int] = set()
    for fn in _gt23_feed_functions(mod):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            ident = None
            if isinstance(f, ast.Attribute):
                ident = f.attr
            elif isinstance(f, ast.Name):
                ident = f.id if f.id in ("device_get", "device_put",
                                         "to_device") else None
            what = _GT23_BLOCKING.get(ident or "")
            if what is None or node.lineno in seen:
                continue
            seen.add(node.lineno)
            yield _finding(
                "GT23", mod, node,
                f"blocking/transfer call ({ident}: {what}) inside ring "
                f"feed scope {fn.name!r}: per-window work in the "
                f"persistent serve loop is ONLY a slot write through "
                f"the stager + one pre-compiled dispatch — a host sync "
                f"re-serializes the loop and a naked transfer bypasses "
                f"the ring's rotation/donation contract. Move waits to "
                f"the completer's harvest, transfers into the stager, "
                f"or waive a documented deliberate call")


from geomesa_tpu.analysis.concurrency import (  # noqa: E402
    CONCURRENCY_RULES)
from geomesa_tpu.analysis.spmd import SPMD_RULES  # noqa: E402
from geomesa_tpu.analysis.dataflow import DATAFLOW_RULES  # noqa: E402

ALL_RULES = {
    "GT01": gt01, "GT02": gt02, "GT03": gt03,
    "GT04": gt04, "GT05": gt05, "GT06": gt06,
    "GT13": gt13, "GT14": gt14, "GT15": gt15, "GT16": gt16,
    "GT17": gt17, "GT18": gt18, "GT19": gt19, "GT20": gt20,
    "GT21": gt21, "GT22": gt22, "GT23": gt23,
    **CONCURRENCY_RULES,
    **SPMD_RULES,
    **DATAFLOW_RULES,
}
