"""`python -m geomesa_tpu.analysis` — standalone gmtpu-lint entry point
(the same linter the `gmtpu lint` CLI subcommand wires up)."""

from __future__ import annotations

import argparse
import sys

from geomesa_tpu.analysis.linter import add_lint_arguments, run_cli


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="gmtpu-lint",
        description="JAX-aware static analysis for geomesa-tpu "
                    "(rules GT01..GT06 + concurrency GT07..GT12)")
    add_lint_arguments(p)
    return run_cli(p.parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
