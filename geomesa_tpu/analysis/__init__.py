"""geomesa_tpu.analysis — JAX-aware static analysis + runtime guards.

`gmtpu-lint` walks the package AST (never importing it) and reports
JAX-specific hazards GT01..GT06; `runtime` adds opt-in recompile
counters and transfer guards around the engine's jit caches. See
docs/ANALYSIS.md for the rule catalog and waiver syntax.
"""

from geomesa_tpu.analysis.model import RULES, Finding
from geomesa_tpu.analysis.linter import (
    exit_code, lint_paths, render_json, render_text)

__all__ = [
    "RULES", "Finding", "lint_paths", "render_text", "render_json",
    "exit_code",
]
