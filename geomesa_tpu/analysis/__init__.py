"""geomesa_tpu.analysis — JAX-aware static analysis + runtime guards.

`gmtpu-lint` walks the package AST (never importing it) and reports
JAX-specific hazards GT01..GT06 plus the lock-discipline rules
GT07..GT12 (`concurrency`); `runtime` adds opt-in recompile counters
and transfer guards around the engine's jit caches, and `locksets` is
the Eraser-style runtime race harness behind `gmtpu guard --races`.
See docs/ANALYSIS.md for the rule catalog and waiver syntax.
"""

from geomesa_tpu.analysis.model import RULES, Finding
from geomesa_tpu.analysis.linter import (
    exit_code, lint_paths, render_json, render_sarif, render_text)
from geomesa_tpu.analysis.locksets import (
    note_access, trace_locks, tracked_lock)

__all__ = [
    "RULES", "Finding", "lint_paths", "render_text", "render_json",
    "render_sarif", "exit_code", "trace_locks", "tracked_lock",
    "note_access",
]
