"""Incremental lint engine: a content-hash cache that makes warm
`gmtpu lint --incremental` runs (and the CI gate's repeated format
renders) drop from a ~20-30s full analysis to well under a second on an
unchanged tree, with findings **byte-identical** to a cold scan — the
tests assert `render_json(cold) == render_json(incremental)` on warm,
touched, and edited trees.

Cache file: `.gmtpu-lintcache` at the repo root (JSON, written
atomically tmp+`os.replace`, git-ignored). It stores, keyed on the
sha256 of every file in the scan set AND the reference universe (the
rest of the repo — GT05's liveness counts and the GT08 lock graph read
it), plus the waiver file's hash and a config signature (rule selection,
scan paths, cache schema):

- the final post-pipeline findings (the warm-replay payload),
- per-file findings of every *file-local* rule, pre file-waiver,
- per-file `spmd.ModuleSummary` dicts (GT24-GT27's cross-file index
  rebuilds from these for unchanged files instead of re-walking ASTs),
- per-file GT05 reference-count summaries (the reference universe
  rebuilds by summation instead of a fresh whole-repo AST walk).

Three tiers, strictly ordered by how much changed:

1. **Warm** — nothing changed anywhere: replay the cached final
   findings. Zero parses, zero rule runs.
2. **Partial** — some files changed but the project jit-def universe is
   intact (`jit_sig` matches: the (name, file, file-hash) set of every
   jit def — the only cross-file state the file-local rules consume):
   re-parse the tree, then rerun file-local rules only on changed files
   (cached findings replay for the rest), rebuild GT05 counts and SPMD
   summaries from cache for unchanged files, and rerun the genuinely
   cross-file rules (GT05/GT07-GT12/GT19/GT24-GT27) — whose per-module
   output can change when *any* file changes — over everything. (The
   concurrency index keeps AST anchors for its finding messages, so it
   rebuilds from the fresh parse rather than from serialized summaries.)
3. **Cold** — no cache, config changed, or the jit universe shifted:
   the full pipeline, identical to `lint_paths`.

Every non-warm run rewrites the cache, so the gate's sequence of
text/json/sarif renders pays for one analysis, not three.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Set, Tuple

from geomesa_tpu.analysis.linter import (
    _check_inline_waiver_tokens, _iter_py_files, build_project,
    finalize_findings, find_repo_root, lint_paths, module_reference_counts,
    resolve_waiver_file)
from geomesa_tpu.analysis.dataflow import DATAFLOW_SCHEMA, ModuleFlow
from geomesa_tpu.analysis.model import ANALYSIS_VERSION, Finding
from geomesa_tpu.analysis.rules import ALL_RULES
from geomesa_tpu.analysis.spmd import SPMD_SCHEMA, ModuleSummary

__all__ = ["lint_paths_incremental", "DEFAULT_CACHE_FILENAME"]

# bump on any change to what the cache stores or what the replay paths
# assume — an old cache must fall through to a cold scan, never mis-replay
CACHE_SCHEMA = 2

DEFAULT_CACHE_FILENAME = ".gmtpu-lintcache"

# Rules whose findings for a module depend ONLY on that module's source
# plus the project jit-def universe (name -> JitDef; pinned by the
# cache's jit_sig). Everything else — GT05 (reference universe),
# GT07-GT12 (concurrency index), GT19 (registry index), GT24-GT27 (SPMD
# call graph) — is cross-file: its per-module findings can change when a
# DIFFERENT module changes, so those rules rerun on every non-warm run.
PER_FILE_RULES = frozenset({
    "GT01", "GT02", "GT03", "GT04", "GT06",
    "GT13", "GT14", "GT15", "GT16", "GT17", "GT18",
    "GT20", "GT21", "GT22", "GT23",
})


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    try:
        with open(path, "rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 16), b""):
                h.update(chunk)
    except OSError:
        return ""
    return h.hexdigest()


def _hash_tree(paths: List[str],
               repo_root: str) -> Tuple[Dict[str, str], Dict[str, str]]:
    """(scan hashes, reference hashes), both relpath -> sha256, walked
    in exactly `build_project`'s order and dedup discipline so the cache
    key covers precisely the files a cold scan would read."""

    def rel(af: str) -> str:
        return os.path.relpath(af, repo_root).replace(os.sep, "/")

    scan: Dict[str, str] = {}
    seen: Set[str] = set()
    for p in paths:
        for f in _iter_py_files(p):
            af = os.path.abspath(f)
            if af in seen:
                continue
            seen.add(af)
            scan[rel(af)] = _sha256_file(af)
    refs: Dict[str, str] = {}
    for f in _iter_py_files(repo_root):
        af = os.path.abspath(f)
        if af in seen:
            continue
        seen.add(af)
        refs[rel(af)] = _sha256_file(af)
    return scan, refs


def _ruleset_sig() -> str:
    """Fingerprint of the rule set that wrote the cache: the registered
    rule codes plus ANALYSIS_VERSION (bumped by any PR that changes
    rule semantics). The cache keys on target-file content — without
    this stamp, upgrading gmtpu-lint would replay stale findings from
    an older rule set as a byte-identical \"warm\" result. A mismatched
    or corrupt stamp falls through to a cold scan."""
    doc = {"version": ANALYSIS_VERSION, "rules": sorted(ALL_RULES)}
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()


def _config_sig(selected: List[str], paths: List[str]) -> str:
    doc = {"schema": CACHE_SCHEMA, "rules": selected,
           "paths": sorted(os.path.abspath(p) for p in paths)}
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()


def _jit_signature(project, scan_hashes: Dict[str, str]) -> str:
    """Pins the jit-def universe the file-local rules consult: every
    JitDef is derived solely from its defining file, so the (name, file,
    file-hash) set changing is exactly when a cached per-file finding
    could go stale through `project.jit_by_name`."""
    entries = []
    for m in project.modules:
        if not m.jit_defs:
            continue
        h = scan_hashes.get(m.relpath, "")
        for jd in m.jit_defs:
            entries.append(f"{jd.name}|{m.relpath}|{h}")
    return hashlib.sha256("\n".join(sorted(entries)).encode()).hexdigest()


def _finding_to(f: Finding) -> dict:
    d = {"rule": f.rule, "path": f.path, "line": f.line, "col": f.col,
         "message": f.message, "severity": f.severity,
         "waived": f.waived, "waived_by": f.waived_by}
    if f.extra:
        # dataflow provenance chains ride along so a warm replay's
        # SARIF render carries the same relatedLocations as a cold scan
        d["extra"] = f.extra
    return d


def _finding_from(d: dict) -> Finding:
    return Finding(rule=d["rule"], path=d["path"], line=int(d["line"]),
                   col=int(d["col"]), message=d["message"],
                   severity=d.get("severity", "warn"),
                   waived=bool(d.get("waived")),
                   waived_by=d.get("waived_by", ""),
                   extra=d.get("extra") or {})


def _load_cache(path: str) -> Optional[dict]:
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def _write_cache(path: str, doc: dict) -> None:
    """Atomic (tmp+rename); a failure to persist is a slower next run,
    never a wrong one — so it degrades silently."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
        # gt: waive GT27
        # (the lint cache is a per-checkout build artifact — multi-host
        # runtimes never share it; CI runs lint on one box)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def lint_paths_incremental(paths: List[str],
                           rules: Optional[List[str]] = None,
                           waiver_file: Optional[str] = None,
                           include_waived: bool = True,
                           cache_path: Optional[str] = None,
                           ) -> List[Finding]:
    """Drop-in `lint_paths` with the content-hash cache (see module
    docstring). Outside a repo root there is nowhere canonical to put
    the cache — falls back to the cold path."""
    paths = list(paths)
    repo_root = find_repo_root(paths[0]) if paths else None
    if repo_root is None:
        return lint_paths(paths, rules=rules, waiver_file=waiver_file,
                          include_waived=include_waived)
    cache_path = cache_path or os.path.join(repo_root,
                                            DEFAULT_CACHE_FILENAME)
    selected = rules or sorted(ALL_RULES)
    scan_hashes, ref_hashes = _hash_tree(paths, repo_root)
    if not scan_hashes:
        raise FileNotFoundError(
            f"gmtpu-lint: no .py files found under {paths!r}")
    wf = resolve_waiver_file(paths, waiver_file)
    waiver_sha = _sha256_file(wf) if wf else ""
    cfg = _config_sig(selected, paths)
    cache = _load_cache(cache_path)
    usable = (cache is not None
              and cache.get("schema") == CACHE_SCHEMA
              and cache.get("ruleset") == _ruleset_sig()
              and cache.get("config") == cfg)

    # -- tier 1: warm replay -----------------------------------------------
    if (usable and cache.get("waiver_sha") == waiver_sha
            and cache.get("files") == scan_hashes
            and cache.get("ref_files") == ref_hashes):
        findings = [_finding_from(d) for d in cache.get("findings", [])]
        if not include_waived:
            findings = [f for f in findings if not f.waived]
        return findings

    # -- tiers 2/3: re-parse, then reuse whatever is still valid -----------
    project = build_project(paths, repo_root=repo_root)
    if not project.modules:
        raise FileNotFoundError(
            f"gmtpu-lint: no .py files found under {paths!r}")
    old_files = cache.get("files", {}) if usable else {}
    old_refs = cache.get("ref_files", {}) if usable else {}
    changed = {r for r, h in scan_hashes.items() if old_files.get(r) != h}
    changed_refs = {r for r, h in ref_hashes.items()
                    if old_refs.get(r) != h}

    # SPMD summaries are pure per-file extractions — reusable for any
    # unchanged file regardless of what else moved
    if usable:
        spmd_cached: Dict[str, ModuleSummary] = {}
        for r, d in (cache.get("spmd") or {}).items():
            if r in changed or r not in scan_hashes:
                continue
            if not isinstance(d, dict) or d.get("schema") != SPMD_SCHEMA:
                continue
            try:
                spmd_cached[r] = ModuleSummary.from_dict(d)
            except (KeyError, TypeError, ValueError):
                continue
        if spmd_cached:
            project._gt_spmd_summaries = spmd_cached
        # dataflow flow summaries: same discipline as the SPMD ones
        df_cached: Dict[str, ModuleFlow] = {}
        for r, d in (cache.get("dataflow") or {}).items():
            if r in changed or r not in scan_hashes:
                continue
            if not isinstance(d, dict) or \
                    d.get("schema") != DATAFLOW_SCHEMA:
                continue
            try:
                df_cached[r] = ModuleFlow.from_dict(d)
            except (KeyError, TypeError, ValueError):
                continue
        if df_cached:
            project._gt_dataflow_summaries = df_cached

    jit_sig = _jit_signature(project, scan_hashes)
    perfile_ok = usable and cache.get("jit_sig") == jit_sig

    # GT05 reference universe: sum per-file count summaries (cached for
    # unchanged files — valid only while the jit-name set is pinned by
    # jit_sig — freshly counted for changed ones)
    wanted = set(project.jit_by_name)
    old_counts = cache.get("refcounts", {}) if perfile_ok else {}
    refcounts: Dict[str, Dict[str, int]] = {}
    total: Dict[str, int] = {}
    for m in project.modules + project.ref_modules:
        r = m.relpath
        counts = None
        if r not in changed and r not in changed_refs and r in old_counts:
            c = old_counts[r]
            if isinstance(c, dict):
                counts = {k: int(v) for k, v in c.items()}
        if counts is None:
            counts = module_reference_counts(m, wanted)
        refcounts[r] = counts
        for k, v in counts.items():
            total[k] = total.get(k, 0) + v
    project._refs = total

    cached_perfile = cache.get("perfile", {}) if perfile_ok else {}
    findings: List[Finding] = []
    new_perfile: Dict[str, Dict[str, List[dict]]] = {}
    for mod in project.modules:
        _check_inline_waiver_tokens(mod)
        r = mod.relpath
        slot = new_perfile.setdefault(r, {})
        file_cache = cached_perfile.get(r, {})
        for code in selected:
            if (code in PER_FILE_RULES and r not in changed
                    and code in file_cache):
                fs = [_finding_from(d) for d in file_cache[code]]
            else:
                fs = []
                for f in ALL_RULES[code](mod, project):
                    if mod.is_waived(f.rule, f.line):
                        f.waived = True
                        f.waived_by = f"inline:{mod.relpath}:{f.line}"
                    fs.append(f)
            if code in PER_FILE_RULES:
                # serialized NOW — pre file-waiver, post inline-waiver:
                # exactly the state a replay must re-enter the pipeline in
                slot[code] = [_finding_to(f) for f in fs]
            findings.extend(fs)
    finalize_findings(findings, paths, wf)

    spmd_out = getattr(project, "_gt_spmd_summaries", None) or {}
    df_out = getattr(project, "_gt_dataflow_summaries", None) or {}
    _write_cache(cache_path, {
        "schema": CACHE_SCHEMA,
        "ruleset": _ruleset_sig(),
        "config": cfg,
        "waiver_sha": waiver_sha,
        "jit_sig": jit_sig,
        "files": scan_hashes,
        "ref_files": ref_hashes,
        "findings": [_finding_to(f) for f in findings],
        "perfile": new_perfile,
        "refcounts": refcounts,
        "spmd": {r: s.to_dict() for r, s in spmd_out.items()},
        "dataflow": {r: s.to_dict() for r, s in df_out.items()},
    })
    if not include_waived:
        findings = [f for f in findings if not f.waived]
    return findings
