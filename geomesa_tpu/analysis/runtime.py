"""Opt-in runtime guards: recompile counters + transfer guards.

The static linter catches what the AST shows; these guards catch what
only shows up live — a retrace storm from an unhashable config object,
or a silent host round-trip on the query hot path. Both surface through
`geomesa_tpu.utils.metrics` (gauges `analysis.recompiles.<name>`,
counter `analysis.recompiles`), so the existing JSON/Prometheus
exporters pick them up with no extra wiring.

`JitTracker.wrap` wraps a single jitted callable; `guard_engine` sweeps
the engine modules and wraps every jitted callable in place (reversible
with `.unwrap()`); `transfer_guard` is a thin, version-tolerant wrapper
over `jax.transfer_guard`. All of it is pay-when-used: importing this
module does not import jax.
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import Callable, Dict, List, Optional, Tuple

TRANSFER_MODES = ("allow", "log", "disallow")


def is_jitted(obj) -> bool:
    """A jax.jit product exposes a per-callable compile-cache size; that
    is also exactly the hook the recompile counter needs."""
    return callable(obj) and hasattr(obj, "_cache_size")


class JitTracker:
    """Counts compile-cache growth per wrapped jitted callable.

    Every wrapped call compares `fn._cache_size()` before/after; growth
    means this call traced+compiled instead of hitting the cache. The
    counts publish to the metrics registry on every recompile and are
    queryable via `report()`. `warn_after` (per callable) invokes
    `on_storm` once when a callable exceeds it — the runtime analog of
    lint rule GT01.
    """

    def __init__(self, registry=None, warn_after: Optional[int] = None,
                 on_storm: Optional[Callable[[str, int], None]] = None):
        if registry is None:
            from geomesa_tpu.utils.metrics import metrics as registry
        self.registry = registry
        self.warn_after = warn_after
        self.on_storm = on_storm
        self._lock = threading.Lock()
        self.recompiles: Dict[str, int] = {}
        self.calls: Dict[str, int] = {}
        self._warned: set = set()
        self._installed: List[tuple] = []  # (module, attr, original)

    def wrap(self, fn, name: Optional[str] = None):
        if not is_jitted(fn):
            raise TypeError(
                f"JitTracker.wrap expects a jax.jit callable, got {fn!r}")
        label = name or getattr(fn, "__name__", repr(fn))

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            before = fn._cache_size()
            out = fn(*args, **kwargs)
            grew = fn._cache_size() - before
            storm_count = 0
            with self._lock:
                self.calls[label] = self.calls.get(label, 0) + 1
                if grew > 0:
                    n = self.recompiles.get(label, 0) + grew
                    self.recompiles[label] = n
                    self.registry.counter("analysis.recompiles", grew)
                    self.registry.gauge(
                        f"analysis.recompiles.{label}", float(n))
                    if (self.warn_after is not None
                            and n > self.warn_after
                            and label not in self._warned):
                        self._warned.add(label)
                        storm = self.on_storm
                        storm_count = n  # captured under the lock (GT07)
                    else:
                        storm = None
                else:
                    storm = None
            if storm is not None:
                storm(label, storm_count)
            return out

        wrapper._gt_tracked = fn  # type: ignore[attr-defined]
        return wrapper

    # -- in-place module instrumentation ----------------------------------

    def install(self, module, names: Optional[List[str]] = None) -> int:
        """Wrap every jitted top-level callable of `module` in place.
        Returns how many were wrapped. Idempotent per module attr."""
        wrapped = 0
        for attr in names or sorted(vars(module)):
            obj = getattr(module, attr, None)
            if not is_jitted(obj) or hasattr(obj, "_gt_tracked"):
                continue
            label = f"{module.__name__.rsplit('.', 1)[-1]}.{attr}"
            setattr(module, attr, self.wrap(obj, name=label))
            with self._lock:
                self._installed.append((module, attr, obj))
            wrapped += 1
        return wrapped

    def unwrap(self) -> None:
        with self._lock:
            installed, self._installed = self._installed, []
        for module, attr, original in reversed(installed):
            setattr(module, attr, original)

    def report(self) -> Dict[str, dict]:
        with self._lock:
            return {
                name: {"calls": self.calls.get(name, 0),
                       "recompiles": self.recompiles.get(name, 0)}
                for name in sorted(set(self.calls) | set(self.recompiles))
            }


_ENGINE_MODULES = (
    "geomesa_tpu.engine.bin",
    "geomesa_tpu.engine.density",
    "geomesa_tpu.engine.density_zsparse",
    "geomesa_tpu.engine.grid_index",
    "geomesa_tpu.engine.knn",
    "geomesa_tpu.engine.knn_scan",
    "geomesa_tpu.engine.pip_pallas",
    "geomesa_tpu.engine.pip_sparse",
    "geomesa_tpu.engine.raster",
    "geomesa_tpu.engine.stats",
    "geomesa_tpu.engine.tube",
)


def guard_engine(registry=None, warn_after: Optional[int] = None,
                 on_storm: Optional[Callable[[str, int], None]] = None,
                 modules=None) -> JitTracker:
    """Wrap every jitted callable across the engine modules with one
    shared tracker (the engine's jit caches, guarded). Call `.unwrap()`
    to restore."""
    import importlib

    tracker = JitTracker(registry=registry, warn_after=warn_after,
                         on_storm=on_storm)
    for modname in modules or _ENGINE_MODULES:
        try:
            mod = importlib.import_module(modname)
        except ImportError:
            continue
        tracker.install(mod)
    return tracker


@contextlib.contextmanager
def transfer_guard(mode: str = "disallow"):
    """`jax.transfer_guard` with a version-tolerant fallback: "log"
    logs every implicit host<->device transfer, "disallow" raises on
    them — the runtime teeth behind lint rule GT02."""
    if mode not in TRANSFER_MODES:
        raise ValueError(
            f"transfer mode must be one of {TRANSFER_MODES}, got {mode!r}")
    import jax

    guard = getattr(jax, "transfer_guard", None)
    if guard is None:  # very old jax: guard unavailable, run unguarded
        yield
        return
    with guard(mode):
        yield


def run_guarded(path: str, argv: Optional[List[str]] = None,
                transfer: str = "allow",
                warn_after: Optional[int] = None,
                on_storm: Optional[Callable[[str, int], None]] = None,
                registry=None,
                races: bool = False) -> Tuple[Dict[str, dict], int]:
    """Execute a Python script under the runtime guards (the `gmtpu
    guard` command): engine jit caches tracked, optional transfer
    guard, optional lockset race harness (`races=True`: every lock the
    script CREATES is tracked; lock-order inversions and empty-lockset
    accesses land in the report under "locksets"). Returns (report,
    script exit status) — a script ending in the standard
    `sys.exit(main())` idiom must not swallow the report, so SystemExit
    is caught and surfaced as the status."""
    import runpy
    import sys

    tracker = guard_engine(registry=registry, warn_after=warn_after,
                           on_storm=on_storm)
    old_argv = sys.argv
    sys.argv = [path] + list(argv or ())
    status = 0
    lock_report = None
    try:
        with contextlib.ExitStack() as stack:
            if transfer != "allow":
                stack.enter_context(transfer_guard(transfer))
            watch = None
            if races:
                from geomesa_tpu.analysis.locksets import trace_locks

                watch = stack.enter_context(trace_locks())
            try:
                runpy.run_path(path, run_name="__main__")
            except SystemExit as e:
                code = e.code
                status = code if isinstance(code, int) else (
                    0 if code is None else 1)
            if watch is not None:
                lock_report = watch.report()
    finally:
        sys.argv = old_argv
        tracker.unwrap()
    report = tracker.report()
    if lock_report is not None:
        report["locksets"] = lock_report
    return report, status
