"""Opt-in runtime guards: recompile counters + transfer guards.

The static linter catches what the AST shows; these guards catch what
only shows up live — a retrace storm from an unhashable config object,
or a silent host round-trip on the query hot path. Both surface through
`geomesa_tpu.utils.metrics` (gauges `analysis.recompiles.<name>`,
counter `analysis.recompiles`), so the existing JSON/Prometheus
exporters pick them up with no extra wiring.

`JitTracker.wrap` wraps a single jitted callable; `guard_engine` sweeps
the engine modules and wraps every jitted callable in place (reversible
with `.unwrap()`); `transfer_guard` is a thin, version-tolerant wrapper
over `jax.transfer_guard`. All of it is pay-when-used: importing this
module does not import jax.
"""

from __future__ import annotations

import contextlib
import functools
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

# the canonical module list + jit predicate + sweep live in
# compilecache.kernels (stdlib-only import) so this tracker, the
# ExecutableRegistry default sweep and warmup check() can never drift
# apart about what the hot-kernel universe IS
from geomesa_tpu.compilecache.kernels import (  # noqa: F401 (re-export)
    ENGINE_MODULES as _ENGINE_MODULES, is_jitted, iter_jitted)

TRANSFER_MODES = ("allow", "log", "disallow")


class JitTracker:
    """Counts compile-cache growth per wrapped jitted callable.

    Every wrapped call compares `fn._cache_size()` before/after; growth
    means this call traced+compiled instead of hitting the cache. The
    counts publish to the metrics registry on every recompile and are
    queryable via `report()`. `warn_after` (per callable) invokes
    `on_storm` once when a callable exceeds it — the runtime analog of
    lint rule GT01.

    Warmup plumbing (docs/SERVING.md "Cold start"): a compiling call's
    wall time is noted into the process-wide compile-stall meter
    (`compilecache.stall.STALLS`, feeding ServeEvent attribution and the
    `compile.stall` histogram), and when a `recorder`
    (`compilecache.manifest.WarmupRecorder`) is attached, the observed
    (kernel, shapes, dtypes, static-args) tuple is recorded into the
    warmup manifest — the tuples `gmtpu warmup` later replays.
    """

    def __init__(self, registry=None, warn_after: Optional[int] = None,
                 on_storm: Optional[Callable[[str, int], None]] = None,
                 recorder=None):
        if registry is None:
            from geomesa_tpu.utils.metrics import metrics as registry
        self.registry = registry
        self.warn_after = warn_after
        self.on_storm = on_storm
        self.recorder = recorder  # read per call: attachable post-install
        self._lock = threading.Lock()
        self.recompiles: Dict[str, int] = {}
        self.calls: Dict[str, int] = {}
        self._warned: set = set()
        self._installed: List[tuple] = []  # (module, attr, original)

    def total_recompiles(self) -> int:
        with self._lock:
            return sum(self.recompiles.values())

    def wrap(self, fn, name: Optional[str] = None,
             origin: Optional[Tuple[str, str]] = None):
        """`origin` is the (full module name, attr) pair install() saw —
        the manifest needs the importable path, not just the label."""
        if not is_jitted(fn):
            raise TypeError(
                f"JitTracker.wrap expects a jax.jit callable, got {fn!r}")
        label = name or getattr(fn, "__name__", repr(fn))

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            before = fn._cache_size()
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            elapsed = time.perf_counter() - t0
            grew = fn._cache_size() - before
            storm_count = 0
            with self._lock:
                self.calls[label] = self.calls.get(label, 0) + 1
                if grew > 0:
                    n = self.recompiles.get(label, 0) + grew
                    self.recompiles[label] = n
                    self.registry.counter("analysis.recompiles", grew)
                    self.registry.gauge(
                        f"analysis.recompiles.{label}", float(n))
                    if (self.warn_after is not None
                            and n > self.warn_after
                            and label not in self._warned):
                        self._warned.add(label)
                        storm = self.on_storm
                        storm_count = n  # captured under the lock (GT07)
                    else:
                        storm = None
                else:
                    storm = None
                recorder = self.recorder if grew > 0 else None
            if grew > 0:
                # the elapsed wall of a compiling call IS the inline
                # stall a request saw (trace + compile + one execute)
                try:
                    from geomesa_tpu.compilecache.stall import STALLS

                    STALLS.note(label, elapsed)
                except Exception:
                    pass
            if recorder is not None and origin is not None:
                try:
                    recorder.record_kernel(
                        origin[0], origin[1], args, kwargs, elapsed)
                except Exception:
                    pass  # recording must never break the live call
            if storm is not None:
                storm(label, storm_count)
            return out

        wrapper._gt_tracked = fn  # type: ignore[attr-defined]
        wrapper._gt_tracker = self  # type: ignore[attr-defined]
        return wrapper

    # -- in-place module instrumentation ----------------------------------

    def install(self, module, names: Optional[List[str]] = None) -> int:
        """Wrap every jitted top-level callable of `module` in place.
        Returns how many were wrapped. Idempotent per module attr."""
        wrapped = 0
        for attr in names or sorted(vars(module)):
            obj = getattr(module, attr, None)
            if not is_jitted(obj) or hasattr(obj, "_gt_tracked"):
                continue
            label = f"{module.__name__.rsplit('.', 1)[-1]}.{attr}"
            setattr(module, attr, self.wrap(
                obj, name=label, origin=(module.__name__, attr)))
            with self._lock:
                self._installed.append((module, attr, obj))
            wrapped += 1
        return wrapped

    def unwrap(self) -> None:
        with self._lock:
            installed, self._installed = self._installed, []
        for module, attr, original in reversed(installed):
            setattr(module, attr, original)

    def is_installed(self) -> bool:
        with self._lock:
            return bool(self._installed)

    def report(self) -> Dict[str, dict]:
        with self._lock:
            return {
                name: {"calls": self.calls.get(name, 0),
                       "recompiles": self.recompiles.get(name, 0)}
                for name in sorted(set(self.calls) | set(self.recompiles))
            }


def guard_engine(registry=None, warn_after: Optional[int] = None,
                 on_storm: Optional[Callable[[str, int], None]] = None,
                 modules=None, recorder=None) -> JitTracker:
    """Wrap every jitted callable across the engine modules with one
    shared tracker (the engine's jit caches, guarded). Call `.unwrap()`
    to restore. `recorder` (a WarmupRecorder) additionally records every
    compiling signature into a warmup manifest. A tracker that actually
    wrapped something claims the process-wide active slot (see
    acquire_engine_tracker), so later sharers can find it."""
    import importlib

    global _active_tracker, _active_refs, _active_owned
    tracker = JitTracker(registry=registry, warn_after=warn_after,
                         on_storm=on_storm, recorder=recorder)
    with _active_lock:
        for modname in modules or _ENGINE_MODULES:
            try:
                mod = importlib.import_module(modname)
            except ImportError:
                continue
            tracker.install(mod)
        if tracker.is_installed():
            # direct callers (gmtpu guard) own their wrappers and unwrap
            # them themselves; acquirers sharing this epoch refcount
            # from zero and never unwrap it (see release_engine_tracker)
            _active_tracker = tracker
            _active_refs = 0
            _active_owned = False
    return tracker


# The engine jits are MODULE GLOBALS, so only one tracker's wrappers can
# be installed at a time — a second guard_engine() finds everything
# already wrapped and silently counts nothing. Long-lived consumers
# (QueryService) therefore acquire the process-wide tracker instead of
# installing their own. Acquisition is REFCOUNTED: every acquire pairs
# with a release, and the wrappers come off only when the last acquirer
# releases — closing the first of two live services must not disable
# tracking for the survivor. The RLock makes check-then-install atomic
# (guard_engine re-enters it), and _find_installed_tracker recovers a
# tracker installed OUTSIDE this protocol (e.g. `gmtpu guard` calling
# guard_engine directly) via the back-pointer every wrapper carries —
# such adopted trackers are shared but never unwrapped by release (their
# installer owns the wrappers).
_active_lock = threading.RLock()
_active_tracker: Optional[JitTracker] = None
_active_refs = 0
_active_owned = False  # True iff acquire's own install put the wrappers on


def _find_installed_tracker(modules=None) -> Optional[JitTracker]:
    """The tracker whose wrappers currently sit on the engine modules
    (every wrapper back-points to its tracker), or None."""
    import importlib

    for modname in modules or _ENGINE_MODULES:
        try:
            mod = importlib.import_module(modname)
        except ImportError:
            continue
        for attr in sorted(vars(mod)):
            tracker = getattr(getattr(mod, attr, None), "_gt_tracker", None)
            if tracker is not None:
                return tracker
    return None


def acquire_engine_tracker(recorder=None, **kwargs
                           ) -> "Tuple[JitTracker, bool]":
    """Returns (tracker, installed_by_me). EVERY acquire must be paired
    with release_engine_tracker(tracker); the wrappers come off when the
    last acquirer releases (and only if an acquire installed them)."""
    global _active_tracker, _active_refs, _active_owned
    with _active_lock:
        active = _active_tracker
        if active is not None and active.is_installed():
            if recorder is not None:
                active.recorder = recorder
            _active_refs += 1
            return active, False
        tracker = guard_engine(recorder=recorder, **kwargs)
        if tracker.is_installed():
            # guard_engine claimed the slot; this epoch is acquire-owned
            _active_refs = 1
            _active_owned = True
            return tracker, True
        # nothing wrapped: either a foreign tracker already owns the
        # modules (adopt + share it, never count-nothing silently) or no
        # engine module is importable (degenerate; the tracker is inert)
        foreign = _find_installed_tracker(kwargs.get("modules"))
        if foreign is not None:
            if recorder is not None:
                foreign.recorder = recorder
            # publish so later acquires skip the install + module scan
            _active_tracker = foreign
            _active_refs += 1
            _active_owned = False  # its installer unwraps it, not us
            return foreign, False
        return tracker, True


def release_engine_tracker(tracker: JitTracker) -> None:
    """Counterpart to acquire: drop one reference; the LAST release of
    an acquire-installed epoch restores the bare engine jits (adopted
    foreign trackers are left for their installer to unwrap). The
    tracker object and its counters remain readable. Unwrap happens
    UNDER the slot lock: restoring module attrs while a concurrent
    acquire installs a fresh tracker would interleave the two setattr
    sweeps and leave some kernels untracked. Lock order is always
    _active_lock -> tracker._lock (install/unwrap take the tracker
    lock; JitTracker never takes the slot lock)."""
    global _active_tracker, _active_refs, _active_owned
    with _active_lock:
        if tracker is not _active_tracker:
            # stale epoch (the slot moved on): restoring is this
            # tracker's own business; unwrap is a no-op if already bare
            tracker.unwrap()
            return
        _active_refs = max(_active_refs - 1, 0)
        if _active_refs == 0:
            if _active_owned:
                tracker.unwrap()
            _active_tracker = None
            _active_owned = False


def clear_engine_jit_caches(modules=None) -> int:
    """Drop every engine jit's dispatch cache (unwrapping any tracker
    wrapper). Returns how many caches were cleared — 0 when this jax
    version has no `clear_cache` on jit products. Used by the warmup
    regression tests to simulate a fresh process without spawning one."""
    cleared = 0
    for _mod, _tail, _attr, obj in iter_jitted(modules):
        if hasattr(obj, "clear_cache"):
            try:
                obj.clear_cache()
                cleared += 1
            except Exception:
                pass
    return cleared


@contextlib.contextmanager
def transfer_guard(mode: str = "disallow"):
    """`jax.transfer_guard` with a version-tolerant fallback: "log"
    logs every implicit host<->device transfer, "disallow" raises on
    them — the runtime teeth behind lint rule GT02."""
    if mode not in TRANSFER_MODES:
        raise ValueError(
            f"transfer mode must be one of {TRANSFER_MODES}, got {mode!r}")
    import jax

    guard = getattr(jax, "transfer_guard", None)
    if guard is None:  # very old jax: guard unavailable, run unguarded
        yield
        return
    with guard(mode):
        yield


def run_guarded(path: str, argv: Optional[List[str]] = None,
                transfer: str = "allow",
                warn_after: Optional[int] = None,
                on_storm: Optional[Callable[[str, int], None]] = None,
                registry=None,
                races: bool = False) -> Tuple[Dict[str, dict], int]:
    """Execute a Python script under the runtime guards (the `gmtpu
    guard` command): engine jit caches tracked, optional transfer
    guard, optional lockset race harness (`races=True`: every lock the
    script CREATES is tracked; lock-order inversions and empty-lockset
    accesses land in the report under "locksets"). Returns (report,
    script exit status) — a script ending in the standard
    `sys.exit(main())` idiom must not swallow the report, so SystemExit
    is caught and surfaced as the status."""
    import runpy
    import sys

    tracker = guard_engine(registry=registry, warn_after=warn_after,
                           on_storm=on_storm)
    old_argv = sys.argv
    sys.argv = [path] + list(argv or ())
    status = 0
    lock_report = None
    try:
        with contextlib.ExitStack() as stack:
            if transfer != "allow":
                stack.enter_context(transfer_guard(transfer))
            watch = None
            if races:
                from geomesa_tpu.analysis.locksets import trace_locks

                watch = stack.enter_context(trace_locks())
            try:
                runpy.run_path(path, run_name="__main__")
            except SystemExit as e:
                code = e.code
                status = code if isinstance(code, int) else (
                    0 if code is None else 1)
            if watch is not None:
                lock_report = watch.report()
    finally:
        sys.argv = old_argv
        tracker.unwrap()
    report = tracker.report()
    if lock_report is not None:
        report["locksets"] = lock_report
    return report, status
