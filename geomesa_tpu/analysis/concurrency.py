"""GT07..GT12: lock-discipline static analysis for the serving path.

The lockset family of analyses (Eraser, Savage et al. 1997), restricted
to what the AST can answer without running anything: infer which lock
guards each piece of shared state, then flag accesses that break the
inferred invariant. Shared-state inference is class-aware — a class that
owns a `threading.Lock`/`RLock` (or a `Condition`) has declared its
concurrency contract, and a project-wide thread-entry reachability pass
(`threading.Thread(target=...)`, executor `submit`/`map`, the serve
dispatch loop) classifies which lock-FREE classes are still reached from
threaded code.

Rules:

- GT07  unguarded access to a field that is lock-guarded elsewhere in
        the same class (torn read / lost update), plus unguarded
        container mutations in lock-owning classes.
- GT08  lock-order cycle across the project-wide lock acquisition graph
        (deadlock risk).
- GT09  blocking call while holding a lock: file I/O, device dispatch
        (`to_device`, jitted kernels, `block_until_ready`), `sleep`,
        future `.result()`, thread `.join()`, queue get/put, foreign
        condition `.wait()`.
- GT10  lock created per-call (function-local) — it guards nothing.
- GT11  callback / future `set_result` invoked while holding a lock the
        callback's consumer may also take.
- GT12  shared mutable state mutated from thread-reachable code without
        a guard: mutable default arguments, module globals, and
        container fields of lock-free classes.

Precision stance matches the GT01..GT06 rules: name-based, never
imports the analyzed code, tuned so the shipped tree is clean modulo
documented waivers. Guarded-ness is syntactic: a `with` over an
expression whose name contains "lock"/"mutex", a `with self.<lock
attr>`, a method carrying a locking decorator (`@_locked`), or a
private method whose every intra-class call site is guarded (computed
to a fixpoint).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from geomesa_tpu.analysis.model import Finding
from geomesa_tpu.analysis.modinfo import ClassInfo, ModInfo

# container-mutating method names (list/dict/set/deque)
MUTATORS = {
    "append", "appendleft", "add", "update", "extend", "insert", "pop",
    "popleft", "popitem", "clear", "discard", "remove", "setdefault",
}

_BLOCKING_ATTRS = {"block_until_ready", "device_get", "device_put"}
_CALLBACK_MARKERS = ("callback", "listener", "hook")


def _finding(rule: str, mod: ModInfo, node: ast.AST, msg: str) -> Finding:
    return Finding(rule=rule, path=mod.relpath,
                   line=getattr(node, "lineno", 0),
                   col=getattr(node, "col_offset", 0), message=msg)


def _expr_name(node: ast.AST) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{_expr_name(node.value)}.{node.attr}"
    if isinstance(node, ast.Call):
        return f"{_expr_name(node.func)}()"
    return ""


def _lockish(node: ast.AST) -> bool:
    name = _expr_name(node).lower()
    return "lock" in name or "mutex" in name


def _self_attr(node: ast.AST) -> Optional[str]:
    return ModInfo._self_attr_name(node)


def _mod_base(mod: ModInfo) -> str:
    return mod.relpath


def _enclosing_class(mod: ModInfo, node: ast.AST) -> Optional[ClassInfo]:
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.ClassDef):
            return mod.classes.get(anc.name)
    return None


def _enclosing_method(mod: ModInfo, node: ast.AST,
                      ci: ClassInfo) -> Optional[str]:
    """Name of the ci method whose body holds node (node itself when it
    is the method's def)."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        parent = mod.parent(node)
        if isinstance(parent, ast.ClassDef) and parent.name == ci.name:
            return node.name
    for anc in mod.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            parent = mod.parent(anc)
            if isinstance(parent, ast.ClassDef) and parent.name == ci.name:
                return anc.name
    return None


def _lock_id(mod: ModInfo, expr: ast.AST,
             ci: Optional[ClassInfo]) -> Optional[str]:
    """Stable identity for a lock expression, or None if it does not
    look like a lock. Class lock attrs key as "Class.attr" so every
    instance of the class maps to one graph node."""
    attr = _self_attr(expr)
    if attr is not None and ci is not None:
        if attr in ci.cond_attrs:
            return f"{ci.name}.{ci.cond_attrs[attr]}"
        if attr in ci.lock_attrs:
            return f"{ci.name}.{attr}"
    if _lockish(expr):
        name = _expr_name(expr)
        if attr is not None and ci is not None:
            return f"{ci.name}.{attr}"
        return f"{_mod_base(mod)}:{name}"
    return None


def _held_with_locks(mod: ModInfo, node: ast.AST) -> List[str]:
    """Lock ids of every `with <lock>` enclosing node (lexically)."""
    ci = _enclosing_class(mod, node)
    out: List[str] = []
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                lid = _lock_id(mod, item.context_expr, ci)
                if lid is not None:
                    out.append(lid)
    return out


# -- per-class discipline ----------------------------------------------------


class _Access:
    __slots__ = ("field", "method", "node", "kind", "guarded")

    def __init__(self, field, method, node, kind, guarded):
        self.field = field
        self.method = method
        self.node = node
        self.kind = kind          # "read" | "write" | "mutate"
        self.guarded = guarded


class _Discipline:
    """Lock discipline of one class: which methods are fully guarded
    (locking decorator), which are only ever called with the lock held
    (fixpoint over intra-class call sites), and every `self.<field>`
    access with its guarded-ness."""

    def __init__(self, mod: ModInfo, ci: ClassInfo):
        self.mod = mod
        self.ci = ci
        self.full_lock: Dict[str, str] = {}     # method -> lock attr
        self.guard_only: Set[str] = set()
        self.init_only: Set[str] = set()
        self.accesses: List[_Access] = []
        self.acquires: Dict[str, Set[str]] = {}  # method -> lock attrs
        self._intra: Dict[str, List[Tuple[str, bool]]] = {}
        self._build()

    def _build(self) -> None:
        mod, ci = self.mod, self.ci
        for name, fn in ci.methods.items():
            for dec in fn.decorator_list:
                if (isinstance(dec, ast.Name)
                        and dec.id in mod.locking_decorators):
                    self.full_lock[name] = mod.locking_decorators[dec.id]
        raw: List[_Access] = []
        for name, fn in ci.methods.items():
            aliases = self._aliases(fn)
            acq: Set[str] = set(
                [self.full_lock[name]] if name in self.full_lock else [])
            for node in ast.walk(fn):
                if isinstance(node, ast.With):
                    for item in node.items:
                        a = _self_attr(item.context_expr)
                        if a in ci.lock_attrs:
                            acq.add(a)
                        elif a in ci.cond_attrs:
                            acq.add(ci.cond_attrs[a])
                for field, kind in self._accesses_of(node, aliases):
                    raw.append(_Access(field, name, node, kind,
                                       self._guarded0(name, node)))
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    callee = _self_attr(node.func)
                    if callee in ci.methods:
                        self._intra.setdefault(callee, []).append(
                            (name, self._guarded0(name, node)))
            self.acquires[name] = acq
        self._fixpoint()
        for a in raw:
            if a.method in self.guard_only:
                a.guarded = True
        self.accesses = raw
        # propagate intra-class acquisitions (apply -> _upsert takes lock)
        changed = True
        while changed:
            changed = False
            for callee, sites in self._intra.items():
                for caller, _g in sites:
                    before = len(self.acquires.setdefault(caller, set()))
                    self.acquires[caller] |= self.acquires.get(callee, set())
                    if len(self.acquires[caller]) != before:
                        changed = True

    def _guarded0(self, method: str, node: ast.AST) -> bool:
        if method in self.full_lock:
            return True
        ci = self.ci
        for anc in self.mod.ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    a = _self_attr(item.context_expr)
                    if a in ci.lock_attrs or a in ci.cond_attrs:
                        return True
                    if _lockish(item.context_expr):
                        return True
            if isinstance(anc, ast.ClassDef):
                break
        return False

    def _aliases(self, fn: ast.FunctionDef) -> Dict[str, str]:
        """Local names bound to self fields (or elements of them):
        `cached = self._compiled_filters`, `st = self._state[name]`,
        `cached = self._compiled_filters = {}`, `x = getattr(self, "f")`.
        """
        out: Dict[str, str] = {}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            field = None
            v = node.value
            if _self_attr(v) is not None:
                field = _self_attr(v)
            elif (isinstance(v, ast.Subscript)
                  and _self_attr(v.value) is not None):
                field = _self_attr(v.value)
            elif (isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
                  and v.func.id == "getattr" and len(v.args) >= 2
                  and isinstance(v.args[0], ast.Name)
                  and v.args[0].id == "self"
                  and isinstance(v.args[1], ast.Constant)):
                field = str(v.args[1].value)
            for t in node.targets:
                if field is None and _self_attr(t) is not None:
                    field = _self_attr(t)  # chained: x = self.f = {}
            if field is None or field in self.ci.methods:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = field
        return out

    def _ref_field(self, node: ast.AST,
                   aliases: Dict[str, str]) -> Optional[str]:
        """self.F or an alias of it -> F (never a method name)."""
        attr = _self_attr(node)
        if attr is not None:
            return None if attr in self.ci.methods else attr
        if isinstance(node, ast.Name):
            return aliases.get(node.id)
        return None

    def _accesses_of(self, node: ast.AST,
                     aliases: Dict[str, str]):
        """(field, kind) accesses contributed by this single node."""
        if isinstance(node, ast.Attribute):
            attr = _self_attr(node)
            if attr is not None and attr not in self.ci.methods:
                if isinstance(node.ctx, ast.Store):
                    yield attr, "write"
                elif isinstance(node.ctx, ast.Del):
                    yield attr, "mutate"
                else:
                    yield attr, "read"
        elif isinstance(node, ast.Subscript):
            f = self._ref_field(node.value, aliases)
            if f is not None and isinstance(node.ctx, (ast.Store, ast.Del)):
                yield f, "mutate"
        elif isinstance(node, ast.AugAssign):
            # `self.f += 1` and `alias[k] += 1` are field mutations;
            # `alias += 1` on a bare local name only rebinds the local
            t = node.target
            f = None
            if _self_attr(t) is not None:
                f = self._ref_field(t, aliases)
            elif isinstance(t, ast.Subscript):
                f = self._ref_field(t.value, aliases)
            if f is not None:
                yield f, "mutate"
        elif isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in MUTATORS:
                f = self._ref_field(fn.value, aliases)
                # a field holding a project-class instance (self.queue =
                # AdmissionQueue(...)) is an object with its own
                # discipline, not a raw container — .pop()/.put() on it
                # is a method call, not a container mutation
                if f is not None and f not in self.ci.field_types:
                    yield f, "mutate"

    def _fixpoint(self) -> None:
        ci = self.ci
        changed = True
        while changed:
            changed = False
            for m in ci.methods:
                if m == "__init__" or m in self.guard_only:
                    continue
                sites = [(c, g) for c, g in self._intra.get(m, ())
                         if c != "__init__" and c not in self.init_only]
                if not sites or m not in self._intra:
                    continue
                if all(g or c in self.full_lock or c in self.guard_only
                       for c, g in sites):
                    self.guard_only.add(m)
                    changed = True
        changed = True
        while changed:
            changed = False
            for m, sites in self._intra.items():
                if m == "__init__" or m in self.init_only:
                    continue
                if sites and all(c == "__init__" or c in self.init_only
                                 for c, _g in sites):
                    self.init_only.add(m)
                    changed = True

    def effectively_guarded(self, method: str) -> Optional[str]:
        """Lock attr this method runs under, if fully guarded."""
        if method in self.full_lock:
            return self.full_lock[method]
        if method in self.guard_only:
            return next(iter(sorted(self.ci.lock_attrs)), None)
        return None


def _discipline(mod: ModInfo, ci: ClassInfo) -> _Discipline:
    cache = getattr(mod, "_gt_disciplines", None)
    if cache is None:
        cache = mod._gt_disciplines = {}  # type: ignore[attr-defined]
    if ci.name not in cache:
        cache[ci.name] = _Discipline(mod, ci)
    return cache[ci.name]


# -- project-wide concurrency index -----------------------------------------


class ConcurrencyIndex:
    """Thread-entry reachability + attribute-call-site guard map + the
    lock acquisition graph, computed once per lint run over scan AND
    reference modules (a thread started in `bench.py` makes package code
    thread-reachable just like one started inside the package)."""

    def __init__(self, modules: List[ModInfo]):
        self.modules = modules
        # every function/method (incl. nested defs), indexed by name
        self.defs: Dict[str, List[Tuple[ModInfo, ast.AST]]] = {}
        for mod in modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.defs.setdefault(node.name, []).append((mod, node))
        self.reached: Set[int] = set()
        self.reached_classes: Set[str] = set()
        self._reach()
        self.call_sites: Dict[str, List[Tuple[ModInfo, ast.Call, bool]]] = {}
        self._index_call_sites()
        self.edges: Dict[Tuple[str, str], Tuple[ModInfo, ast.AST]] = {}
        self._lock_graph()
        self.cyclic_edges: Set[Tuple[str, str]] = self._cycles()
        self._confined: Dict[str, bool] = {}
        # name -> [(mod, Call)] for every Name-called constructor site,
        # built lazily on the first class_confined query: the per-name
        # full-project walk this replaces dominated whole-repo lint
        # wall time (one walk per queried class vs one walk total)
        self._ctor_sites: Optional[
            Dict[str, List[Tuple[ModInfo, ast.Call]]]] = None

    # -- thread-entry reachability ----------------------------------------

    def _entry_defs(self) -> List[Tuple[ModInfo, ast.AST]]:
        out = []
        for mod in self.modules:
            for owner, name in mod.thread_targets:
                if owner is not None:
                    ci = mod.classes.get(owner)
                    if ci is not None and name in ci.methods:
                        out.append((mod, ci.methods[name]))
                        continue
                out.extend(
                    (m, fn) for m, fn in self.defs.get(name, ()) )
        return out

    def _reach(self) -> None:
        work = list(self._entry_defs())
        while work:
            mod, fn = work.pop()
            if id(fn) in self.reached:
                continue
            self.reached.add(id(fn))
            parent = mod.parent(fn)
            if isinstance(parent, ast.ClassDef):
                self.reached_classes.add(parent.name)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = None
                if isinstance(node.func, ast.Name):
                    name = node.func.id
                elif isinstance(node.func, ast.Attribute):
                    name = node.func.attr
                if name is None:
                    continue
                for target in self.defs.get(name, ()):
                    if id(target[1]) not in self.reached:
                        work.append(target)

    def class_reached(self, name: str) -> bool:
        return name in self.reached_classes

    def func_reached(self, fn: ast.AST) -> bool:
        return id(fn) in self.reached

    def class_confined(self, name: str) -> bool:
        """True when every constructor call of `name` in the universe
        binds the instance to a plain local in a function that spawns no
        threads, and that local never escapes (returned, stored onto an
        object/module, passed as an argument, put in a literal): such
        instances live and die inside one call frame — parser/cursor
        classes — and cannot be shared across threads."""
        if name in self._confined:
            return self._confined[name]
        if self._ctor_sites is None:
            self._ctor_sites = {}
            for mod in self.modules:
                for node in ast.walk(mod.tree):
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Name)):
                        self._ctor_sites.setdefault(
                            node.func.id, []).append((mod, node))
        sites = self._ctor_sites.get(name, [])
        ok = bool(sites)
        for mod, call in sites:
            if not self._ctor_confined(mod, call):
                ok = False
                break
        self._confined[name] = ok
        return ok

    def _ctor_confined(self, mod: ModInfo, call: ast.Call) -> bool:
        parent = mod.parent(call)
        if isinstance(parent, ast.Attribute):
            # `_Parser(text).parse()`: the temporary instance is consumed
            # by one method call and never bound at all
            return True
        if not (isinstance(parent, ast.Assign)
                and all(isinstance(t, ast.Name) for t in parent.targets)):
            return False
        fn = None
        for anc in mod.ancestors(call):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = anc
                break
        if fn is None:
            return False  # module-level instance: shared by definition
        for n in ast.walk(fn):
            if mod.is_thread_ctor(n) or (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Name)
                    and "Executor" in n.func.id):
                return False
        names = {t.id for t in parent.targets}
        for n in ast.walk(fn):
            if isinstance(n, ast.Return) and n.value is not None:
                if _uses_names(n.value, names):
                    return False
            elif isinstance(n, (ast.Yield, ast.YieldFrom)) \
                    and n.value is not None:
                if _uses_names(n.value, names):
                    return False
            elif isinstance(n, ast.Call) and n is not call:
                args = list(n.args) + [kw.value for kw in n.keywords]
                if any(isinstance(a, ast.Name) and a.id in names
                       for a in args):
                    return False
            elif isinstance(n, ast.Assign) and n is not parent:
                if isinstance(n.value, ast.Name) and n.value.id in names \
                        and any(not isinstance(t, ast.Name)
                                for t in n.targets):
                    return False
            elif isinstance(n, (ast.List, ast.Tuple, ast.Dict, ast.Set)):
                if _uses_names(n, names):
                    return False
        return True

    # -- call-site guard map ------------------------------------------------

    def _site_guarded(self, mod: ModInfo, node: ast.AST) -> bool:
        """Is this node inside any guarded region (with-lock, locking
        decorator, or guard-only method)?"""
        if _held_with_locks(mod, node):
            return True
        ci = _enclosing_class(mod, node)
        if ci is not None:
            m = _enclosing_method(mod, node, ci)
            if m is not None:
                d = _discipline(mod, ci)
                if d.effectively_guarded(m) is not None:
                    return True
        return False

    def _index_call_sites(self) -> None:
        for mod in self.modules:
            for node in ast.walk(mod.tree):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    self.call_sites.setdefault(node.func.attr, []).append(
                        (mod, node, self._site_guarded(mod, node)))

    def all_sites_guarded(self, method_name: str) -> bool:
        """True when every attribute call site of `method_name` in the
        whole universe is inside a guarded region (caller-holds-lock
        discipline: the callee does not need its own lock)."""
        sites = self.call_sites.get(method_name)
        if not sites:
            return True  # never called through an attribute: unreachable
        return all(g for _m, _n, g in sites)

    # -- lock acquisition graph (GT08) --------------------------------------

    def _class_index(self) -> Dict[str, Tuple[ModInfo, ClassInfo]]:
        out: Dict[str, Tuple[ModInfo, ClassInfo]] = {}
        for mod in self.modules:
            for name, ci in mod.classes.items():
                out.setdefault(name, (mod, ci))
        return out

    def _local_types(self, fn: ast.AST,
                     classes: Dict[str, Tuple[ModInfo, ClassInfo]],
                     ci: Optional[ClassInfo]) -> Dict[str, str]:
        """Local / field variable -> class name, from annotations
        (`cache: KafkaFeatureCache = ...`), constructor assignments and
        the enclosing class's typed fields."""
        out: Dict[str, str] = {}
        for node in ast.walk(fn):
            if (isinstance(node, ast.AnnAssign)
                    and isinstance(node.target, ast.Name)):
                ann = node.annotation
                if isinstance(ann, ast.Name) and ann.id in classes:
                    out[node.target.id] = ann.id
                elif (isinstance(ann, ast.Constant)
                      and isinstance(ann.value, str)
                      and ann.value in classes):
                    out[node.target.id] = ann.value
            elif (isinstance(node, ast.Assign) and len(node.targets) == 1
                  and isinstance(node.targets[0], ast.Name)
                  and isinstance(node.value, ast.Call)
                  and isinstance(node.value.func, ast.Name)
                  and node.value.func.id in classes):
                out[node.targets[0].id] = node.value.func.id
        return out

    def _callee_acquisitions(
        self, mod: ModInfo, call: ast.Call,
        classes: Dict[str, Tuple[ModInfo, ClassInfo]],
        ci: Optional[ClassInfo],
        local_types: Dict[str, str],
    ) -> Set[str]:
        """Lock ids a call may acquire, via typed receivers only."""
        fn = call.func
        if not isinstance(fn, ast.Attribute):
            return set()
        target_cls: Optional[str] = None
        recv = fn.value
        recv_attr = _self_attr(recv)
        if recv_attr is not None and ci is not None:
            if recv_attr in ci.field_types:
                target_cls = ci.field_types[recv_attr]
            elif recv_attr == "self":
                target_cls = ci.name
        elif isinstance(recv, ast.Name):
            if recv.id == "self" and ci is not None:
                target_cls = ci.name
            else:
                target_cls = local_types.get(recv.id)
        if target_cls is None or target_cls not in classes:
            return set()
        tmod, tci = classes[target_cls]
        d = _discipline(tmod, tci)
        return {f"{tci.name}.{a}"
                for a in d.acquires.get(fn.attr, set())}

    def _lock_graph(self) -> None:
        classes = self._class_index()
        for mod in self.modules:
            for fn in _functions(mod):
                ci = _enclosing_class(mod, fn)
                method = (_enclosing_method(mod, fn, ci)
                          if ci is not None else None)
                held_base: List[str] = []
                if ci is not None and method is not None:
                    d = _discipline(mod, ci)
                    lk = d.effectively_guarded(method)
                    if lk is not None:
                        held_base.append(f"{ci.name}.{lk}")
                local_types = self._local_types(fn, classes, ci)
                for node in _own_nodes(fn):
                    held = held_base + _held_with_locks(mod, node)
                    if not held:
                        continue
                    acquired: Set[str] = set()
                    if isinstance(node, ast.With):
                        # held comes from ANCESTOR withs only, so every
                        # lock item of this with is a fresh acquisition
                        for item in node.items:
                            lid = _lock_id(mod, item.context_expr, ci)
                            if lid is not None:
                                acquired.add(lid)
                        for h in held:
                            for a in acquired:
                                if a != h:
                                    self.edges.setdefault(
                                        (h, a), (mod, node))
                        continue
                    if isinstance(node, ast.Call):
                        acquired = self._callee_acquisitions(
                            mod, node, classes, ci, local_types)
                        for h in held:
                            for a in acquired:
                                if a != h:
                                    self.edges.setdefault(
                                        (h, a), (mod, node))

    def _cycles(self) -> Set[Tuple[str, str]]:
        """Edges that participate in a cycle (SCC with >= 2 nodes)."""
        graph: Dict[str, Set[str]] = {}
        for a, b in self.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        sccs: List[Set[str]] = []
        counter = [0]

        def strong(v: str) -> None:
            work = [(v, iter(sorted(graph[v])))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on.add(w)
                        work.append((w, iter(sorted(graph[w]))))
                        advanced = True
                        break
                    if w in on:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = set()
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        scc.add(w)
                        if w == node:
                            break
                    if len(scc) >= 2:
                        sccs.append(scc)

        for v in sorted(graph):
            if v not in index:
                strong(v)
        bad: Set[Tuple[str, str]] = set()
        for scc in sccs:
            for a, b in self.edges:
                if a in scc and b in scc:
                    bad.add((a, b))
        return bad


def _uses_names(node: ast.AST, names: Set[str]) -> bool:
    return any(isinstance(s, ast.Name) and s.id in names
               for s in ast.walk(node))


def _functions(mod: ModInfo):
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _own_nodes(fn: ast.AST):
    """Nodes of fn excluding nested function bodies (each nested def is
    walked on its own by _functions)."""
    skip: Set[int] = set()
    for n in ast.walk(fn):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)) and n is not fn:
            for sub in ast.walk(n):
                if sub is not n:
                    skip.add(id(sub))
    for n in ast.walk(fn):
        if id(n) not in skip and n is not fn:
            yield n


def _concurrency_index(project) -> ConcurrencyIndex:
    idx = getattr(project, "_gt_concurrency", None)
    if idx is None:
        idx = ConcurrencyIndex(project.modules + project.ref_modules)
        project._gt_concurrency = idx
    return idx


# -- GT07: inconsistent lock discipline within a class -----------------------


def gt07(mod: ModInfo, project) -> Iterator[Finding]:
    """In a class that owns a lock: a field guarded by the lock in one
    method but accessed bare in another (torn read / lost update), or a
    container field mutated with no guard at all. Fields written only in
    __init__ are immutable and exempt; private helpers whose every call
    site holds the lock count as guarded (fixpoint)."""
    for ci in mod.classes.values():
        if not ci.lock_attrs and not ci.cond_attrs:
            continue
        d = _discipline(mod, ci)
        by_field: Dict[str, List[_Access]] = {}
        for a in d.accesses:
            by_field.setdefault(a.field, []).append(a)
        for field, accs in sorted(by_field.items()):
            non_init = [a for a in accs
                        if a.method != "__init__"
                        and a.method not in d.init_only]
            writes = [a for a in non_init if a.kind in ("write", "mutate")]
            if not writes:
                continue  # immutable after construction
            guarded = [a for a in non_init if a.guarded]
            unguarded = [a for a in non_init if not a.guarded]
            if not unguarded:
                continue
            lock = sorted(ci.lock_attrs)[0] if ci.lock_attrs else \
                sorted(ci.cond_attrs.values())[0]
            seen: Set[str] = set()
            if guarded:
                for a in unguarded:
                    if a.method in seen:
                        continue
                    seen.add(a.method)
                    yield _finding(
                        "GT07", mod, a.node,
                        f"field '{field}' of {ci.name} is guarded by "
                        f"self.{lock} elsewhere but {_verb(a.kind)} "
                        f"without it in {a.method!r}: torn read / lost "
                        f"update under the serve threads")
            else:
                for a in unguarded:
                    if a.kind != "mutate" or a.method in seen:
                        continue
                    seen.add(a.method)
                    yield _finding(
                        "GT07", mod, a.node,
                        f"container field '{field}' of lock-owning class "
                        f"{ci.name} is mutated in {a.method!r} without "
                        f"self.{lock}: racy against the guarded methods")


def _verb(kind: str) -> str:
    return {"read": "read", "write": "written",
            "mutate": "mutated"}[kind]


# -- GT08: lock-order cycles -------------------------------------------------


def gt08(mod: ModInfo, project) -> Iterator[Finding]:
    """Project-wide lock acquisition graph: `with A: ... with B:` (or a
    call into a lock-taking method of a typed field) adds edge A->B; any
    cycle is a deadlock waiting for the right interleaving. Findings
    anchor at each acquisition edge inside the scanned module."""
    idx = _concurrency_index(project)
    for (a, b) in sorted(idx.cyclic_edges):
        emod, enode = idx.edges[(a, b)]
        if emod is not mod:
            continue
        cycle = _cycle_text(idx, a, b)
        yield _finding(
            "GT08", mod, enode,
            f"lock-order cycle: {a} is held while acquiring {b}, but the "
            f"reverse order also exists ({cycle}): deadlock risk")


def _cycle_text(idx: ConcurrencyIndex, a: str, b: str) -> str:
    rev = [(x, y) for (x, y) in idx.cyclic_edges if x != a or y != b]
    parts = [f"{a} -> {b}"] + [f"{x} -> {y}" for x, y in sorted(rev)]
    return ", ".join(parts[:4])


# -- GT09: blocking call while holding a lock --------------------------------


def gt09(mod: ModInfo, project) -> Iterator[Finding]:
    """Blocking operations inside a guarded region serialize every other
    thread contending for the lock behind device dispatches, file I/O or
    sleeps — the direct throughput killer for the serve dispatch path."""
    jit_names = _project_jit_names(mod)
    for fn in _functions(mod):
        ci = _enclosing_class(mod, fn)
        base_guard = False
        if ci is not None:
            m = _enclosing_method(mod, fn, ci)
            if m is not None:
                base_guard = _discipline(mod, ci).effectively_guarded(m) \
                    is not None
        for node in _own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            if not base_guard and not _held_with_locks(mod, node):
                continue
            hit = _blocking_hit(mod, node, jit_names, ci)
            if hit is not None:
                yield _finding(
                    "GT09", mod, node,
                    f"{hit} while holding a lock: every contending "
                    f"thread stalls behind it (move it outside the "
                    f"guarded region or waive with a justification)")


def _blocking_hit(mod: ModInfo, call: ast.Call, jit_names: Set[str],
                  ci: Optional[ClassInfo]) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        if f.id == "open":
            return "file I/O (open)"
        if f.id == "to_device":
            return "device upload (to_device)"
        if f.id == "sleep":
            return "sleep"
        if f.id in jit_names:
            return f"device dispatch ({f.id})"
        return None
    if not isinstance(f, ast.Attribute):
        return None
    recv = _expr_name(f.value).lower()
    if f.attr in _BLOCKING_ATTRS:
        return f"device sync ({f.attr})"
    if f.attr == "to_device":
        return "device upload (to_device)"
    if f.attr == "sleep" and isinstance(f.value, ast.Name) \
            and f.value.id in mod.time_aliases:
        return "sleep"
    if f.attr == "result" and "fut" in recv:
        return "future .result()"
    if f.attr == "join" and any(s in recv
                                for s in ("thread", "worker", "proc")):
        return "thread join"
    if f.attr in ("get", "put") and "queue" in recv:
        return f"queue .{f.attr}()"
    if f.attr == "wait":
        attr = _self_attr(f.value)
        if attr is not None and ci is not None \
                and attr in ci.cond_attrs:
            # waiting on a condition releases its own tied lock — only a
            # FOREIGN lock held around the wait blocks
            return None
        return "blocking .wait()"
    if f.attr in jit_names:
        return f"device dispatch ({f.attr})"
    return None


def _project_jit_names(mod: ModInfo) -> Set[str]:
    names = getattr(mod, "_gt_project_jit_names", None)
    if names is not None:
        return names
    return {jd.name for jd in mod.jit_defs}


# -- GT10: per-call lock -----------------------------------------------------


def gt10(mod: ModInfo, project) -> Iterator[Finding]:
    """A lock created as a function local and only used inside that same
    call guards nothing — every caller gets a fresh lock. Orchestrators
    that hand the lock to worker closures/threads are exempt."""
    for fn in _functions(mod):
        spawns = any(
            mod.is_thread_ctor(n)
            or (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and "Executor" in n.func.id)
            or (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in ("submit", "map")
                and not isinstance(n.func.value, ast.Constant))
            for n in ast.walk(fn))
        if spawns:
            continue
        locals_: Dict[str, ast.AST] = {}
        for node in _own_nodes(fn):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and mod.is_lock_ctor(node.value)):
                locals_[node.targets[0].id] = node
        if not locals_:
            continue
        escaped: Set[str] = set()
        for name in locals_:
            for node in ast.walk(fn):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)) and node is not fn:
                    if any(isinstance(s, ast.Name) and s.id == name
                           for s in ast.walk(node)):
                        escaped.add(name)
                elif isinstance(node, ast.Return) and node.value is not None:
                    if any(isinstance(s, ast.Name) and s.id == name
                           for s in ast.walk(node.value)):
                        escaped.add(name)
                elif isinstance(node, ast.Assign):
                    if (any(not isinstance(t, ast.Name)
                            for t in node.targets)
                            and isinstance(node.value, ast.Name)
                            and node.value.id == name):
                        escaped.add(name)
                elif isinstance(node, ast.Call):
                    if any(isinstance(a, ast.Name) and a.id == name
                           for a in node.args):
                        escaped.add(name)
        for name, node in sorted(locals_.items(),
                                 key=lambda kv: kv[1].lineno):
            if name in escaped:
                continue
            yield _finding(
                "GT10", mod, node,
                f"lock {name!r} is created per-call inside "
                f"{fn.name!r} and never escapes: every caller gets a "
                f"fresh lock, so it guards nothing (make it an instance "
                f"or module attribute)")


# -- GT11: callback / set_result under a lock --------------------------------


def gt11(mod: ModInfo, project) -> Iterator[Finding]:
    """Resolving a future or invoking a caller-supplied callback while
    holding a lock runs unknown consumer code inside the critical
    section: if that consumer takes the same lock (or a lock ordered
    before it), it deadlocks; at best it stretches the hold time."""
    for fn in _functions(mod):
        ci = _enclosing_class(mod, fn)
        base_guard = False
        if ci is not None:
            m = _enclosing_method(mod, fn, ci)
            if m is not None:
                base_guard = _discipline(mod, ci).effectively_guarded(m) \
                    is not None
        params = set()
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            params = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
        listener_loops = _listener_loop_vars(fn)
        for node in _own_nodes(fn):
            if not isinstance(node, ast.Call):
                continue
            if not base_guard and not _held_with_locks(mod, node):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in (
                    "set_result", "set_exception"):
                yield _finding(
                    "GT11", mod, node,
                    f"future .{f.attr}() under a lock: done-callbacks "
                    f"and waiters run inside the critical section "
                    f"(resolve futures after releasing the lock)")
            elif isinstance(f, ast.Name) and (
                    (f.id in params and _callbackish(f.id))
                    or f.id in listener_loops):
                yield _finding(
                    "GT11", mod, node,
                    f"callback {f.id!r} invoked while holding a lock: "
                    f"its consumer may take the same lock (deadlock) or "
                    f"stretch the critical section")


def _callbackish(name: str) -> bool:
    low = name.lower()
    return low.startswith("on_") or any(
        s in low for s in _CALLBACK_MARKERS)


def _listener_loop_vars(fn: ast.AST) -> Set[str]:
    """`for cb in self._listeners: cb(...)` — loop vars drawn from
    listener/callback-named fields."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.For):
            continue
        src = _expr_name(node.iter).lower()
        if any(s in src for s in _CALLBACK_MARKERS):
            out |= {n.id for n in ast.walk(node.target)
                    if isinstance(n, ast.Name)}
    return out


# -- GT12: unguarded shared mutable state ------------------------------------


def gt12(mod: ModInfo, project) -> Iterator[Finding]:
    """Three shapes of shared state mutated from thread-reachable code
    with no guard: (a) mutable default arguments that the body mutates,
    (b) module-global containers (or `global` rebinds) mutated outside
    any lock, (c) container fields of LOCK-FREE classes whose mutating
    methods have at least one unguarded call site (classes whose every
    call site holds a caller's lock follow the caller-holds-lock
    discipline and are exempt)."""
    idx = _concurrency_index(project)
    yield from _gt12_defaults(mod)
    yield from _gt12_globals(mod, idx)
    yield from _gt12_classes(mod, idx)


def _gt12_defaults(mod: ModInfo) -> Iterator[Finding]:
    for fn in _functions(mod):
        args = fn.args
        pos = args.posonlyargs + args.args
        defaults = args.defaults
        pairs = list(zip(pos[len(pos) - len(defaults):], defaults))
        pairs += [(a, d) for a, d in zip(args.kwonlyargs, args.kw_defaults)
                  if d is not None]
        for arg, default in pairs:
            if not isinstance(default, (ast.List, ast.Dict, ast.Set)):
                continue
            if _mutates_name(fn, arg.arg):
                yield _finding(
                    "GT12", mod, default,
                    f"mutable default argument {arg.arg!r} of "
                    f"{fn.name!r} is mutated in the body: one shared "
                    f"instance across ALL calls and threads")


def _mutates_name(fn: ast.AST, name: str) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) and node.func.attr in MUTATORS:
            if isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == name:
                return True
        elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            if isinstance(node.value, ast.Name) and node.value.id == name:
                return True
        elif isinstance(node, ast.AugAssign):
            t = node.target
            if isinstance(t, ast.Name) and t.id == name:
                return True
            if isinstance(t, ast.Subscript) and isinstance(
                    t.value, ast.Name) and t.value.id == name:
                return True
    return False


def _gt12_globals(mod: ModInfo, idx: ConcurrencyIndex) -> Iterator[Finding]:
    globals_: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            v = node.value
            container = isinstance(v, (ast.List, ast.Dict, ast.Set)) or (
                isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
                and v.func.id in ("dict", "list", "set", "deque",
                                  "defaultdict", "OrderedDict"))
            if container:
                globals_.add(node.targets[0].id)
    for fn in _functions(mod):
        if not idx.func_reached(fn):
            continue
        declared = {n for s in ast.walk(fn) if isinstance(s, ast.Global)
                    for n in s.names}
        seen: Set[str] = set()
        for node in _own_nodes(fn):
            name = None
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute) \
                    and node.func.attr in MUTATORS \
                    and isinstance(node.func.value, ast.Name):
                name = node.func.value.id
            elif isinstance(node, ast.Subscript) and isinstance(
                    node.ctx, (ast.Store, ast.Del)) \
                    and isinstance(node.value, ast.Name):
                name = node.value.id
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id in declared:
                name = node.targets[0].id
            if name is None or name in seen:
                continue
            if name not in globals_ and name not in declared:
                continue
            if _local_shadow(fn, name) and name not in declared:
                continue
            if _held_with_locks(mod, node):
                continue
            seen.add(name)
            yield _finding(
                "GT12", mod, node,
                f"module global {name!r} mutated from thread-reachable "
                f"{fn.name!r} with no lock held: lost updates / torn "
                f"state under concurrent callers")


def _local_shadow(fn: ast.AST, name: str) -> bool:
    """Is `name` rebound as a plain local anywhere in fn (so the
    mutation touches a local, not the module global)?"""
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return True
        elif isinstance(node, (ast.For,)):
            if any(isinstance(n, ast.Name) and n.id == name
                   for n in ast.walk(node.target)):
                return True
    return False


def _gt12_classes(mod: ModInfo, idx: ConcurrencyIndex) -> Iterator[Finding]:
    for ci in mod.classes.values():
        if ci.lock_attrs or ci.cond_attrs:
            continue  # lock-owning classes are GT07's jurisdiction
        if not idx.class_reached(ci.name):
            continue
        if idx.class_confined(ci.name):
            continue  # instances never leave one call frame
        d = _discipline(mod, ci)
        seen: Set[Tuple[str, str]] = set()
        for a in d.accesses:
            if a.kind != "mutate" or a.guarded:
                continue
            if a.method == "__init__" or a.method in d.init_only:
                continue
            if (a.method, a.field) in seen:
                continue
            if idx.all_sites_guarded(a.method):
                continue  # caller-holds-lock discipline
            seen.add((a.method, a.field))
            yield _finding(
                "GT12", mod, a.node,
                f"lock-free class {ci.name} is reached from thread "
                f"entry points but {a.method!r} mutates shared field "
                f"'{a.field}' with no guard: add a lock or confine "
                f"instances to one thread (waive with justification)")


CONCURRENCY_RULES = {
    "GT07": gt07, "GT08": gt08, "GT09": gt09,
    "GT10": gt10, "GT11": gt11, "GT12": gt12,
}
