"""Waiver-file support for gmtpu-lint.

Two waiver channels exist:

1. Inline comments, parsed per-module by `ModInfo`:
       x = y.astype(np.float64)  # gt: f64-refine
       some_call(...)            # gt: waive GT01
   A directive on a comment-only line also covers the next code line.

2. A committed waiver file (default: `.gmtpu-waivers` at the repo root,
   or --waivers PATH), one entry per line:

       # comment
       <path-glob> <RULE|*> [line]

   Paths are matched against the finding's repo-relative posix path with
   `fnmatch` (so `geomesa_tpu/engine/*.py` works). A bare rule of `*`
   waives every rule for the glob; an optional line number pins the
   waiver to one site so it goes stale loudly when the code moves.
"""

from __future__ import annotations

import fnmatch
import os
from dataclasses import dataclass
from typing import List, Optional

from geomesa_tpu.analysis.model import Finding

DEFAULT_WAIVER_FILENAME = ".gmtpu-waivers"


@dataclass(frozen=True)
class WaiverEntry:
    glob: str
    rule: str          # "GT03" or "*"
    line: Optional[int]
    origin: str        # "file:lineno" for reporting

    def matches(self, f: Finding) -> bool:
        if self.rule != "*" and self.rule != f.rule:
            return False
        if self.line is not None and self.line != f.line:
            return False
        path = f.path.replace(os.sep, "/")
        return (fnmatch.fnmatch(path, self.glob)
                or fnmatch.fnmatch(os.path.basename(path), self.glob))


def load_waiver_file(path: str) -> List[WaiverEntry]:
    entries: List[WaiverEntry] = []
    with open(path, encoding="utf-8") as fh:
        for i, raw in enumerate(fh, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            if len(parts) not in (2, 3):
                raise ValueError(
                    f"{path}:{i}: expected '<glob> <RULE|*> [line]', "
                    f"got {line!r}")
            ln: Optional[int] = None
            if len(parts) == 3:
                try:
                    ln = int(parts[2])
                except ValueError:
                    raise ValueError(
                        f"{path}:{i}: line must be an integer, "
                        f"got {parts[2]!r}") from None
            entries.append(WaiverEntry(glob=parts[0], rule=parts[1],
                                       line=ln, origin=f"{path}:{i}"))
    return entries


def apply_file_waivers(findings: List[Finding],
                       entries: List[WaiverEntry]) -> None:
    for f in findings:
        if f.waived:
            continue
        for e in entries:
            if e.matches(f):
                f.waived = True
                f.waived_by = e.origin
                break
