"""Waiver-file support for gmtpu-lint.

Two waiver channels exist:

1. Inline comments, parsed per-module by `ModInfo`:
       x = y.astype(np.float64)  # gt: f64-refine
       some_call(...)            # gt: waive GT01
   A directive on a comment-only line also covers the next code line.

2. A committed waiver file (default: `.gmtpu-waivers` at the repo root,
   or --waivers PATH), one entry per line:

       # comment
       <path-glob> <RULE|*> [line]
       severity <RULE> <info|warn|error>

   Paths are matched against the finding's repo-relative posix path with
   `fnmatch` (so `geomesa_tpu/engine/*.py` works). A bare rule of `*`
   waives every rule for the glob; an optional line number pins the
   waiver to one site so it goes stale loudly when the code moves.
   `severity` lines re-classify a rule for the whole run (e.g. land a
   new advisory rule as `info` so `--fail-on warn` ignores it until the
   tree is clean).

Waivers (file or inline) naming a rule code that does not exist raise a
ValueError instead of silently never matching — a typo must not read as
"waived".
"""

from __future__ import annotations

import fnmatch
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from geomesa_tpu.analysis.model import SEVERITIES, RULES, Finding

DEFAULT_WAIVER_FILENAME = ".gmtpu-waivers"


def check_rule_code(code: str, origin: str) -> None:
    """Unknown rule codes in waivers are an error, not a silent skip."""
    if code != "*" and code not in RULES:
        raise ValueError(
            f"{origin}: unknown rule code {code!r} "
            f"(have {', '.join(sorted(RULES))})")


@dataclass(frozen=True)
class WaiverEntry:
    glob: str
    rule: str          # "GT03" or "*"
    line: Optional[int]
    origin: str        # "file:lineno" for reporting

    def matches(self, f: Finding) -> bool:
        if self.rule != "*" and self.rule != f.rule:
            return False
        if self.line is not None and self.line != f.line:
            return False
        path = f.path.replace(os.sep, "/")
        return (fnmatch.fnmatch(path, self.glob)
                or fnmatch.fnmatch(os.path.basename(path), self.glob))


def load_waiver_file(
    path: str,
) -> Tuple[List[WaiverEntry], Dict[str, str]]:
    """Parse a waiver file into (entries, severity overrides)."""
    entries: List[WaiverEntry] = []
    severities: Dict[str, str] = {}
    with open(path, encoding="utf-8") as fh:
        for i, raw in enumerate(fh, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            if parts[0] == "severity":
                if len(parts) != 3 or parts[2] not in SEVERITIES:
                    raise ValueError(
                        f"{path}:{i}: expected 'severity <RULE> "
                        f"<{'|'.join(SEVERITIES)}>', got {line!r}")
                check_rule_code(parts[1], f"{path}:{i}")
                if parts[1] == "*":
                    raise ValueError(
                        f"{path}:{i}: severity needs a concrete rule code")
                severities[parts[1]] = parts[2]
                continue
            if len(parts) not in (2, 3):
                raise ValueError(
                    f"{path}:{i}: expected '<glob> <RULE|*> [line]', "
                    f"got {line!r}")
            check_rule_code(parts[1], f"{path}:{i}")
            ln: Optional[int] = None
            if len(parts) == 3:
                try:
                    ln = int(parts[2])
                except ValueError:
                    raise ValueError(
                        f"{path}:{i}: line must be an integer, "
                        f"got {parts[2]!r}") from None
            entries.append(WaiverEntry(glob=parts[0], rule=parts[1],
                                       line=ln, origin=f"{path}:{i}"))
    return entries, severities


def apply_file_waivers(findings: List[Finding],
                       entries: List[WaiverEntry]) -> None:
    for f in findings:
        if f.waived:
            continue
        for e in entries:
            if e.matches(f):
                f.waived = True
                f.waived_by = e.origin
                break
