"""Finding model + rule registry for gmtpu-lint.

Every rule reports `Finding`s with a stable code (GT01..GT06), a file:line
anchor, and a message precise enough to act on. Severity is uniform
("warn") today; the gate's --fail-on flag decides what fails the build,
so new advisory rules can land as "info" without breaking CI.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

SEVERITIES = ("info", "warn", "error")

# Rule-set fingerprint component for the incremental cache: bump on any
# PR that adds/changes rule semantics so a cache written by an older
# rule set can never replay stale findings as a byte-identical "warm"
# result (analysis/incremental.py stamps it into .gmtpu-lintcache).
ANALYSIS_VERSION = "18.0"


@dataclass(frozen=True)
class Rule:
    code: str
    title: str
    severity: str = "warn"


RULES: Dict[str, Rule] = {
    r.code: r
    for r in (
        Rule("GT01", "retrace storm: loop-varying or unhashable value "
                     "passed to a static jit argument"),
        Rule("GT02", "implicit host transfer inside jit scope"),
        Rule("GT03", "dtype drift: float64 reachable from an f32 kernel "
                     "path without a '# gt: f64-refine' waiver"),
        Rule("GT04", "unsynced timing: device dispatch timed without "
                     "block_until_ready (or another sync) before the "
                     "closing timestamp"),
        Rule("GT05", "dead jit entry point: jitted callable with no "
                     "remaining call sites"),
        Rule("GT06", "inconsistent mask plumbing: sibling call sites of "
                     "the same kernel disagree on validity masking"),
        Rule("GT07", "inconsistent lock discipline: field guarded by the "
                     "class lock in one method, accessed bare in another"),
        Rule("GT08", "lock-order cycle in the project-wide lock "
                     "acquisition graph (deadlock risk)"),
        Rule("GT09", "blocking call (file I/O, device dispatch, sleep, "
                     "future.result, queue get/put) while holding a lock"),
        Rule("GT10", "per-call lock: created as a function local, guards "
                     "nothing"),
        Rule("GT11", "callback or future set_result invoked while "
                     "holding a lock its consumer may also take"),
        Rule("GT12", "shared mutable state (mutable default, module "
                     "global, lock-free class field) mutated from "
                     "thread-reachable code without a guard"),
        Rule("GT13", "serve/plan hot-path jax.jit site bypasses the "
                     "compilecache ExecutableRegistry (invisible to "
                     "warmup manifests; compiles inline under traffic)"),
        Rule("GT14", "error-swallowing I/O: bare/broad except that "
                     "discards the failure, or an unbounded while-True "
                     "retry loop around an I/O call site (use the "
                     "faults/ retry fabric: bounded, typed, "
                     "deadline-aware)"),
        Rule("GT15", "telemetry discipline: time.time() measuring a "
                     "duration (wall clock is not monotonic — spans and "
                     "latencies must use perf_counter/monotonic), or a "
                     "tracer .span() opened outside a `with` block "
                     "(leaks an unbalanced open span)"),
        Rule("GT16", "blocking call (block_until_ready / future "
                     ".result() / jax.device_get) inside a pipeline "
                     "prepare/transfer/launch stage: the stage must "
                     "return before the device finishes or window "
                     "overlap silently dies (sync belongs on the "
                     "completer)"),
        Rule("GT17", "blocking call (I/O, future .result(), device "
                     "sync/transfer, sleep) inside a subscription "
                     "listener/callback body: feature-event listeners "
                     "run inside the Kafka fold (store lock held) — "
                     "they must only buffer; evaluation belongs in the "
                     "post-fold pump (subscribe/evaluator.py)"),
        Rule("GT18", "per-device placement bypassing NamedSharding: a "
                     "device_put/to_device loop over jax.devices() (or "
                     "an alias), or jax.devices()[i] indexing, in "
                     "serve//plan/ scope — sharded serving places data "
                     "ONCE via NamedSharding over the mesh; ad-hoc "
                     "per-chip placement breaks tile ownership and "
                     "forces per-dispatch reshards"),
        Rule("GT19", "inconsistent metric label sets: the same metric "
                     "family emitted with different label-key sets "
                     "across call sites (serve//telemetry/ scope) — "
                     "the series silently forks (one family, "
                     "incompatible label schemas) and Prometheus "
                     "scrapes/dashboard joins break"),
        Rule("GT20", "unbounded socket call in the fleet tier: "
                     "connect/recv/accept without settimeout (or "
                     "create_connection without timeout=) in "
                     "fleet//serve/protocol.py scope — one dead peer "
                     "wedges the router's reader thread and with it "
                     "every client's failover"),
        Rule("GT21", "result-cache key built from raw CQL text instead "
                     "of the canonical ast.to_cql form: equivalent "
                     "filter spellings fork the key space into a "
                     "cache-miss storm (serve/approx/plan scope)"),
        Rule("GT22", "per-row serialization in a wire-encode loop "
                     "(serve//subscribe/ scope): json.dumps inside a "
                     "loop or a per-iteration dict comprehension pays "
                     "N encodes for N rows/subscribers — encode once "
                     "and ship columnar frames / fan through PushMux "
                     "(docs/SERVING.md \"Columnar wire\")"),
        Rule("GT23", "blocking host sync (block_until_ready / future "
                     ".result() / device_get) or naked per-window "
                     "device_put/to_device inside the ring feed loop "
                     "scope (serve/ringloop.py): the persistent serve "
                     "loop's per-window work is ONLY a stager slot "
                     "write + one pre-compiled dispatch — waits belong "
                     "to the completer's harvest, transfers to the "
                     "ring stager (docs/SERVING.md \"Persistent serve "
                     "loop\")"),
        Rule("GT24", "unbound collective axis: a jax.lax collective "
                     "(psum/all_gather/ppermute/axis_index/...) whose "
                     "axis name no enclosing shard_map/pjit wrap and no "
                     "calling context binds — traces only under a mesh "
                     "that defines the axis; on a pod it fails or hangs "
                     "at first dispatch"),
        Rule("GT25", "process-divergent control flow: a branch on "
                     "jax.process_index()/process_count() or an "
                     "os.environ read whose arms differ in collective-"
                     "relevant effects (collectives issued, "
                     "jax.config.update) on a distributed-reachable "
                     "path — processes take different sides and the "
                     "pod's collective sequences stop matching (the "
                     "static deadlock detector; CPU CI runs one "
                     "process and can never see it)"),
        Rule("GT26", "sharding-spec drift: in_specs/out_specs/"
                     "PartitionSpec/NamedSharding naming a mesh axis "
                     "the constructing mesh (or any project mesh) does "
                     "not define, or a literal in_specs tuple whose "
                     "arity disagrees with the mapped function's "
                     "positional parameters"),
        Rule("GT27", "ungated process-local side effect: an atomic "
                     "persist (tmp + os.replace) or port bind on a "
                     "multi-process-reachable path (parallel//store//"
                     "compilecache//serve//telemetry//approx/ scope) "
                     "without a parallel.is_coordinator()/"
                     "process_index()==0 gate — every host of a pod "
                     "performs it against shared storage"),
        Rule("GT28", "recompile storm (static): a raw (unbucketed) "
                     "dynamic shape — len()/np.asarray over wire "
                     "payloads — reaches a jit/AOT/ring dispatch on "
                     "the hot path; every distinct extent compiles a "
                     "fresh executable — pad through pad_to/next_pow2/"
                     "stack_queries so the shape set stays the warmup "
                     "manifest's"),
        Rule("GT29", "f64 exactness leak: an f32-cast value flows into "
                     "an exact-f64 consumer (f64 upcast site or a "
                     "*_f64 parameter) without passing the canonical "
                     "f64 recompute — upcasting rounded f32 restores "
                     "nothing; answers drift an ulp"),
        Rule("GT30", "unmatchable registry key: an AOT/ring lookup "
                     "names a variant key no registry.register/"
                     "serve_variant/ring_variant/mesh_variant site in "
                     "the project can produce — the warmup manifest "
                     "can never warm this caller (KeyError or inline "
                     "compile under traffic)"),
        Rule("GT31", "device→host→device bounce: a jax.device_get "
                     "result transitively re-enters device_put or a "
                     "dispatch — two transfers plus a host sync where "
                     "zero were needed; keep the device reference"),
    )
}


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    severity: str = "warn"
    waived: bool = False
    waived_by: str = ""
    extra: dict = field(default_factory=dict)

    def render(self) -> str:
        tag = " (waived)" if self.waived else ""
        return (f"{self.path}:{self.line}:{self.col} "
                f"{self.rule} [{self.severity}]{tag} {self.message}")

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "severity": self.severity,
            "message": self.message,
            "waived": self.waived,
            **({"waived_by": self.waived_by} if self.waived else {}),
        }
