"""geomesa_tpu — a TPU-native geospatial analytics framework.

A brand-new, columnar, Arrow-first re-design of GeoMesa's capabilities
(reference: nstires-boundless/geomesa; upstream locationtech/geomesa) for
JAX/XLA/Pallas on TPU:

- ``core``    — SimpleFeatureType schemas, columnar feature batches, Arrow IO
                (semantic parity with geomesa-utils SimpleFeatureTypes and
                geomesa-arrow SimpleFeatureVector).
- ``curve``   — Z2/Z3/XZ2/XZ3 space-filling curves, BinnedTime, range
                decomposition (parity with geomesa-z3 org.locationtech.geomesa.curve
                and the sfcurve dependency).
- ``cql``     — ECQL parser, filter analysis (geometry/interval extraction) and
                a predicate compiler to jitted mask functions (parity with
                geomesa-filter FastFilterFactory/FilterHelper).
- ``store``   — filesystem (Parquet) datastore with partition schemes and
                pruning (parity with geomesa-fs), plus a device cache manager.
- ``engine``  — the TPU kernel suite replacing server-side iterator scans
                (geomesa-index-api iterators: DensityScan, ArrowScan, BinScan,
                StatsScan) and process hot loops: filter masks, point-in-polygon,
                haversine kNN, density scatter, tube-select, stats reductions.
- ``plan``    — query planner, hints, explain, audit (parity with
                geomesa-index-api planning: QueryPlanner, QueryHints, Explainer).
- ``process`` — analytics process library (parity with geomesa-process):
                KNN, Density, TubeSelect, Proximity, Unique, Stats, Sampling...
- ``convert`` — converter-lite ingest framework (parity with geomesa-convert).
- ``stats``   — mergeable stat sketches + Stat DSL (parity with geomesa-utils
                org.locationtech.geomesa.utils.stats).
- ``security``— visibility expressions (parity with geomesa-security).
- ``faults``  — fault-injection harness (named sites at every dependency
                boundary, seeded replayable FaultPlans) + the recovery
                fabric: typed error taxonomy, deadline-aware retry with
                full-jitter backoff, per-dependency circuit breakers,
                device-OOM host-eval fallback, poison-query quarantine,
                and the ``gmtpu chaos`` invariant gate (no upstream
                analog; docs/ROBUSTNESS.md).
- ``cli``     — command-line tools (parity with geomesa-tools).

Parallelism: feature batches shard over a ``jax.sharding.Mesh`` axis "shard";
aggregations merge with XLA collectives (psum / all_gather / ring top-k over
ICI) — the TPU-native replacement for Accumulo/HBase server-side fan-in.
"""

__version__ = "0.1.0"

from geomesa_tpu.core.sft import SimpleFeatureType, AttributeDescriptor
from geomesa_tpu.core.columnar import FeatureBatch

__all__ = [
    "SimpleFeatureType",
    "AttributeDescriptor",
    "FeatureBatch",
    "__version__",
]
