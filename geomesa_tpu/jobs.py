"""Parallel ingest/export jobs.

Parity: geomesa-jobs + the distributed halves of the ingest/export CLI
(SURVEY.md C19/C20: ConverterInputFormat -> mapper -> GeoMesaOutputFormat)
[upstream, unverified]. The reference distributes per-file converter tasks
over MapReduce/Spark; the analog here is a thread pool converting files
concurrently (parsing is I/O + pyarrow/numpy work that releases the GIL)
with a single writer fold, which preserves the reference's contract:
per-file task granularity, per-file failure isolation, resumability at file
granularity (§5.4 — completed files are recorded and skipped on re-run).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from concurrent.futures import ThreadPoolExecutor, as_completed
from typing import Callable, List, Optional, Sequence

from geomesa_tpu.core.columnar import FeatureBatch


@dataclasses.dataclass
class IngestReport:
    files_ok: List[str]
    files_failed: List[str]  # "path: error"
    features: int
    skipped: List[str]  # already-ingested files (resume)
    records_failed: int = 0  # per-record converter failures in ok files


def _checkpoint_path(storage_root: str) -> str:
    return os.path.join(storage_root, ".ingest_checkpoint.json")


def _load_checkpoint(storage_root: str) -> set:
    p = _checkpoint_path(storage_root)
    if os.path.exists(p):
        with open(p) as f:
            return set(json.load(f).get("done", []))
    return set()


def _save_checkpoint(storage_root: str, done: set) -> None:
    p = _checkpoint_path(storage_root)
    tmp = p + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"done": sorted(done)}, f)
    os.replace(tmp, p)


def ingest_files(
    source,
    converter_factory: Callable[[], object],
    files: Sequence[str],
    workers: int = 4,
    resume: bool = True,
    on_error: str = "continue",  # or "raise"
) -> IngestReport:
    """Convert + write many files concurrently through one feature source.

    `source` is any object with .write(batch) and .storage.root (the FS
    FeatureSource); `converter_factory` builds a SimpleFeatureConverter
    (.convert(path) -> FeatureBatch) — one per worker thread, because
    converters keep per-run state (failure counters). Files already
    recorded in the ingest checkpoint are skipped when `resume` (upstream:
    ingest resumability at file granularity).
    """
    root = source.storage.root
    done = _load_checkpoint(root) if resume else set()
    todo = [f for f in files if os.path.abspath(f) not in done]
    skipped = [f for f in files if os.path.abspath(f) in done]
    ok: List[str] = []
    failed: List[str] = []
    total = 0
    rec_failed = 0
    write_lock = threading.Lock()
    tls = threading.local()

    def task(path: str):
        if not hasattr(tls, "conv"):
            tls.conv = converter_factory()
        batch = tls.conv.convert(path)
        return path, batch, int(getattr(tls.conv, "failed", 0))

    with ThreadPoolExecutor(max_workers=max(1, workers)) as pool:
        futures = {pool.submit(task, f): f for f in todo}
        for fut in as_completed(futures):
            try:
                path, batch, n_bad = fut.result()
            except Exception as e:  # per-file failure isolation
                failed.append(
                    f"{futures[fut]}: {e.__class__.__name__}: {e}"
                )
                if on_error == "raise":
                    for other in futures:
                        other.cancel()
                    raise
                continue
            rec_failed += n_bad
            if batch is not None and len(batch):
                with write_lock:  # single-writer fold
                    source.write(batch)
                total += len(batch)
            ok.append(path)
            done.add(os.path.abspath(path))
            if resume:
                with write_lock:
                    _save_checkpoint(root, done)
    return IngestReport(ok, failed, total, skipped, rec_failed)


def export_partitions(
    source,
    writer: Callable[[str, FeatureBatch], None],
    cql: str = "INCLUDE",
    workers: int = 4,
    partitions: Optional[Sequence[str]] = None,
) -> List[str]:
    """Per-partition parallel export (the distributed-export analog):
    `writer(partition_name, batch)` is called once per non-empty partition,
    concurrently. Returns the partitions exported."""
    from geomesa_tpu.cql import compile_filter, parse_cql
    from geomesa_tpu.cql import ast as _ast
    from geomesa_tpu.engine.device import to_device

    import numpy as np

    storage = source.storage
    names = list(partitions) if partitions is not None else storage.partitions()
    f = parse_cql(cql)
    compiled = None if isinstance(f, _ast.Include) else compile_filter(f, storage.sft)

    def task(name: str):
        batches = list(storage.scan_partitions([name]))
        if not batches:
            return None
        batch = FeatureBatch.concat(batches)
        if compiled is not None:
            dev = to_device(batch)
            # f64 borderline refinement for polygon predicates (no-op
            # otherwise) keeps distributed exports oracle-exact
            mask = compiled.mask_refined(dev, batch)
            batch = batch.select(np.nonzero(mask)[0])
        if not len(batch):
            return None
        writer(name, batch)
        return name

    out = []
    with ThreadPoolExecutor(max_workers=max(1, workers)) as pool:
        for res in pool.map(task, names):
            if res is not None:
                out.append(res)
    return sorted(out)
