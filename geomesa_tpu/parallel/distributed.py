"""Multi-host distributed initialization (DCN) for the shard mesh.

Parity: the reference's distributed runtime is storage RPC + Zookeeper
coordination (SURVEY.md C27/§5.8); the TPU-native equivalent is
`jax.distributed` over DCN with one global mesh on the same "shard" axis
the single-host kernels already use. Because every kernel in engine/ is
written against the mesh axis name (not a device count), scaling to
multi-host is configuration, not code: collectives ride ICI within a slice
and DCN across slices, routed by XLA.

Usage on each host (same program, standard JAX multi-host SPMD):

    from geomesa_tpu.parallel.distributed import initialize, global_mesh
    initialize(coordinator="host0:1234", num_processes=4, process_id=ID)
    mesh = global_mesh()           # one "shard" axis over ALL devices
    dev = shard_batch_host(local_batch, mesh)   # per-host arrays
    grid = density_sharded(mesh, ...)           # psum crosses hosts

Host-level data feeding follows the reference's storage division: each host
reads its own partitions (FS store over a shared filesystem), mirroring
per-tablet data locality; result merging is the collectives' job.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from geomesa_tpu.parallel.mesh import SHARD_AXIS


def initialize(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """jax.distributed.initialize with env-var fallback
    (GEOMESA_TPU_COORDINATOR / _NUM_PROCESSES / _PROCESS_ID; on Cloud TPU
    pods all three are auto-detected and may be omitted)."""
    import jax

    coordinator = coordinator or os.environ.get("GEOMESA_TPU_COORDINATOR")
    if num_processes is None and "GEOMESA_TPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["GEOMESA_TPU_NUM_PROCESSES"])
    if process_id is None and "GEOMESA_TPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["GEOMESA_TPU_PROCESS_ID"])
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def is_coordinator() -> bool:
    """True on process 0 — and in every single-process run (the fast
    path: an uninitialized distributed runtime is process 0 of 1, and
    `jax.process_index()` answers without touching the network).

    This is the gate for shared-storage side effects — store metadata,
    device-cache manifests, sketch sidecars, SLO baselines, warmup
    manifests (gmtpu-lint GT27): exactly one host of a pod may perform
    them, or N processes race identical (or worse, divergent) writes
    into one file. Per-partition data writes stay per-host by design
    (`process_partitions`) and are waived, not gated."""
    try:
        import jax

        return int(jax.process_index()) == 0
    except Exception:
        # jax unavailable or backend not yet up: by definition not a
        # multi-process run — behave like the single-process path
        return True


def process_suffix() -> str:
    """'' in single-process runs, '.p<idx>' on a pod — appended to
    per-process debug artifacts (flight dumps) whose value is per-host,
    so hosts never collide on shared storage yet nothing is lost."""
    try:
        import jax

        if int(jax.process_count()) > 1:
            return f".p{int(jax.process_index())}"
    except Exception:
        pass
    return ""


def runtime_fingerprint() -> int:
    """A 31-bit digest of the process-local knobs that reshape every
    compiled program (the GT25 divergence surface): the effective x64
    switch, the env var that selects it, and the jax version. Two
    processes with different fingerprints would compile different
    sharded programs against the same mesh — mismatched collectives, a
    silent pod hang."""
    import hashlib

    import jax

    parts = (
        str(bool(jax.config.jax_enable_x64)),
        os.environ.get("GEOMESA_TPU_ENABLE_X64", "1"),
        jax.__version__,
    )
    digest = hashlib.sha256("|".join(parts).encode("utf-8")).digest()
    return int.from_bytes(digest[:4], "big") & 0x7FFFFFFF


def assert_uniform_runtime(mesh=None) -> None:
    """Collectively verify every process runs the same program-shaping
    configuration before any kernel dispatches: each process contributes
    its `runtime_fingerprint()` on its shard of the global mesh; a
    pmin/pmax pair then proves all contributions equal. The check itself
    is divergence-proof — it runs on fixed int32 whatever the x64 knobs
    say — so it detects exactly the drift it guards against instead of
    hanging on it. Raises RuntimeError on mismatch (the worker should
    die loudly NOW, not deadlock at the first real psum).

    Call it right after `initialize()` (parallel/launch.py does); it is
    a cheap no-op-equivalent on a single process."""
    import functools

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from geomesa_tpu.utils.jaxcompat import shard_map as _shard_map

    mesh = mesh if mesh is not None else global_mesh()
    fp = runtime_fingerprint()
    n = int(mesh.devices.size)
    host = np.full((n,), fp, np.int32)
    spec = NamedSharding(mesh, P(SHARD_AXIS))
    # every process fills only its addressable shards — the standard
    # per-host feeding idiom (launch.smoke_step's `put`)
    vals = jax.make_array_from_callback((n,), spec, lambda idx: host[idx])

    @functools.partial(_shard_map, mesh=mesh, in_specs=(P(SHARD_AXIS),),
                       out_specs=(P(), P()), check_vma=False)
    def minmax(v):
        return (jax.lax.pmin(v[0], SHARD_AXIS),
                jax.lax.pmax(v[0], SHARD_AXIS))

    lo, hi = minmax(vals)
    lo, hi = int(lo), int(hi)
    if lo != hi:
        raise RuntimeError(
            f"divergent runtime configuration across processes: "
            f"fingerprint spread [{lo}, {hi}], local {fp} (process "
            f"{jax.process_index()}/{jax.process_count()}). Check "
            f"GEOMESA_TPU_ENABLE_X64 and jax versions on every host — "
            f"divergent programs deadlock at the first collective."
        )


def global_mesh():
    """One 1-D mesh with the shard axis over every device of every host.

    jax.devices() is globally consistent across processes after
    initialize(), so each host constructs the identical mesh."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()), (SHARD_AXIS,))


def process_partitions(partitions, process_id=None, num_processes=None):
    """Deterministic partition->host assignment for host-local feeding:
    host i reads partitions[i::P] (the per-tablet locality analog). Same
    list on every host => disjoint, exhaustive coverage."""
    import jax

    pid = process_id if process_id is not None else jax.process_index()
    n = num_processes if num_processes is not None else jax.process_count()
    return sorted(partitions)[pid::n]
