"""Multi-host distributed initialization (DCN) for the shard mesh.

Parity: the reference's distributed runtime is storage RPC + Zookeeper
coordination (SURVEY.md C27/§5.8); the TPU-native equivalent is
`jax.distributed` over DCN with one global mesh on the same "shard" axis
the single-host kernels already use. Because every kernel in engine/ is
written against the mesh axis name (not a device count), scaling to
multi-host is configuration, not code: collectives ride ICI within a slice
and DCN across slices, routed by XLA.

Usage on each host (same program, standard JAX multi-host SPMD):

    from geomesa_tpu.parallel.distributed import initialize, global_mesh
    initialize(coordinator="host0:1234", num_processes=4, process_id=ID)
    mesh = global_mesh()           # one "shard" axis over ALL devices
    dev = shard_batch_host(local_batch, mesh)   # per-host arrays
    grid = density_sharded(mesh, ...)           # psum crosses hosts

Host-level data feeding follows the reference's storage division: each host
reads its own partitions (FS store over a shared filesystem), mirroring
per-tablet data locality; result merging is the collectives' job.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from geomesa_tpu.parallel.mesh import SHARD_AXIS


def initialize(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """jax.distributed.initialize with env-var fallback
    (GEOMESA_TPU_COORDINATOR / _NUM_PROCESSES / _PROCESS_ID; on Cloud TPU
    pods all three are auto-detected and may be omitted)."""
    import jax

    coordinator = coordinator or os.environ.get("GEOMESA_TPU_COORDINATOR")
    if num_processes is None and "GEOMESA_TPU_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["GEOMESA_TPU_NUM_PROCESSES"])
    if process_id is None and "GEOMESA_TPU_PROCESS_ID" in os.environ:
        process_id = int(os.environ["GEOMESA_TPU_PROCESS_ID"])
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_mesh():
    """One 1-D mesh with the shard axis over every device of every host.

    jax.devices() is globally consistent across processes after
    initialize(), so each host constructs the identical mesh."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()), (SHARD_AXIS,))


def process_partitions(partitions, process_id=None, num_processes=None):
    """Deterministic partition->host assignment for host-local feeding:
    host i reads partitions[i::P] (the per-tablet locality analog). Same
    list on every host => disjoint, exhaustive coverage."""
    import jax

    pid = process_id if process_id is not None else jax.process_index()
    n = num_processes if num_processes is not None else jax.process_count()
    return sorted(partitions)[pid::n]
