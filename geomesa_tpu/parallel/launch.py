"""Multi-host launch harness: jax.distributed over DCN.

Parity: the reference's distributed batch compute (SURVEY.md C26) runs on
Spark/MapReduce clusters; the TPU-native equivalent is multi-host JAX — one
process per host, `jax.distributed.initialize` over the DCN coordinator,
one global Mesh spanning every host's chips, the SAME shard_map kernels as
single-host (collectives ride ICI within a slice and DCN across hosts;
SURVEY.md §5.8 commits to XLA collectives only, no NCCL/MPI).

Two entry points:

- `python -m geomesa_tpu.parallel.launch --num-processes N` (launcher):
  spawns N local processes wired to a localhost coordinator — the CI-able
  smoke test proving the multi-process path end-to-end on CPU devices
  without TPU hardware (the reference's "mini-cluster in one box" testing
  idea, §4).
- `python -m geomesa_tpu.parallel.launch --process-id I --num-processes N
  --coordinator HOST:PORT` (worker): one per real host in production; on
  TPU pods, `initialize()` with no args picks the coordinator from the
  TPU environment instead.

The smoke step runs a real sharded query step (predicate mask -> density
psum + moments psum over the global mesh) on deterministic synthetic data
and verifies the merged results against a host NumPy oracle in EVERY
process — a wrong collective cannot pass.
"""

from __future__ import annotations

import argparse
import os
import sys


def _force_cpu() -> None:
    """Pin this process to the virtual-CPU platform (mirrors
    tests/conftest.py: the axon image pins jax_platforms=axon at plugin
    registration, so env vars alone cannot select CPU)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.experimental.pallas  # noqa: F401  (register lowering rules)
    from jax._src import xla_bridge as _xb

    for _name in ("axon", "tpu"):
        _xb._backend_factories.pop(_name, None)
    jax.config.update("jax_platforms", "cpu")


def smoke_step(verbose: bool = True) -> dict:
    """One sharded query step over the GLOBAL mesh; oracle-checked."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from geomesa_tpu.engine.density import density_sharded
    from geomesa_tpu.engine.stats import masked_moments, stats_sharded
    from geomesa_tpu.parallel.mesh import SHARD_AXIS

    devices = np.asarray(jax.devices())
    mesh = Mesh(devices, (SHARD_AXIS,))
    n = len(devices) * 512
    rng = np.random.default_rng(42)  # same seed in every process
    x = rng.uniform(-60, 60, n).astype(np.float32)
    y = rng.uniform(-45, 45, n).astype(np.float32)
    score = rng.uniform(-10, 10, n).astype(np.float32)

    spec = NamedSharding(mesh, P(SHARD_AXIS))

    def put(arr):
        # every process holds the full (deterministic) array; each
        # contributes only its addressable shards
        return jax.make_array_from_callback(
            arr.shape, spec, lambda idx: arr[idx]
        )

    gx, gy, gs = put(x), put(y), put(score)
    mask_np = (np.abs(x) < 50) & (score > 0)
    gmask = put(mask_np)

    grid = density_sharded(
        mesh, gx, gy, put(np.ones(n, np.float32)), gmask,
        (-60.0, -45.0, 60.0, 45.0), 16, 16,
    )
    c, s, ss = stats_sharded(
        mesh, lambda v, m: masked_moments(v, m), gs, gmask
    )

    # oracle check in EVERY process: psum over DCN must reproduce the
    # single-host NumPy truth
    want_count = int(mask_np.sum())
    got_mass = float(np.asarray(grid).sum())
    got_count = int(np.asarray(c))
    want_sum = float(score[mask_np].sum())
    got_sum = float(np.asarray(s))
    ok = (
        round(got_mass) == want_count
        and got_count == want_count
        and abs(got_sum - want_sum) < 1e-2
    )
    out = {
        "process": jax.process_index(),
        "processes": jax.process_count(),
        "devices": len(devices),
        "count": got_count,
        "grid_mass": got_mass,
        "ok": ok,
    }
    if verbose:
        print(f"multihost-smoke {out}", flush=True)
    if not ok:
        raise AssertionError(f"multi-host collective mismatch: {out}")
    return out


def run_worker(coordinator: str, num_processes: int, process_id: int) -> None:
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    # before any real kernel: prove every process compiled from the same
    # program-shaping config (x64 knobs, jax version) — divergent env
    # across hosts deadlocks at the first psum, invisibly (GT25); this
    # check fails loudly instead
    from geomesa_tpu.parallel.distributed import assert_uniform_runtime

    assert_uniform_runtime()
    smoke_step()


def launch_local(num_processes: int, port: int = 29511) -> int:
    """Spawn N local worker processes over a localhost coordinator (the
    2-process DCN smoke test). Returns the number of failed workers."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # each worker gets ONE cpu device so the global mesh really spans
    # processes (collectives must cross the process boundary)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    procs = []
    for i in range(num_processes):
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-m", "geomesa_tpu.parallel.launch",
                    "--coordinator", f"127.0.0.1:{port}",
                    "--num-processes", str(num_processes),
                    "--process-id", str(i),
                ],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    failed = 0
    for i, p in enumerate(procs):
        out, _ = p.communicate(timeout=300)
        sys.stdout.write(out)
        if p.returncode != 0:
            failed += 1
            print(f"worker {i} FAILED (rc={p.returncode})", flush=True)
    return failed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--num-processes", type=int, default=2)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--coordinator", default=None)
    ap.add_argument("--port", type=int, default=29511)
    args = ap.parse_args(argv)

    if args.process_id is None:
        # launcher mode: spawn the workers locally
        return launch_local(args.num_processes, args.port)
    # worker mode
    _force_cpu()
    run_worker(args.coordinator, args.num_processes, args.process_id)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
