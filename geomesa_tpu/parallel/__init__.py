"""Device mesh + sharding utilities.

Parity role: the reference's parallelism inventory (SURVEY.md C24-C27) —
range-partitioned scan parallelism and server-side compute offload — becomes
data-parallel sharding of the feature batch axis over a 1-D `jax.sharding.Mesh`
axis "shard", with XLA collectives (psum / all_gather / ppermute over ICI)
replacing client-coordinated fan-in merges. There is no NCCL/MPI: ICI/DCN via
XLA is the whole communication backend (SURVEY.md §5.8).
"""

from geomesa_tpu.parallel.distributed import is_coordinator
from geomesa_tpu.parallel.mesh import (
    SHARD_AXIS,
    default_mesh,
    shard_device_batch,
    shard_batch_host,
    replicated,
)

__all__ = [
    "SHARD_AXIS",
    "default_mesh",
    "is_coordinator",
    "shard_device_batch",
    "shard_batch_host",
    "replicated",
]
