"""Mesh construction and batch sharding.

The feature-batch axis is the one meaningful parallel axis for this workload
(SURVEY.md C24): every kernel is a masked map/reduction over features, so a
1-D mesh with axis "shard" covers DP-style scaling; multi-host runs extend the
same axis over DCN via jax.distributed initialization (no code change in the
kernels — XLA routes collectives over ICI within a slice and DCN across).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.engine.device import VALID, DeviceBatch, to_device

SHARD_AXIS = "shard"


def default_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (SHARD_AXIS,))


def serve_mesh(spec="auto", devices=None) -> Optional[Mesh]:
    """Resolve a `ServeConfig.mesh` spec to a serving mesh, or None for
    the single-chip path.

    spec: None/"off"/1 -> None (single-chip);
          "auto"       -> all local devices when more than one exists,
                          else None (the satellite default: single-chip
                          on 1 device, sharded on >1);
          N (int/str)  -> the first N devices (ValueError if fewer);
          a Mesh       -> passed through.
    """
    if spec is None or isinstance(spec, Mesh):
        return spec
    if isinstance(spec, str):
        s = spec.strip().lower()
        if s in ("off", "none", "", "1"):
            return None
        if s == "auto":
            devs = devices if devices is not None else jax.devices()
            return default_mesh(devs) if len(devs) > 1 else None
        try:
            spec = int(s)
        except ValueError:
            raise ValueError(
                f"mesh spec must be auto|N|off, got {spec!r}") from None
    if spec <= 1:
        return None
    devs = list(devices if devices is not None else jax.devices())
    if len(devs) < spec:
        raise ValueError(
            f"mesh={spec} requested but only {len(devs)} device(s) "
            f"available")
    return default_mesh(devs[:spec])


def replicated(mesh: Mesh, x):
    return jax.device_put(x, NamedSharding(mesh, P()))


def shard_view(arr, shard: int, shard_rows: int, device=None):
    """The device-local view of one shard's rows of a mesh-sharded (or
    replicated) array — zero-copy when a local shard on `device` covers
    the row range (`addressable_shards` lookup), a gathered slice
    otherwise. Used by the shard-affinity serve route: a window whose
    tiles all live on one chip runs a single-device kernel against that
    chip's resident rows instead of a whole-mesh program. For a
    replicated array (P() placement) the `device` replica is returned
    whole, so staged query buffers resolve to the owning chip's copy."""
    lo = shard * shard_rows
    try:
        for s in arr.addressable_shards:
            if device is not None and s.device != device:
                continue
            idx = s.index[0] if s.index else slice(None)
            start = idx.start or 0
            stop = idx.stop if idx.stop is not None else arr.shape[0]
            if start <= lo and lo + shard_rows <= stop:
                data = s.data
                if start != lo or stop != lo + shard_rows:
                    data = data[lo - start:lo - start + shard_rows]
                return data
    except Exception:
        pass
    # fallback (unexpected layout): a cross-device slice — slower,
    # never wrong
    out = arr[lo:lo + shard_rows]
    return jax.device_put(out, device) if device is not None else out


def shard_device_batch(dev: DeviceBatch, mesh: Mesh) -> DeviceBatch:
    """Shard feature-axis arrays over the mesh; CSR buffers stay replicated.

    Arrays whose leading dim equals the batch length shard on axis 0; the
    batch length must divide evenly (pad first — pad_to a multiple of the
    mesh size; the validity mask keeps padding inert).
    """
    n = int(dev[VALID].shape[0])
    d = mesh.devices.size
    if n % d != 0:
        raise ValueError(
            f"batch length {n} not divisible by mesh size {d}; pad_to first"
        )
    row = NamedSharding(mesh, P(SHARD_AXIS))
    rep = NamedSharding(mesh, P())
    out = {}
    for k, v in dev.items():
        if v.ndim >= 1 and v.shape[0] == n and not k.endswith(
            ("__verts", "__rings", "__featr", "__vfeat", "__ex1", "__ey1", "__ex2", "__ey2", "__efeat")
        ):
            out[k] = jax.device_put(v, row)
        else:
            out[k] = jax.device_put(v, rep)
    return out


def shard_batch_host(
    batch: FeatureBatch, mesh: Mesh, coord_dtype=jnp.float32
) -> DeviceBatch:
    """Host FeatureBatch -> padded, sharded device batch."""
    d = mesh.devices.size
    n = len(batch)
    padded = batch.pad_to(((n + d - 1) // d) * d) if n % d else batch
    if padded.valid is None:
        padded = padded.pad_to(len(padded))  # force a validity mask
    return shard_device_batch(to_device(padded, coord_dtype), mesh)
