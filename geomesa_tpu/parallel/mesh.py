"""Mesh construction and batch sharding.

The feature-batch axis is the one meaningful parallel axis for this workload
(SURVEY.md C24): every kernel is a masked map/reduction over features, so a
1-D mesh with axis "shard" covers DP-style scaling; multi-host runs extend the
same axis over DCN via jax.distributed initialization (no code change in the
kernels — XLA routes collectives over ICI within a slice and DCN across).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.engine.device import VALID, DeviceBatch, to_device

SHARD_AXIS = "shard"


def default_mesh(devices=None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (SHARD_AXIS,))


def replicated(mesh: Mesh, x):
    return jax.device_put(x, NamedSharding(mesh, P()))


def shard_device_batch(dev: DeviceBatch, mesh: Mesh) -> DeviceBatch:
    """Shard feature-axis arrays over the mesh; CSR buffers stay replicated.

    Arrays whose leading dim equals the batch length shard on axis 0; the
    batch length must divide evenly (pad first — pad_to a multiple of the
    mesh size; the validity mask keeps padding inert).
    """
    n = int(dev[VALID].shape[0])
    d = mesh.devices.size
    if n % d != 0:
        raise ValueError(
            f"batch length {n} not divisible by mesh size {d}; pad_to first"
        )
    row = NamedSharding(mesh, P(SHARD_AXIS))
    rep = NamedSharding(mesh, P())
    out = {}
    for k, v in dev.items():
        if v.ndim >= 1 and v.shape[0] == n and not k.endswith(
            ("__verts", "__rings", "__featr", "__vfeat", "__ex1", "__ey1", "__ex2", "__ey2", "__efeat")
        ):
            out[k] = jax.device_put(v, row)
        else:
            out[k] = jax.device_put(v, rep)
    return out


def shard_batch_host(
    batch: FeatureBatch, mesh: Mesh, coord_dtype=jnp.float32
) -> DeviceBatch:
    """Host FeatureBatch -> padded, sharded device batch."""
    d = mesh.devices.size
    n = len(batch)
    padded = batch.pad_to(((n + d - 1) // d) * d) if n % d else batch
    if padded.valid is None:
        padded = padded.pad_to(len(padded))  # force a validity mask
    return shard_device_batch(to_device(padded, coord_dtype), mesh)
