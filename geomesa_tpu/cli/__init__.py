"""Command-line tools (parity target: geomesa-tools)."""
