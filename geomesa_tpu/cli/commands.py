"""CLI subcommands.

Parity: geomesa-tools commands [upstream, unverified]: create-schema,
describe-schema, get-type-names, remove-schema, ingest, export, explain,
stats-analyze/bounds/count/histogram/top-k, delete-features (via
remove-schema), env. All commands take --catalog (the catalog directory,
standing in for the reference's store connection params).
"""

from __future__ import annotations

import argparse
import json
import sys


def register(sub: "argparse._SubParsersAction") -> None:
    def cmd(name, help_, fn, args):
        p = sub.add_parser(name, help=help_)
        for flags, kw in args:
            p.add_argument(*flags, **kw)
        p.set_defaults(func=fn)
        return p

    cat = (["--catalog", "-c"], {"required": True, "help": "catalog directory"})
    feat = (["--feature-name", "-f"], {"required": True, "help": "feature type name"})
    cql = (["--cql", "-q"], {"default": "INCLUDE", "help": "ECQL filter"})

    cmd("version", "print version", _version, [])
    cmd(
        "create-schema", "create a feature type",
        _create_schema,
        [cat, feat,
         (["--spec", "-s"], {"required": True, "help": "SFT spec string"}),
         (["--partition-scheme"], {"default": None,
          "help": "JSON scheme config (default: daily datetime)"}),
         (["--encoding"], {"default": "parquet",
          "choices": ["parquet", "orc"], "help": "file encoding"})],
    )
    cmd("get-type-names", "list feature types", _get_type_names, [cat])
    cmd("describe-schema", "show a feature type", _describe_schema, [cat, feat])
    cmd("remove-schema", "drop a feature type and its data", _remove_schema, [cat, feat])
    # destructive: the filter is REQUIRED (the shared --cql default of
    # INCLUDE would make a forgotten -q silently delete everything —
    # round-4 review); delete-all must be spelled out as -q INCLUDE
    cmd("delete-features", "delete features matching a CQL filter "
        "(explicit -q INCLUDE deletes all)",
        _delete_features,
        [cat, feat,
         (["--cql", "-q"], {"required": True, "help": "ECQL filter "
                            "(INCLUDE = delete every feature)"})])
    cmd("age-off", "delete features older than an ISO instant",
        _age_off,
        [cat, feat,
         (["--older-than"], {"required": True,
                             "help": "ISO-8601 instant (e.g. "
                                     "2020-06-01T00:00:00Z)"})])
    cmd(
        "ingest", "ingest files through a converter (or Arrow IPC "
                  "record-batch files columnar, no converter needed)",
        _ingest,
        [cat, feat,
         (["--converter", "-C"], {"required": False, "default": None,
          "help": "converter config JSON file, or a well-known name "
                  "(gdelt|ais|nyctaxi); optional for .arrow/.ipc "
                  "inputs, which ingest columnar via write_batch"}),
         (["--arrow"], {"action": "store_true",
          "help": "treat every input as an Arrow IPC stream file "
                  "(the columnar bulk-ingest path — record-batch "
                  "buffers flow in as NumPy views, no per-feature "
                  "dicts; docs/SERVING.md \"Columnar wire\")"}),
         (["--workers"], {"type": int, "default": 1,
          "help": "parallel converter threads (distributed-ingest analog)"}),
         (["--no-resume"], {"action": "store_true",
          "help": "ignore the per-file ingest checkpoint"}),
         (["files"], {"nargs": "+", "help": "input files"})],
    )
    cmd(
        "export", "export features",
        _export,
        [cat, feat, cql,
         (["--output", "-o"], {"default": "-", "help": "output path (- = stdout)"}),
         (["--format", "-F"], {"default": "csv",
          "choices": ["csv", "tsv", "json", "gml", "arrow", "bin", "wkt",
                      "shp", "parquet", "orc", "leaflet"]}),
         (["--attributes", "-a"], {"default": None, "help": "comma-sep projection"}),
         (["--max-features", "-m"], {"type": int, "default": None}),
         (["--bin-track"], {"default": None, "help": "track attr for bin format"}),
         (["--crs"], {"default": None,
          "help": "output CRS: an EPSG code (4326, 3857, UTM 326xx/327xx) "
                  "or 'utm' to pick the zone of the query bbox center"})],
    )
    cmd("explain", "print the query plan", _explain, [cat, feat, cql])
    cmd("stats-analyze", "compute and persist stats", _stats_analyze, [cat, feat])
    cmd("stats-bounds", "attribute bounds", _stats_bounds,
        [cat, feat, cql, (["--attributes", "-a"], {"default": None})])
    cmd("stats-count", "feature count", _stats_count,
        [cat, feat, cql, (["--no-exact"], {"action": "store_true"})])
    cmd(
        "stats-histogram", "attribute histogram", _stats_histogram,
        [cat, feat, cql,
         (["--attribute", "-a"], {"required": True}),
         (["--bins"], {"type": int, "default": 10})],
    )
    cmd(
        "stats-top-k", "most frequent values", _stats_topk,
        [cat, feat, cql,
         (["--attribute", "-a"], {"required": True}),
         (["--k"], {"type": int, "default": 10})],
    )
    cmd("manage-partitions", "list partitions and their files",
        _manage_partitions, [cat, feat])
    cmd("compact", "merge each partition's files into one", _compact,
        [cat, feat,
         (["--partition"], {"default": None, "help": "limit to one partition"})])
    cmd("env", "show system properties", _env, [])
    cmd(
        "sql", "run a SQL SELECT against the catalog",
        _sql,
        [cat,
         (["--query", "-q"], {"required": True, "help": "SQL SELECT text"}),
         (["--format", "-F"], {"default": "csv",
          "choices": ["csv", "json"], "help": "output format"})],
    )
    cmd(
        "bench", "run a BASELINE benchmark config",
        _bench,
        [(["--config"], {"type": int, "default": 3,
          "choices": [1, 2, 3, 4, 5, 6],
          "help": "BASELINE.json config (3 = headline BBOX+time+kNN, "
                  "6 = polygon density)"}),
         (["--smoke"], {"action": "store_true",
          "help": "small sizes, force CPU"}),
         (["--dist"], {"choices": ["uniform", "clustered"],
          "default": "uniform",
          "help": "configs 3/4: data distribution"}),
         (["--cold"], {"action": "store_true",
          "help": "config 1: also time the parquet->device cold path"}),
         (["--n"], {"type": int, "default": None, "help": "points"})],
    )

    # serve subsystem (docs/SERVING.md): concurrent query serving with
    # admission control + request coalescing
    serve_p = sub.add_parser(
        "serve", help="concurrent query serving: JSON-lines requests on "
                      "stdin (or --input), responses on stdout")
    serve_p.add_argument("--catalog", "-c", default=None,
                         help="catalog directory (required unless "
                              "--self-check)")
    serve_p.add_argument("--input", default="-",
                         help="JSON-lines request file (- = stdin)")
    serve_p.add_argument("--self-check", action="store_true",
                         help="run the end-to-end serving smoke against "
                              "a throwaway store and exit")
    serve_p.add_argument("--max-queue", type=int, default=128,
                         help="admission queue bound (backpressure)")
    serve_p.add_argument("--max-batch", type=int, default=64,
                         help="coalescing cap per device dispatch")
    serve_p.add_argument("--max-wait-ms", type=float, default=2.0,
                         help="coalescing window (added-latency ceiling)")
    serve_p.add_argument("--timeout-ms", type=int, default=None,
                         help="default per-request deadline")
    serve_p.add_argument("--tenant-rate", type=float, default=None,
                         help="per-tenant rate limit in qps")
    serve_p.add_argument("--degrade", action="store_true",
                         help="enable the overload degradation ladder")
    serve_p.add_argument("--no-device-cache", action="store_true",
                         help="serve from the scan path instead of "
                              "HBM-resident partitions")
    serve_p.add_argument("--mesh", default="auto", metavar="auto|N|off",
                         help="sharded serving (docs/SERVING.md): "
                              "route live traffic through the "
                              "multi-chip engine. auto (default) = "
                              "single-chip on 1 device, sharded over "
                              "all devices when >1; N = first N "
                              "devices; off = single-chip")
    serve_p.add_argument("--metrics", action="store_true",
                         help="print Prometheus metrics to stderr on exit")
    serve_p.add_argument("--metrics-port", type=int, default=None,
                         metavar="PORT",
                         help="serve live /metrics + /healthz + "
                              "/debug/traces|stats|gap on this port "
                              "(0 = OS-assigned; docs/OBSERVABILITY.md)")
    serve_p.add_argument("--metrics-interval", type=float, default=None,
                         metavar="SECONDS",
                         help="print a Prometheus snapshot to stderr "
                              "every N seconds (long-running serves "
                              "without --metrics-port aren't blind)")
    serve_p.add_argument("--trace", action="store_true",
                         help="enable per-query span tracing into the "
                              "flight recorder (read via "
                              "/debug/traces or gmtpu trace)")
    serve_p.add_argument("--slo", default=None, metavar="SPEC",
                         help="SLO spec (.toml or .json, docs/"
                              "OBSERVABILITY.md): evaluate declared "
                              "objectives over sliding windows, export "
                              "slo.* burn gauges + /debug/slo, and "
                              "feed the --degrade ladder on budget "
                              "exhaustion")
    serve_p.add_argument("--profile", action="store_true",
                         help="continuous profiler: fold every traced "
                              "query into lifetime per-phase/kernel/"
                              "shard distributions (/debug/prof, "
                              "gmtpu prof; implies --trace)")
    serve_p.add_argument("--flight-dump", default=None, metavar="PATH",
                         help="flight-recorder crash-dump path (default: "
                              "$GEOMESA_TPU_FLIGHT_DUMP or a pid file "
                              "in the temp dir)")
    serve_p.add_argument("--warmup", default=None, metavar="MANIFEST",
                         help="warmup manifest to replay before accepting "
                              "traffic (docs/SERVING.md cold start)")
    serve_p.add_argument("--track-compiles", action="store_true",
                         help="count engine recompiles and attribute "
                              "inline compile stalls in ServeEvents")
    serve_p.add_argument("--live-poll-ms", type=float, default=None,
                         metavar="MS",
                         help="standing queries: auto-poll the live "
                              "store every MS milliseconds while "
                              "subscriptions are active (push frames "
                              "arrive without explicit poll verbs; "
                              "docs/SERVING.md \"Standing queries\")")
    serve_p.add_argument("--max-subscriptions", type=int, default=256,
                         help="standing-query table bound")
    serve_p.set_defaults(func=_serve)

    warm_p = sub.add_parser(
        "warmup", help="replay a warmup manifest: pre-compile every "
                       "recorded kernel/query shape (and persist the "
                       "executables) before serving")
    warm_p.add_argument("--manifest", "-m", required=True,
                        help="warmup manifest JSON "
                             "(recorded via QueryService.record_warmup)")
    warm_p.add_argument("--catalog", "-c", default=None,
                        help="catalog directory for query entries "
                             "(kernel entries replay without one)")
    warm_p.add_argument("--check", action="store_true",
                        help="after replaying, prove a second pass "
                             "compiles NOTHING; exit nonzero if serving "
                             "would still compile anything")
    warm_p.add_argument("--mesh", default="auto", metavar="auto|N|off",
                        help="replay query entries through the sharded "
                             "serving route (docs/SERVING.md): the mesh "
                             "the serving process will use, so the "
                             "mesh-keyed AOT executables (kernel, "
                             "bucket, dtype, mesh_shape) are the ones "
                             "warmed. auto (default) matches `gmtpu "
                             "serve`")
    warm_p.set_defaults(func=_warmup)

    bserve_p = sub.add_parser(
        "bench-serve", help="serving load generator: open/closed-loop "
                            "workloads, p50/p95/p99 + coalescing report")
    bserve_p.add_argument("--catalog", "-c", default=None,
                          help="existing catalog (default: synthesize a "
                               "throwaway store)")
    bserve_p.add_argument("--feature-name", "-f", default=None,
                          help="feature type (required with --catalog)")
    bserve_p.add_argument("--n", type=int, default=20000,
                          help="synthetic store size (no --catalog)")
    bserve_p.add_argument("--kind", default="knn",
                          choices=["knn", "count"], help="workload kind")
    bserve_p.add_argument("--k", type=int, default=8, help="kNN k")
    bserve_p.add_argument("--mode", default="closed",
                          choices=["closed", "open", "sustained",
                                   "subscribe", "approx", "wire"])
    bserve_p.add_argument("--wire-rows", type=int, default=100_000,
                          help="wire mode: rows per bulk execute "
                               "response (the JSON-vs-columnar encode "
                               "comparison; docs/SERVING.md "
                               "\"Columnar wire\")")
    bserve_p.add_argument("--push-sinks", type=int, default=1000,
                          help="wire mode: fan-out subscriber count "
                               "(one encode per frame, asserted)")
    bserve_p.add_argument("--tolerance", type=float, default=0.1,
                          help="approx mode: tolerant clients' accuracy "
                               "contract (bound <= tolerance * answer)")
    bserve_p.add_argument("--subs", type=int, default=8,
                          help="subscribe mode: standing subscriptions "
                               "(bbox/dwithin geofences + density "
                               "windows, cycling)")
    bserve_p.add_argument("--batches", type=int, default=20,
                          help="subscribe mode: kafka batches folded")
    bserve_p.add_argument("--lanes", action="store_true",
                          help="subscribe mode: vmapped-lane vs "
                               "fused-slot comparison at "
                               "S in {64, 1024, 8192} (docs/SERVING.md "
                               "\"Standing queries\"); the fused leg "
                               "is capped at S<=1024 — its compile "
                               "cost grows super-linearly with S")
    bserve_p.add_argument("--rows", type=int, default=64,
                          help="subscribe mode: rows per kafka batch")
    bserve_p.add_argument("--clients", type=int, default=16,
                          help="closed-loop client count")
    bserve_p.add_argument("--rate", type=float, default=200.0,
                          help="open-loop offered rate (qps)")
    bserve_p.add_argument("--outstanding", type=int, default=32,
                          help="sustained-mode in-flight request cap "
                               "(semaphore-gated closed loop reporting "
                               "pts/s + windows-in-flight)")
    bserve_p.add_argument("--no-pipeline", action="store_true",
                          help="serial dispatch (pipelined is the "
                               "default for kNN windows)")
    bserve_p.add_argument("--ring", action="store_true",
                          help="sustained mode: also run a ring-off "
                               "(pipelined) baseline and report the "
                               "dispatches_per_window ratio — the "
                               "persistent serve loop's headline "
                               "(docs/SERVING.md \"Persistent serve "
                               "loop\"); with --record-baseline the "
                               "ring.dispatch.* sentinel family is "
                               "recorded too")
    bserve_p.add_argument("--no-ring", action="store_true",
                          help="disable the persistent serve loop for "
                               "the measured run (ring programs are "
                               "the default for eligible kNN windows)")
    bserve_p.add_argument("--duration", type=float, default=5.0,
                          help="seconds per measured run")
    bserve_p.add_argument("--max-wait-ms", type=float, default=2.0)
    bserve_p.add_argument("--max-batch", type=int, default=64)
    bserve_p.add_argument("--no-compare", action="store_true",
                          help="skip the serial (coalescing-off) baseline")
    bserve_p.add_argument("--mesh", default="auto", metavar="auto|N|off",
                          help="sharded serving for the measured run "
                               "(docs/SERVING.md): auto = all devices "
                               "when >1; N = first N devices; off = "
                               "single-chip. When a mesh resolves, the "
                               "comparison adds a same-stack single-"
                               "chip run and reports mesh_speedup + "
                               "per-shard pts/s")
    bserve_p.add_argument("--fleet", type=int, default=None,
                          metavar="N",
                          help="serve through an N-replica fleet "
                               "router instead of one service: "
                               "closed-loop clients over the wire, "
                               "one replica killed abruptly at "
                               "half-time, report p99-during-kill + "
                               "zero-drop accounting, and compare "
                               "against a single-replica run "
                               "(docs/SERVING.md \"Replica fleets\")")
    bserve_p.add_argument("--no-kill", action="store_true",
                          help="fleet mode: skip the scripted "
                               "replica kill")
    bserve_p.add_argument("--smoke", action="store_true",
                          help="small sizes for CI")
    bserve_p.add_argument("--trace", default=None, metavar="OUT.json",
                          help="trace the measured runs and write a "
                               "Perfetto trace_event JSON here; also "
                               "prints the dispatch-gap report line "
                               "(docs/OBSERVABILITY.md)")
    bserve_p.add_argument("--record-baseline", default=None,
                          metavar="PATH", nargs="?",
                          const="BASELINE_SERVE.json",
                          help="record the measured run's profile as a "
                               "sentinel baseline (default path "
                               "BASELINE_SERVE.json; docs/"
                               "OBSERVABILITY.md \"Sentinel\")")
    bserve_p.add_argument("--sentinel", default=None, metavar="PATH",
                          nargs="?", const="BASELINE_SERVE.json",
                          help="compare the measured run against a "
                               "sentinel baseline; exit nonzero on a "
                               "regressed verdict")
    bserve_p.add_argument("--sentinel-threshold", type=float,
                          default=None, metavar="RATIO",
                          help="sentinel median-ratio threshold "
                               "(default 1.5)")
    bserve_p.set_defaults(func=_bench_serve)

    prof_p = sub.add_parser(
        "prof", help="continuous serve profile: lifetime per-phase/"
                     "per-kernel/per-shard distributions from a "
                     "--metrics-port endpoint (/debug/prof) or a "
                     "saved profile JSON")
    prof_p.add_argument("--url", default=None,
                        help="endpoint base URL (default: "
                             "http://HOST:PORT from --host/--port)")
    prof_p.add_argument("--host", default="127.0.0.1")
    prof_p.add_argument("--port", type=int, default=9090)
    prof_p.add_argument("--input", "-i", default=None, metavar="JSON",
                        help="read a saved /debug/prof document "
                             "instead of polling a live endpoint")
    prof_p.add_argument("--json", action="store_true",
                        help="machine output instead of text")
    prof_p.set_defaults(func=_prof)

    sentinel_p = sub.add_parser(
        "sentinel", help="perf-regression sentinel: compare a serve "
                         "profile against a committed baseline; typed "
                         "per-metric verdicts (ok/regressed/improved/"
                         "insufficient-data), nonzero exit on "
                         "regression")
    sentinel_p.add_argument("--baseline", "-b", required=True,
                            help="baseline JSON (bench-serve "
                                 "--record-baseline)")
    sentinel_p.add_argument("--input", "-i", default=None,
                            metavar="JSON",
                            help="current profile: a saved /debug/prof "
                                 "document (default: poll --url/"
                                 "--host/--port live)")
    sentinel_p.add_argument("--url", default=None,
                            help="live endpoint base URL")
    sentinel_p.add_argument("--host", default="127.0.0.1")
    sentinel_p.add_argument("--port", type=int, default=9090)
    sentinel_p.add_argument("--threshold", type=float, default=None,
                            help="median-ratio regression threshold "
                                 "(default 1.5)")
    sentinel_p.add_argument("--min-overlap", type=float, default=None,
                            help="distribution-overlap floor below "
                                 "which a shifted median counts "
                                 "(default 0.2)")
    sentinel_p.add_argument("--min-n", type=int, default=None,
                            help="samples required per side before any "
                                 "verdict but insufficient-data "
                                 "(default 8)")
    sentinel_p.add_argument("--strict", action="store_true",
                            help="also exit nonzero on any "
                                 "insufficient-data verdict (a metric "
                                 "that stopped being comparable — "
                                 "renamed phase, lost instrumentation "
                                 "— must not read as green)")
    sentinel_p.add_argument("--json", action="store_true",
                            help="machine output instead of text")
    sentinel_p.set_defaults(func=_sentinel)

    # telemetry surface (docs/OBSERVABILITY.md)
    top_p = sub.add_parser(
        "top", help="live serving dashboard: poll a --metrics-port "
                    "endpoint and render qps/p99/queue/breakers to the "
                    "terminal (no curses; plain text refresh)")
    top_p.add_argument("--url", default=None,
                       help="endpoint base URL (default: "
                            "http://HOST:PORT from --host/--port)")
    top_p.add_argument("--host", default="127.0.0.1")
    top_p.add_argument("--port", type=int, default=9090)
    top_p.add_argument("--interval", type=float, default=2.0,
                       help="poll interval seconds")
    top_p.add_argument("--count", type=int, default=None,
                       help="number of polls, then exit (default: "
                            "until interrupted)")
    top_p.add_argument("--no-clear", action="store_true",
                       help="append frames instead of clearing the "
                            "screen (logs, pipes)")
    top_p.set_defaults(func=_top)

    trace_p = sub.add_parser(
        "trace", help="inspect a trace dump (flight-recorder JSON or "
                      "Perfetto trace_event JSON): per-trace summary, "
                      "or the dispatch-gap report with --gap")
    trace_p.add_argument("--input", "-i", required=True,
                         help="trace file: a flight-recorder dump "
                              "(gmtpu serve --flight-dump, /debug/* "
                              "saved to disk) or Perfetto JSON "
                              "(bench-serve --trace)")
    trace_p.add_argument("--gap", action="store_true",
                         help="print the dispatch-gap report (host-gap "
                              "vs kernel-time attribution)")
    trace_p.add_argument("--json", action="store_true",
                         help="machine output instead of text")
    trace_p.add_argument("--perfetto", default=None, metavar="OUT.json",
                         help="also convert to Perfetto trace_event "
                              "JSON at this path")
    trace_p.set_defaults(func=_trace)

    # fault injection + recovery fabric (docs/ROBUSTNESS.md)
    chaos_p = sub.add_parser(
        "chaos", help="run a serve workload under a fault plan and "
                      "check the recovery invariants (no torn "
                      "manifests, typed errors only, breaker cycles "
                      "visible, deterministic replay)")
    chaos_p.add_argument("--plan", "-p", default=None,
                         help="fault plan JSON (see docs/ROBUSTNESS.md; "
                              "required unless --list-sites)")
    chaos_p.add_argument("--requests", type=int, default=32,
                         help="workload length (mixed count/knn/"
                              "features/Kafka, writers interleaved)")
    chaos_p.add_argument("--seed", type=int, default=None,
                         help="override the plan's RNG seed")
    chaos_p.add_argument("--check", action="store_true",
                         help="exit nonzero unless every invariant "
                              "holds (the acceptance gate)")
    chaos_p.add_argument("--no-replay", action="store_true",
                         help="skip the determinism replay "
                              "(second seeded run + fire-log diff)")
    chaos_p.add_argument("--list-sites", action="store_true",
                         help="print the registered fault-site catalog "
                              "and exit")
    chaos_p.add_argument("--fleet", action="store_true",
                         help="replica-kill certification "
                              "(docs/ROBUSTNESS.md \"Replica "
                              "fleets\"): a 2-replica fleet serves "
                              "through an abrupt replica kill — zero "
                              "un-typed client errors, zero dropped "
                              "or double-delivered requests, every "
                              "deterministic rule fired, replay-exact "
                              "fire log, and a fresh replica refuses "
                              "traffic until warmup --check is green. "
                              "--plan overrides the built-in plan")
    chaos_p.set_defaults(func=_chaos)

    # replica fleet (docs/SERVING.md "Replica fleets"): N QueryService
    # replicas behind a fault-tolerant router
    fleet_p = sub.add_parser(
        "fleet", help="replica fleet: spawn N serve replicas behind a "
                      "fault-tolerant router (shard-affinity + "
                      "least-loaded + SLO-burn-aware routing, "
                      "drain-then-redistribute failover)")
    fleet_p.add_argument("action", nargs="?", default="serve",
                         choices=["serve", "status", "restart"],
                         help="serve = run a fleet; status = print a "
                              "running fleet's membership; restart = "
                              "rolling restart (drain one replica at "
                              "a time, gated on the survivors' SLO "
                              "budget)")
    fleet_p.add_argument("--catalog", "-c", default=None,
                         help="catalog directory (serve)")
    fleet_p.add_argument("--replicas", "-n", type=int, default=2,
                         help="replica count (serve)")
    fleet_p.add_argument("--host", default="127.0.0.1")
    fleet_p.add_argument("--port", type=int, default=0,
                         help="router port (serve: 0 = ephemeral, "
                              "printed on startup; status/restart: "
                              "the running router's port)")
    fleet_p.add_argument("--spawn", default="process",
                         choices=["thread", "process"],
                         help="replica isolation: separate OS "
                              "processes (default; a crash takes one "
                              "replica) or in-process threads (CI/"
                              "smoke)")
    fleet_p.add_argument("--warmup", default=None, metavar="MANIFEST",
                         help="warmup manifest every replica must "
                              "replay GREEN (gmtpu warmup --check "
                              "semantics) before taking traffic")
    fleet_p.add_argument("--metrics-port", type=int, default=None,
                         help="per-replica metrics port; use 0 — "
                              "ephemeral, reported per replica — N "
                              "replicas on one host cannot share a "
                              "fixed port")
    fleet_p.add_argument("--force-cpu", action="store_true",
                         help="pin replica workers to CPU (CI)")
    fleet_p.set_defaults(func=_fleet)

    # analysis subsystem (docs/ANALYSIS.md): gmtpu-lint + runtime guards
    from geomesa_tpu.analysis.linter import add_lint_arguments

    lint_p = sub.add_parser(
        "lint", help="JAX-aware static analysis (rules GT01..GT06 + "
                     "concurrency GT07..GT12)")
    add_lint_arguments(lint_p)
    lint_p.set_defaults(func=_lint)
    guard_p = sub.add_parser(
        "guard", help="run a script under runtime guards "
                      "(recompile counters, transfer guard)")
    guard_p.add_argument("script", help="python script to run")
    guard_p.add_argument("script_args", nargs=argparse.REMAINDER,
                         help="arguments passed to the script")
    guard_p.add_argument("--transfer", default="allow",
                         choices=["allow", "log", "disallow"],
                         help="jax.transfer_guard mode while the script "
                              "runs (default: allow)")
    guard_p.add_argument("--recompile-warn", type=int, default=None,
                         help="warn on stderr when one jitted callable "
                              "recompiles more than N times")
    guard_p.add_argument("--races", action="store_true",
                         help="lockset race harness: track every lock "
                              "the script creates; exit nonzero on "
                              "lock-order inversions or empty-lockset "
                              "accesses (docs/ANALYSIS.md)")
    guard_p.set_defaults(func=_guard)


def _serve(args) -> int:
    from geomesa_tpu.serve.service import ServeConfig, self_check

    if args.self_check:
        return self_check()
    if not args.catalog:
        print("error: serve needs --catalog (or --self-check)",
              file=sys.stderr)
        return 2
    from geomesa_tpu.plan import DataStore
    from geomesa_tpu.serve.protocol import serve_lines

    store = DataStore(args.catalog,
                      use_device_cache=not args.no_device_cache)
    profile = getattr(args, "profile", False)
    config = ServeConfig(
        max_queue=args.max_queue,
        max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        default_timeout_ms=args.timeout_ms,
        tenant_rate=args.tenant_rate,
        degrade=args.degrade,
        warmup_manifest=getattr(args, "warmup", None),
        track_compiles=getattr(args, "track_compiles", False),
        # the profiler folds recorded traces: --profile without
        # --trace would fold nothing, so it implies tracing
        trace=getattr(args, "trace", False) or profile,
        flight_dump=getattr(args, "flight_dump", None),
        subscribe_poll_ms=getattr(args, "live_poll_ms", None),
        subscribe_max=getattr(args, "max_subscriptions", 256),
        mesh=getattr(args, "mesh", "auto"),
        slo=getattr(args, "slo", None),
        profile=profile,
    )
    def write_line(s: str) -> None:
        # flush per response: with stdout piped (the normal programmatic
        # client), block buffering would deadlock a request/response
        # client against the server's blocking read of the next line
        sys.stdout.write(s)
        sys.stdout.flush()

    import threading

    from geomesa_tpu.serve.service import QueryService

    svc = QueryService(store, config)
    server = None
    stop_snap = threading.Event()
    snap_thread = None
    if getattr(args, "metrics_port", None) is not None:
        from geomesa_tpu.telemetry.export import MetricsServer

        server = MetricsServer(
            port=args.metrics_port,
            stats_fn=svc.stats,
            pre_scrape=svc.export_gauges,
            slo_fn=(svc.slo.report if svc.slo is not None else None))
        port = server.start()
        # the BOUND port, not the requested one: --metrics-port 0 asks
        # the OS for an ephemeral port (fleet replicas sharing a host
        # must), and stats()/this line are where it is reported
        svc.metrics_port = port
        print(f"metrics: {server.url}/metrics (also /healthz, "
              f"/debug/traces, /debug/stats, /debug/gap, /debug/slo, "
              f"/debug/prof) — gmtpu top --port {port}",
              file=sys.stderr)
    if getattr(args, "metrics_interval", None):
        from geomesa_tpu.utils.metrics import metrics

        def snapshot_loop():
            # periodic stderr visibility for long-running serves
            # without a scrape endpoint; stops with the serve loop
            while not stop_snap.wait(args.metrics_interval):
                svc.export_gauges()
                print(f"--- metrics snapshot ---\n"
                      f"{metrics.to_prometheus()}", file=sys.stderr)

        snap_thread = threading.Thread(
            target=snapshot_loop, name="gmtpu-metrics-snapshot",
            daemon=True)
        snap_thread.start()
    try:
        if args.input == "-":
            n = serve_lines(store, sys.stdin, write_line, config,
                            service=svc)
        else:
            with open(args.input) as f:
                n = serve_lines(store, f, write_line, config, service=svc)
    finally:
        stop_snap.set()
        if snap_thread is not None:
            snap_thread.join(timeout=5.0)
        if server is not None:
            server.stop()
    print(f"served {n} request(s)", file=sys.stderr)
    if args.metrics:
        from geomesa_tpu.utils.metrics import metrics

        print(metrics.to_prometheus(), file=sys.stderr)
    return 0


def _bench_serve(args) -> int:
    import contextlib
    import tempfile

    import numpy as np

    from geomesa_tpu.plan import DataStore
    from geomesa_tpu.serve.loadgen import (
        count_request_factory, knn_request_factory, run_closed_loop,
        run_open_loop, run_sustained)
    from geomesa_tpu.serve.service import QueryService, ServeConfig

    if args.smoke:
        args.n = min(args.n, 2000)
        args.duration = min(args.duration, 2.0)
        args.clients = min(args.clients, 8)
        args.subs = min(args.subs, 4)
        args.batches = min(args.batches, 6)
        args.rows = min(args.rows, 32)
    if args.smoke and args.mode == "wire":
        args.wire_rows = min(args.wire_rows, 20_000)
        args.push_sinks = min(args.push_sinks, 128)
    if args.mode == "subscribe":
        if args.lanes:
            return _bench_subscribe_lanes(args)
        return _bench_subscribe(args)
    if args.mode == "approx":
        return _bench_approx(args)
    if args.mode == "wire":
        return _bench_wire(args)
    if getattr(args, "fleet", None):
        return _bench_fleet(args)
    with contextlib.ExitStack() as stack:
        if args.catalog:
            if not args.feature_name:
                print("error: --catalog needs --feature-name",
                      file=sys.stderr)
                return 2
            store = DataStore(args.catalog, use_device_cache=True)
            type_name = args.feature_name
        else:
            from geomesa_tpu.core.columnar import FeatureBatch
            from geomesa_tpu.core.sft import SimpleFeatureType

            tmp = stack.enter_context(tempfile.TemporaryDirectory())
            rng = np.random.default_rng(11)
            sft = SimpleFeatureType.from_spec(
                "bench", "name:String,score:Double,dtg:Date,*geom:Point")
            store = DataStore(tmp, use_device_cache=True)
            src = store.create_schema(sft)
            src.write(FeatureBatch.from_pydict(sft, {
                "name": rng.choice(["a", "b", "c"], args.n).tolist(),
                "score": rng.uniform(-10, 10, args.n),
                "dtg": rng.integers(
                    1_590_000_000_000, 1_600_000_000_000, args.n),
                "geom": np.stack([rng.uniform(-170, 170, args.n),
                                  rng.uniform(-80, 80, args.n)], 1),
            }))
            type_name = "bench"
        cql = "BBOX(geom, -180, -90, 180, 90)"
        if args.kind == "knn":
            factory = knn_request_factory(type_name, cql, k=args.k)
        else:
            factory = count_request_factory(type_name, [
                cql, "BBOX(geom, -60, -30, 60, 30)",
                "BBOX(geom, 0, 0, 90, 45)"])
        # warm the jit caches + device cache outside the measured window
        warm = QueryService(store, ServeConfig(max_wait_ms=0.0))
        warm.submit(factory(0)).result(timeout=300)
        warm.close()

        tracing = getattr(args, "trace", None)
        record_baseline = getattr(args, "record_baseline", None)
        sentinel_path = getattr(args, "sentinel", None)
        profiling = record_baseline or sentinel_path
        if tracing or profiling:
            # trace only the measured runs (warmup spans would pollute
            # the gap attribution with deliberate cold-path compiles);
            # the sentinel paths additionally fold them into a fresh
            # profiler window so the baseline is THIS run's
            from geomesa_tpu.telemetry import RECORDER, TRACER

            RECORDER.clear()
            TRACER.enable()
        if profiling:
            from geomesa_tpu.telemetry.prof import PROFILER

            PROFILER.reset()
            PROFILER.enable()

        try:
            store_points = store.get_feature_source(
                type_name).storage.count
        except Exception:
            store_points = args.n if not args.catalog else 0

        pipe = not getattr(args, "no_pipeline", False)

        def run(label: str, config: ServeConfig):
            from geomesa_tpu.serve.loadgen import mesh_dispatch_count

            svc = QueryService(store, config)
            mesh_c0 = mesh_dispatch_count()
            try:
                if args.mode == "closed":
                    rep = run_closed_loop(
                        svc, factory, concurrency=args.clients,
                        duration_s=args.duration)
                elif args.mode == "open":
                    rep = run_open_loop(
                        svc, factory, rate_qps=args.rate,
                        duration_s=args.duration)
                else:
                    rep = run_sustained(
                        svc, factory, duration_s=args.duration,
                        max_outstanding=args.outstanding,
                        points_per_query=store_points)
                if (svc.mesh is not None and not rep.mesh_devices
                        and args.mode in ("closed", "open")
                        and mesh_dispatch_count() > mesh_c0):
                    # closed/open modes: still report the topology —
                    # but only when windows actually took a mesh route
                    # (run_sustained applies the same gate itself)
                    rep.mesh_devices = int(svc.mesh.devices.size)
            finally:
                svc.close(drain=True)
            doc = {"run": label, **rep.to_json()}
            print(json.dumps(doc))
            return rep

        mesh_spec = getattr(args, "mesh", "off")
        ring_on = not getattr(args, "no_ring", False)
        if getattr(args, "ring", False) and not ring_on:
            # --ring measures the ring against a ring-off baseline; a
            # ring-disabled measured run would report a ~1.0 ratio that
            # reads as "no benefit" instead of the conflict it is
            print("error: --ring and --no-ring conflict", file=sys.stderr)
            return 2
        coalesced = run("coalesced", ServeConfig(
            max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
            pipeline=pipe, ring=ring_on, mesh=mesh_spec))
        profile_doc = None
        if profiling:
            # snapshot (and stop) the profiler NOW: the serial/single-
            # chip comparison runs below are deliberately slower and
            # must not fold into the measured profile
            from geomesa_tpu.telemetry.prof import PROFILER

            profile_doc = PROFILER.snapshot(include_samples=True)
            PROFILER.disable()
        if getattr(args, "ring", False) and args.mode == "sustained":
            # the persistent-serve-loop headline (docs/SERVING.md
            # "Persistent serve loop"): identical sustained workload,
            # ring OFF — per-window dispatch count must be strictly
            # higher there. Runs after the profiler snapshot like the
            # serial comparison (the baseline is deliberately slower)
            ringless = run("pipelined_baseline", ServeConfig(
                max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
                pipeline=pipe, ring=False, mesh=mesh_spec))
            doc = {
                "run": "ring_comparison",
                "ring_dispatches_per_window":
                    coalesced.dispatches_per_window,
                "pipelined_dispatches_per_window":
                    ringless.dispatches_per_window,
                "ring_windows": coalesced.ring_windows,
                "ring_fallbacks": coalesced.ring_fallbacks,
            }
            if ringless.dispatches_per_window > 0:
                doc["dispatch_ratio"] = round(
                    coalesced.dispatches_per_window
                    / ringless.dispatches_per_window, 3)
            print(json.dumps(doc))
        if not args.no_compare:
            single = None
            if coalesced.mesh_devices > 1:
                # the mesh multiplier the ROADMAP item-1 claim is
                # judged on: same serve stack (coalescing + pipeline),
                # mesh off — sharded-vs-single-chip on the same store
                single = run("single_chip", ServeConfig(
                    max_batch=args.max_batch,
                    max_wait_ms=args.max_wait_ms, pipeline=pipe,
                    mesh="off"))
            # the serial baseline drops BOTH levers (coalescing and the
            # pipeline) — and the mesh, when one was measured — so the
            # comparison is serve-stack vs bare serial single-chip
            serial = run("serial", ServeConfig(
                max_batch=1, max_wait_ms=0.0, pipeline=False,
                mesh="off" if coalesced.mesh_devices > 1 else None))
            if serial.throughput_qps > 0:
                doc = {
                    "run": "comparison",
                    "throughput_speedup": round(
                        coalesced.throughput_qps / serial.throughput_qps,
                        3),
                    "p99_ratio": round(
                        coalesced.p99_ms / serial.p99_ms, 3)
                    if serial.p99_ms else None,
                }
                if args.mode == "sustained":
                    doc["sustained_pts_per_s"] = round(
                        coalesced.pts_per_s, 1)
                    doc["windows_in_flight_max"] = \
                        coalesced.windows_in_flight_max
                if coalesced.mesh_devices:
                    doc["mesh_devices"] = coalesced.mesh_devices
                    if coalesced.per_shard_pts_per_s:
                        # sustained mode only — closed/open report
                        # topology but have no pts/s to normalize, and
                        # a 0.0 here would read as a measured headline
                        doc["per_shard_pts_per_s"] = round(
                            coalesced.per_shard_pts_per_s, 1)
                if single is not None:
                    base = (single.pts_per_s
                            if args.mode == "sustained"
                            else single.throughput_qps)
                    over = (coalesced.pts_per_s
                            if args.mode == "sustained"
                            else coalesced.throughput_qps)
                    if base > 0:
                        doc["mesh_speedup"] = round(over / base, 3)
                print(json.dumps(doc))
        if tracing:
            # BENCH r06+ carries the dispatch-gap attribution: one JSON
            # line next to the throughput lines, plus a Perfetto file
            # for the flame view (ui.perfetto.dev)
            from geomesa_tpu.telemetry import (
                RECORDER, TRACER, gap_report, to_perfetto)

            TRACER.disable()
            traces = RECORDER.traces()
            with open(tracing, "w") as f:
                json.dump(to_perfetto(traces), f)
            rec = RECORDER.stats()
            print(json.dumps({
                "run": "gap", "perfetto": tracing,
                "traces_recorded": rec["trace_count"],
                **gap_report(traces)}))
        if profiling:
            from geomesa_tpu.telemetry import TRACER
            from geomesa_tpu.telemetry import sentinel as snt

            if not tracing:
                TRACER.disable()
            extra_samples = {}
            if coalesced.dispatches_per_window > 0:
                # ring.dispatch.*: the per-window dispatch count is a
                # deterministic structural constant, replicated to the
                # run's window count so the sentinel's min_n gate
                # applies — a ring regression (e.g. silently falling
                # back to the pipelined 4-op shape) moves the whole
                # vector and fails the median-ratio comparison
                wins = max(int(coalesced.pipelined_windows
                               or coalesced.dispatches or 1), 1)
                extra_samples["ring.dispatch.per_window"] = (
                    [coalesced.dispatches_per_window] * min(wins, 64))
            doc = snt.baseline_from_profile(
                profile_doc, latency_samples_ms=coalesced.samples_ms,
                extra_samples=extra_samples,
                extra={"mode": args.mode, "n": args.n,
                       "kind": args.kind,
                       "ring_windows": coalesced.ring_windows,
                       "throughput_qps": round(
                           coalesced.throughput_qps, 2)})
            if record_baseline:
                path = snt.save_baseline(record_baseline, doc)
                print(json.dumps({"run": "baseline", "path": path,
                                  "metrics": len(doc["metrics"])}))
            if sentinel_path:
                baseline = snt.load_baseline(sentinel_path)
                kw = {}
                if getattr(args, "sentinel_threshold", None):
                    kw["threshold"] = args.sentinel_threshold
                report = snt.compare(baseline, doc, **kw)
                print(json.dumps({"run": "sentinel",
                                  "baseline": sentinel_path,
                                  **report}))
                print(snt.render_verdicts(report), file=sys.stderr)
                return snt.exit_code(report)
    return 0


def _bench_fleet(args) -> int:
    """`gmtpu bench-serve --fleet N`: fleet-through-a-kill throughput
    + p99, compared against a single replica (no kill). The headline
    acceptance: the fleet keeps serving through the kill with p99
    bounded and ZERO dropped requests."""
    import contextlib
    import tempfile

    import numpy as np

    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan import DataStore
    from geomesa_tpu.serve.loadgen import run_fleet_bench

    with contextlib.ExitStack() as stack:
        if args.catalog:
            if not args.feature_name:
                print("error: --catalog needs --feature-name",
                      file=sys.stderr)
                return 2
            catalog, type_name = args.catalog, args.feature_name
        else:
            catalog = stack.enter_context(tempfile.TemporaryDirectory())
            rng = np.random.default_rng(11)
            sft = SimpleFeatureType.from_spec(
                "bench", "name:String,score:Double,dtg:Date,*geom:Point")
            store = DataStore(catalog, use_device_cache=True)
            store.create_schema(sft).write(FeatureBatch.from_pydict(sft, {
                "name": rng.choice(["a", "b", "c"], args.n).tolist(),
                "score": rng.uniform(-10, 10, args.n),
                "dtg": rng.integers(
                    1_590_000_000_000, 1_600_000_000_000, args.n),
                "geom": np.stack([rng.uniform(-170, 170, args.n),
                                  rng.uniform(-80, 80, args.n)], 1),
            }))
            del store
            type_name = "bench"
        kill = not getattr(args, "no_kill", False)
        fleet = run_fleet_bench(
            catalog, type_name, n_replicas=args.fleet,
            duration_s=args.duration, clients=args.clients, k=args.k,
            kill=kill)
        print(json.dumps({"run": "fleet", **fleet}))
        single = None
        if not args.no_compare and args.fleet > 1:
            single = run_fleet_bench(
                catalog, type_name, n_replicas=1,
                duration_s=args.duration, clients=args.clients,
                k=args.k, kill=False)
            print(json.dumps({"run": "single_replica", **single}))
        comparison = {
            "run": "comparison",
            "dropped": fleet["dropped"],
            "untyped": fleet["untyped"],
            "served_through_kill": fleet.get("served_during_kill", 0),
            "p99_during_kill_ms": fleet.get("p99_during_kill_ms"),
        }
        if single is not None and single["throughput_qps"] > 0:
            comparison["fleet_speedup"] = round(
                fleet["throughput_qps"] / single["throughput_qps"], 3)
        print(json.dumps(comparison))
        # the acceptance contract, machine-checkable: zero drops, zero
        # un-typed errors, and — when a kill happened — the fleet
        # demonstrably served inside the kill window
        ok = (fleet["dropped"] == 0 and fleet["untyped"] == 0
              and (not fleet["killed"]
                   or fleet.get("served_during_kill", 0) > 0))
        return 0 if ok else 1


def _bench_subscribe(args) -> int:
    """`gmtpu bench-serve --mode subscribe`: N standing subscriptions
    folded over M synthetic kafka batches; reports events/s and the
    per-batch eval+push latency distribution (p50/p95/p99)."""
    import numpy as np

    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.kafka.store import KafkaDataStore
    from geomesa_tpu.serve.loadgen import run_subscribe

    sft = SimpleFeatureType.from_spec(
        "bench_live", "name:String,score:Double,dtg:Date,*geom:Point")
    store = KafkaDataStore()
    store.create_schema(sft)
    n = args.rows

    def make_batch(i: int) -> FeatureBatch:
        # moving fleet: the same fid population drifts each batch, so
        # geofence enter/exit churn is steady instead of append-only
        rng = np.random.default_rng(997 * i + 13)
        return FeatureBatch.from_pydict(sft, {
            "name": rng.choice(["a", "b", "c"], n).tolist(),
            "score": rng.uniform(-10, 10, n),
            "dtg": rng.integers(
                1_590_000_000_000, 1_600_000_000_000, n),
            "geom": np.stack([rng.uniform(-60, 60, n),
                              rng.uniform(-30, 30, n)], 1),
        }, fids=[f"v{j}" for j in range(n)])

    # seed the live layer; run_subscribe does its own warm fold (the
    # fused-kernel AOT key is per evaluator+version, so only THIS
    # manager's warm fold keeps the compile out of the measured window)
    store.write("bench_live", make_batch(10_001))
    rep = run_subscribe(store, "bench_live", make_batch,
                        subscriptions=args.subs, batches=args.batches)
    print(json.dumps({"run": "subscribe", **rep.to_json()}))
    return 0


def _bench_subscribe_lanes(args) -> int:
    """`gmtpu bench-serve --mode subscribe --lanes`: the vmapped-lane
    vs fused-slot comparison (docs/SERVING.md "Standing queries") at
    S in {64, 1024, 8192} same-class bbox geofences. Each leg runs the
    identical protocol — register-before-seed, first poll, steady
    polls, one membership-churn event — on a fresh store, so events
    match across modes and `speedup` is a wall-clock ratio. The fused
    leg is capped at S<=1024: its trace+compile grows super-linearly
    with S (~1 s at S=64, ~120 s at S=1024 on CPU CI), so beyond the
    cap the sweep reports the lane leg only rather than extrapolating.
    The verdict gates on the >=10x events/s floor at S=1024 and on
    lane dispatches-per-poll staying S-independent (<=4 for one
    geofence class)."""
    import numpy as np

    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.kafka.store import KafkaDataStore
    from geomesa_tpu.serve.loadgen import run_subscribe_lanes

    sft = SimpleFeatureType.from_spec(
        "bench_live", "name:String,score:Double,dtg:Date,*geom:Point")
    n = args.rows

    def make_store():
        store = KafkaDataStore()
        store.create_schema(sft)
        return store

    def make_batch(i: int) -> FeatureBatch:
        rng = np.random.default_rng(997 * i + 13)
        return FeatureBatch.from_pydict(sft, {
            "name": rng.choice(["a", "b", "c"], n).tolist(),
            "score": rng.uniform(-10, 10, n),
            "dtg": rng.integers(
                1_590_000_000_000, 1_600_000_000_000, n),
            "geom": np.stack([rng.uniform(-60, 60, n),
                              rng.uniform(-30, 30, n)], 1),
        }, fids=[f"v{j}" for j in range(n)])

    fused_cap = 1024
    reports = {}
    for s in (64, 1024, 8192):
        rep = run_subscribe_lanes(
            make_store, "bench_live", make_batch, subscriptions=s,
            batches=4, fused=s <= fused_cap)
        reports[s] = rep
        print(json.dumps(rep), flush=True)
    at_1024 = reports[1024]
    verdict = {
        "run": "lanes_verdict",
        "speedup_at_1024": at_1024.get("speedup"),
        "lane_dispatches_per_poll_at_8192":
            reports[8192]["lanes"]["dispatches_per_poll"],
        "floor": 10.0,
    }
    verdict["ok"] = bool(
        (at_1024.get("speedup") or 0.0) >= verdict["floor"]
        and verdict["lane_dispatches_per_poll_at_8192"] <= 4.0)
    print(json.dumps(verdict))
    return 0 if verdict["ok"] else 1


def _bench_wire(args) -> int:
    """`gmtpu bench-serve --mode wire`: the JSON-lines vs columnar
    record-batch comparison over one bulk execute result (rows/s,
    bytes/s, encode p50/p99) plus the PushMux fan-out (events/s at
    --push-sinks subscribers, one encode per frame asserted). The
    verdict gates on decoded-parity, the >=5x rows/s acceptance
    floor, and the one-encode invariant; with --record-baseline /
    --sentinel the wire.encode.* sample families ride the sentinel so
    a slowed encoder fails CI like any other hot-path regression."""
    import contextlib
    import tempfile

    import numpy as np

    from geomesa_tpu.plan import DataStore
    from geomesa_tpu.serve import columnar as colwire
    from geomesa_tpu.serve.loadgen import run_wire

    if not colwire.have_pyarrow():
        # typed skip, mirroring the wire smoke: a json-only host has
        # nothing to compare — not a failure
        print(json.dumps({"run": "wire", "skipped": True,
                          "reason": "pyarrow_unavailable"}))
        return 0
    with contextlib.ExitStack() as stack:
        if args.catalog:
            if not args.feature_name:
                print("error: --catalog needs --feature-name",
                      file=sys.stderr)
                return 2
            store = DataStore(args.catalog, use_device_cache=True)
            type_name = args.feature_name
        else:
            from geomesa_tpu.core.columnar import FeatureBatch
            from geomesa_tpu.core.sft import SimpleFeatureType

            tmp = stack.enter_context(tempfile.TemporaryDirectory())
            n = max(args.n, args.wire_rows)
            rng = np.random.default_rng(11)
            sft = SimpleFeatureType.from_spec(
                "bench", "name:String,score:Double,dtg:Date,*geom:Point")
            store = DataStore(tmp, use_device_cache=True)
            src = store.create_schema(sft)
            src.write(FeatureBatch.from_pydict(sft, {
                "name": rng.choice(["a", "b", "c"], n).tolist(),
                "score": rng.uniform(-10, 10, n),
                "dtg": rng.integers(
                    1_590_000_000_000, 1_600_000_000_000, n),
                "geom": np.stack([rng.uniform(-170, 170, n),
                                  rng.uniform(-80, 80, n)], 1),
            }))
            type_name = "bench"
        rep = run_wire(store, type_name, rows=args.wire_rows,
                       push_sinks=args.push_sinks)
        print(json.dumps({"run": "wire", **rep.to_json()}))
        ok = (rep.wire_parity_ok
              and rep.wire_speedup >= 5.0
              and rep.push_encodes == rep.push_frames)
        print(json.dumps({
            "run": "wire_verdict", "ok": ok,
            "speedup": round(rep.wire_speedup, 1),
            "parity": rep.wire_parity_ok,
            "one_encode": rep.push_encodes == rep.push_frames,
            "push_events_per_s": round(rep.push_events_per_s)}))
        record_baseline = getattr(args, "record_baseline", None)
        sentinel_path = getattr(args, "sentinel", None)
        if record_baseline or sentinel_path:
            from geomesa_tpu.telemetry import sentinel as snt

            doc = snt.baseline_from_profile(
                {},
                extra_samples={
                    "wire.encode.json": rep.wire_json_samples_ms,
                    "wire.encode.columnar": rep.wire_columnar_samples_ms,
                    "wire.push.publish": rep.push_publish_samples_ms,
                },
                extra={"mode": "wire", "rows": rep.wire_rows,
                       "push_sinks": rep.push_sinks,
                       "speedup": round(rep.wire_speedup, 2)})
            if record_baseline:
                path = snt.save_baseline(record_baseline, doc)
                print(json.dumps({"run": "baseline", "path": path,
                                  "metrics": len(doc["metrics"])}))
            if sentinel_path:
                baseline = snt.load_baseline(sentinel_path)
                kw = {}
                if getattr(args, "sentinel_threshold", None):
                    kw["threshold"] = args.sentinel_threshold
                report = snt.compare(baseline, doc, **kw)
                print(json.dumps({"run": "sentinel",
                                  "baseline": sentinel_path, **report}))
                print(snt.render_verdicts(report), file=sys.stderr)
                return max(snt.exit_code(report), 0 if ok else 1)
        return 0 if ok else 1


def _bench_approx(args) -> int:
    """`gmtpu bench-serve --mode approx`: tolerant vs exact count
    clients over one synthetic (or supplied) store — the sketch-tier
    speedup headline, tier shares, zero bound violations, and the
    result-cache second-pass hit. The measured run disables the result
    cache so `exact_p50` is the honest device-scan number; a short
    second pass with the cache on reports the repeated-dashboard-query
    hit rate."""
    import contextlib
    import tempfile

    import numpy as np

    from geomesa_tpu.plan import DataStore
    from geomesa_tpu.serve.loadgen import run_approx
    from geomesa_tpu.serve.service import QueryService, ServeConfig

    with contextlib.ExitStack() as stack:
        if args.catalog:
            if not args.feature_name:
                print("error: --catalog needs --feature-name",
                      file=sys.stderr)
                return 2
            store = DataStore(args.catalog, use_device_cache=True)
            type_name = args.feature_name
        else:
            from geomesa_tpu.core.columnar import FeatureBatch
            from geomesa_tpu.core.sft import SimpleFeatureType

            tmp = stack.enter_context(tempfile.TemporaryDirectory())
            rng = np.random.default_rng(11)
            sft = SimpleFeatureType.from_spec(
                "bench", "name:String,score:Double,dtg:Date,*geom:Point")
            store = DataStore(tmp, use_device_cache=True)
            src = store.create_schema(sft)
            src.write(FeatureBatch.from_pydict(sft, {
                "name": rng.choice(["a", "b", "c"], args.n).tolist(),
                "score": rng.uniform(-10, 10, args.n),
                "dtg": rng.integers(
                    1_590_000_000_000, 1_600_000_000_000, args.n),
                "geom": np.stack([rng.uniform(-170, 170, args.n),
                                  rng.uniform(-80, 80, args.n)], 1),
            }))
            type_name = sft.name
        cqls = ["BBOX(geom, -180, -90, 180, 90)",
                "BBOX(geom, -60, -30, 60, 30)",
                "BBOX(geom, 0, 0, 90, 45)"]
        planner = store.get_feature_source(type_name).planner
        from geomesa_tpu.plan.query import Query

        # exact oracle + warm (compiles, sketches, device cache) stay
        # outside the measured window
        exact_counts = {c: int(planner.count(Query(type_name, c)))
                        for c in cqls}
        record_baseline = getattr(args, "record_baseline", None)
        sentinel_path = getattr(args, "sentinel", None)
        profiling = record_baseline or sentinel_path
        if profiling:
            from geomesa_tpu.telemetry import RECORDER, TRACER
            from geomesa_tpu.telemetry.prof import PROFILER

            RECORDER.clear()
            TRACER.enable()
            PROFILER.reset()
            PROFILER.enable()
        svc = QueryService(store, ServeConfig(
            max_wait_ms=args.max_wait_ms, result_cache=0))
        try:
            rep = run_approx(
                svc, type_name, cqls, duration_s=args.duration,
                clients=args.clients, tolerance=args.tolerance,
                exact_counts=exact_counts)
        finally:
            svc.close(drain=True)
        print(json.dumps({"run": "approx", **rep.to_json()}))
        # second pass, cache ON: repeated exact queries must hit
        svc2 = QueryService(store, ServeConfig(max_wait_ms=0.0))
        try:
            for c in cqls:
                svc2.count(type_name, c).result(timeout=300)
            for c in cqls:
                svc2.count(type_name, c).result(timeout=300)
            cache = svc2.stats().get("cache", {})
        finally:
            svc2.close(drain=True)
        print(json.dumps({"run": "approx_cache_pass",
                          "hits": cache.get("hits", 0),
                          "misses": cache.get("misses", 0)}))
        ok = (rep.bound_violations == 0 and rep.tier_sketch > 0
              and cache.get("hits", 0) >= len(cqls))
        print(json.dumps({
            "run": "approx_verdict", "ok": ok,
            "speedup_p50": round(rep.approx_speedup_p50, 1),
            "bound_violations": rep.bound_violations,
            "tiers": {"sketch": rep.tier_sketch,
                      "cached": rep.tier_cached,
                      "exact": rep.tier_exact}}))
        if profiling:
            from geomesa_tpu.telemetry import TRACER
            from geomesa_tpu.telemetry import sentinel as snt
            from geomesa_tpu.telemetry.prof import PROFILER

            profile_doc = PROFILER.snapshot(include_samples=True)
            PROFILER.disable()
            TRACER.disable()
            doc = snt.baseline_from_profile(
                profile_doc, latency_samples_ms=rep.samples_ms,
                extra_samples={
                    "approx.count.sketch": rep.approx_samples_ms,
                    "approx.count.exact": rep.exact_samples_ms,
                },
                extra={"mode": "approx", "n": args.n,
                       "tolerance": args.tolerance,
                       "speedup_p50": round(rep.approx_speedup_p50, 2)})
            if record_baseline:
                path = snt.save_baseline(record_baseline, doc)
                print(json.dumps({"run": "baseline", "path": path,
                                  "metrics": len(doc["metrics"])}))
            if sentinel_path:
                baseline = snt.load_baseline(sentinel_path)
                kw = {}
                if getattr(args, "sentinel_threshold", None):
                    kw["threshold"] = args.sentinel_threshold
                report = snt.compare(baseline, doc, **kw)
                print(json.dumps({"run": "sentinel",
                                  "baseline": sentinel_path, **report}))
                print(snt.render_verdicts(report), file=sys.stderr)
                # the correctness verdict (bound violations / tier
                # shares / cache pass) gates the exit alongside the
                # latency sentinel: a bound-violating build must fail
                # CI even when the distributions look fine
                return max(snt.exit_code(report), 0 if ok else 1)
        return 0 if ok else 1


def _top(args) -> int:
    """Curses-free polling dashboard over a `--metrics-port` endpoint:
    qps (from completed-request deltas between polls), latency
    quantiles, queue depth, degrade level, breaker states, compile
    stalls, quarantine — the docs/OBSERVABILITY.md terminal view."""
    import time as _time
    import urllib.error
    import urllib.request

    base = args.url or f"http://{args.host}:{args.port}"
    base = base.rstrip("/")
    prev = None
    prev_at = None
    polls = 0
    while True:
        try:
            with urllib.request.urlopen(f"{base}/debug/stats",
                                        timeout=5) as r:
                doc = json.loads(r.read().decode())
        except KeyboardInterrupt:
            # ^C lands in the blocking poll as often as in the sleep —
            # both are a clean exit, not a traceback
            return 0
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"gmtpu top: cannot poll {base}/debug/stats: {e}",
                  file=sys.stderr)
            return 1
        now = _time.monotonic()
        frame = _top_frame(doc, prev, now - prev_at if prev_at else None)
        if not args.no_clear:
            sys.stdout.write("\x1b[2J\x1b[H")
        sys.stdout.write(frame + "\n")
        sys.stdout.flush()
        prev, prev_at = doc, now
        polls += 1
        if args.count is not None and polls >= args.count:
            return 0
        try:
            _time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


def _top_frame(doc: dict, prev, dt) -> str:
    m = doc.get("metrics", {})
    hists = m.get("histograms", {})
    counters = m.get("counters", {})
    gauges = m.get("gauges", {})
    lat = hists.get("serve.latency", {})
    done = lat.get("count", 0)
    qps = None
    if prev is not None and dt:
        prev_done = prev.get("metrics", {}).get(
            "histograms", {}).get("serve.latency", {}).get("count", 0)
        qps = max(done - prev_done, 0) / dt
    serve = doc.get("serve", {})
    rec = doc.get("recorder", {})
    lines = [
        "gmtpu top — serve telemetry",
        f"  qps        {qps:10.1f}" if qps is not None
        else "  qps        (first poll)",
        f"  served     {done:10d}   p50 {lat.get('p50_s', 0) * 1e3:8.2f} ms"
        f"   p95 {lat.get('p95_s', 0) * 1e3:8.2f} ms"
        f"   p99 {lat.get('p99_s', 0) * 1e3:8.2f} ms",
        f"  queue      {gauges.get('serve.queue.depth', 0):10.0f}"
        f"   inflight {gauges.get('serve.inflight', 0):.0f}"
        f"   degrade L{serve.get('degrade_level', 0)}",
        f"  dispatches {serve.get('dispatches', 0):10d}"
        f"   coalesced {serve.get('coalesced', 0)}"
        f"   rejected {serve.get('rejected', 0)}"
        f"   failed {serve.get('failed', 0)}",
        f"  compile    stalls {int(counters.get('compile.stalls', 0)):5d}"
        f"   stalled dispatches "
        f"{serve.get('compile_stalled_dispatches', 0)}",
    ]
    breakers = doc.get("breakers", {})
    open_b = {k: v for k, v in sorted(breakers.items()) if v != "closed"}
    lines.append(
        "  breakers   " + (", ".join(f"{k}={v}" for k, v in open_b.items())
                           if open_b else
                           f"all closed ({len(breakers)} deps)"))
    quar = serve.get("quarantine", {})
    lines.append(
        f"  quarantine {quar.get('quarantined', 0)} blocked, "
        f"{quar.get('striking', 0)} striking"
        f"   flightrec {rec.get('traces_held', 0)} trace(s), "
        f"{rec.get('events_held', 0)} event(s)")
    mesh = serve.get("mesh")
    if mesh:
        md = int(counters.get("knn.mesh.dispatches", 0))
        ml = int(counters.get("knn.mesh.local_dispatches", 0))
        lanes = _lane_counts(counters)
        lane_s = ""
        if lanes:
            prev_lanes = _lane_counts(
                (prev or {}).get("metrics", {}).get("counters", {}))
            if prev is not None and dt:
                lane_s = "   lanes " + " ".join(
                    f"{sid}:{max(c - prev_lanes.get(sid, 0), 0) / dt:.1f}/s"
                    for sid, c in sorted(lanes.items()))
            else:
                lane_s = "   lanes " + " ".join(
                    f"{sid}:{int(c)}" for sid, c in sorted(lanes.items()))
        lines.append(
            f"  mesh       shape {tuple(mesh.get('shape', ()))} "
            f"({mesh.get('devices', 0)} dev)"
            f"   windows {md} mesh / {ml} local{lane_s}")
    approx = serve.get("approx")
    if approx:
        tiers = approx.get("tiers", {})
        total = sum(tiers.values())
        cache = serve.get("cache", {})
        shares = ("  ".join(
            f"{k} {v} ({v / total:.0%})" for k, v in tiers.items())
            if total else "no completed requests yet")
        if not approx.get("enabled", True):
            state = "   approx DISABLED (config)"
        elif not approx.get("allowed_now", True):
            state = "   EXACTNESS BUDGET SPENT (serving exact)"
        else:
            state = ""
        lines.append(
            f"  approx     {shares}"
            + (f"   cache {cache.get('hits', 0)}h/"
               f"{cache.get('misses', 0)}m/{cache.get('entries', 0)}e"
               if cache else "")
            + state)
    subs = serve.get("subscriptions")
    if subs:
        by = subs.get("by_status", {})
        lines.append(
            f"  subs       {by.get('active', 0)} active, "
            f"{subs.get('lagged', 0)} lagged, "
            f"{by.get('quarantined', 0)} quarantined "
            f"({subs.get('subscriptions', 0)} total)")
    slo = serve.get("slo")
    if slo and slo.get("enabled"):
        breaching = slo.get("breaching", [])
        budgets = [o.get("budget_remaining", 1.0)
                   for o in slo.get("objectives", {}).values()]
        lines.append(
            f"  slo        {len(slo.get('objectives', {}))} objective(s)"
            f"   min budget {min(budgets) * 100:.1f}%"
            + (f"   BREACHING: {', '.join(breaching)}" if breaching
               else "   all within budget"))
    return "\n".join(lines)


def _lane_counts(counters: dict) -> dict:
    """Per-shard admitted-query counts off the labeled
    `serve.affinity.admitted{shards=...}` counter series (a multi-owner
    window credits each owning shard)."""
    out: dict = {}
    prefix = "serve.affinity.admitted{"
    for key, v in counters.items():
        if not key.startswith(prefix):
            continue
        label = key[len(prefix):-1]
        if label.startswith('shards="') and label.endswith('"'):
            for sid in label[len('shards="'):-1].split(","):
                sid = sid.strip()
                if sid:
                    out[sid] = out.get(sid, 0.0) + v
    return out


def _fetch_json(base: str, path: str):
    import urllib.request

    with urllib.request.urlopen(f"{base.rstrip('/')}{path}",
                                timeout=10) as r:
        return json.loads(r.read().decode())


def _prof(args) -> int:
    """Render a continuous-profiler snapshot (docs/OBSERVABILITY.md
    "Continuous profiling"): from a live /debug/prof endpoint, or from
    a saved snapshot JSON."""
    import urllib.error

    from geomesa_tpu.telemetry.prof import render_prof

    if args.input:
        with open(args.input) as f:
            doc = json.load(f)
    else:
        base = args.url or f"http://{args.host}:{args.port}"
        try:
            doc = _fetch_json(base, "/debug/prof")
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"gmtpu prof: cannot poll {base}/debug/prof: {e}",
                  file=sys.stderr)
            return 1
    if not isinstance(doc, dict) or "phases" not in doc:
        print("error: input is not a /debug/prof document",
              file=sys.stderr)
        return 2
    print(json.dumps(doc) if args.json else render_prof(doc))
    return 0


def _sentinel(args) -> int:
    """Compare a serve profile against a committed baseline; exit
    nonzero on a regressed verdict (docs/OBSERVABILITY.md
    "Sentinel")."""
    import urllib.error

    from geomesa_tpu.telemetry import sentinel as snt

    baseline = snt.load_baseline(args.baseline)
    if args.input:
        with open(args.input) as f:
            profile = json.load(f)
    else:
        base = args.url or f"http://{args.host}:{args.port}"
        try:
            profile = _fetch_json(base, "/debug/prof")
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"gmtpu sentinel: cannot poll {base}/debug/prof: {e}",
                  file=sys.stderr)
            return 1
    if "metrics" in profile and "phases" not in profile:
        current = profile  # already a baseline-shaped metric table
    else:
        current = snt.baseline_from_profile(profile)
    kw = {}
    if args.threshold is not None:
        kw["threshold"] = args.threshold
    if args.min_overlap is not None:
        kw["min_overlap"] = args.min_overlap
    if args.min_n is not None:
        kw["min_n"] = args.min_n
    report = snt.compare(baseline, current, **kw)
    print(json.dumps(report) if args.json
          else snt.render_verdicts(report))
    return snt.exit_code(report, strict=getattr(args, "strict", False))


def _trace(args) -> int:
    """Inspect a trace dump: flight-recorder JSON (`{"traces": ...}`),
    a bare trace list, or Perfetto trace_event JSON round-trips back
    through telemetry.export.from_perfetto."""
    from geomesa_tpu.telemetry import (
        from_perfetto, gap_report, render_gap, to_perfetto)

    with open(args.input) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "traceEvents" in doc:
        traces = from_perfetto(doc)
    elif isinstance(doc, dict) and "traces" in doc:
        traces = doc["traces"]
    elif isinstance(doc, list):
        traces = doc
    else:
        print(f"error: {args.input} is neither a flight-recorder dump "
              "nor a Perfetto trace", file=sys.stderr)
        return 2
    if args.perfetto:
        with open(args.perfetto, "w") as f:
            json.dump(to_perfetto(traces), f)
        print(f"wrote {args.perfetto}", file=sys.stderr)
    if args.gap:
        rep = gap_report(traces)
        print(json.dumps(rep) if args.json else render_gap(rep))
        return 0
    if args.json:
        print(json.dumps(traces))
        return 0
    for t in traces:
        root = t.get("root") or {}
        dur_ms = max(root.get("t1_ns", 0) - root.get("t0_ns", 0), 0) / 1e6
        attrs = dict(root.get("attrs") or ())
        status = attrs.get("status", "?")
        print(f"{t.get('trace_id', '?'):<16} {t.get('name', ''):<8} "
              f"{dur_ms:10.2f} ms  status={status:<9} "
              f"spans={len(t.get('spans', ()))} "
              f"kind={attrs.get('kind', '')}")
    print(f"{len(traces)} trace(s)", file=sys.stderr)
    return 0


def _warmup(args) -> int:
    from geomesa_tpu.compilecache import warmup as _w
    from geomesa_tpu.compilecache.manifest import WarmupManifest
    from geomesa_tpu.compilecache.persist import enable_persistent_cache

    enable_persistent_cache()
    manifest = WarmupManifest.load(args.manifest)
    store = None
    if args.catalog:
        from geomesa_tpu.plan import DataStore

        store = DataStore(args.catalog, use_device_cache=True)
        from geomesa_tpu.parallel.mesh import serve_mesh

        mesh = serve_mesh(getattr(args, "mesh", "auto"))
        if mesh is not None:
            # warm the route serving will take: query entries replay
            # through the mesh dispatch seam, registering + AOT-
            # compiling the mesh-keyed executables (docs/SERVING.md
            # "Sharded serving")
            store.set_mesh(mesh)
    run = _w.check if args.check else _w.replay
    report = run(manifest, store=store)
    for msg in report.errors:
        print(f"warmup: {msg}", file=sys.stderr)
    print(json.dumps(report.to_json()))
    if args.check and report.queries_skipped:
        # skipped entries mean the check proved nothing about them: a
        # green exit here would read as "serving compiles nothing" when
        # the query paths were never replayed
        print("warmup --check: query entries present but no --catalog "
              "given; cannot verify the serving path", file=sys.stderr)
        return 1
    return 0 if report.ok else 1


def _chaos(args) -> int:
    from geomesa_tpu.faults.chaos import run_cli

    if (not args.list_sites and not args.plan
            and not getattr(args, "fleet", False)):
        print("error: chaos needs --plan (or --fleet / --list-sites)",
              file=sys.stderr)
        return 2
    return run_cli(args)


def _fleet(args) -> int:
    import time

    if args.action in ("status", "restart"):
        if not args.port:
            print("error: fleet status/restart needs --port "
                  "(the running router's port)", file=sys.stderr)
            return 2
        from geomesa_tpu.fleet import FleetClient

        cli = FleetClient(args.host, args.port)
        try:
            if args.action == "status":
                doc = cli.request({"op": "fleet"})
                print(json.dumps(doc, indent=1))
                return 0 if doc.get("ok") else 1
            cli.hello(role="admin")
            # rolling restart can legitimately take minutes: each
            # replica drains, respawns, and re-proves its warmup gate
            doc = cli.request({"op": "restart"}, timeout_s=1800.0)
            print(json.dumps(doc, indent=1))
            return 0 if doc.get("ok") else 1
        finally:
            cli.close()
    if not args.catalog:
        print("error: fleet serve needs --catalog", file=sys.stderr)
        return 2
    from geomesa_tpu.fleet import FleetConfig, FleetSupervisor

    sup = FleetSupervisor(FleetConfig(
        n_replicas=args.replicas, catalog=args.catalog,
        spawn=args.spawn, host=args.host, router_port=args.port,
        warmup_manifest=args.warmup,
        metrics_port=args.metrics_port,
        force_cpu_workers=getattr(args, "force_cpu", False)))
    try:
        port = sup.start()
        print(json.dumps({"event": "fleet_ready", "host": args.host,
                          "port": port, "replicas": args.replicas,
                          "spawn": args.spawn}), flush=True)
        print(f"fleet: {args.replicas} replica(s) behind "
              f"{args.host}:{port} — gmtpu fleet status --port {port}",
              file=sys.stderr)
        while True:
            time.sleep(1.0)
            states = [h.state for h in sup.membership.all()]
            if all(s == "dead" for s in states):
                print("fleet: every replica dead; exiting",
                      file=sys.stderr)
                return 1
    except KeyboardInterrupt:
        print("fleet: draining...", file=sys.stderr)
        return 0
    finally:
        sup.close()


def _lint(args) -> int:
    from geomesa_tpu.analysis.linter import run_cli

    return run_cli(args)


def _guard(args) -> int:
    from geomesa_tpu.analysis.runtime import run_guarded

    def storm(name, count):
        print(f"gmtpu guard: retrace storm: {name} recompiled "
              f"{count} times", file=sys.stderr)

    report, status = run_guarded(
        args.script, argv=list(args.script_args),
        transfer=args.transfer, warn_after=args.recompile_warn,
        on_storm=storm, races=getattr(args, "races", False))
    locksets = report.pop("locksets", None)
    tracked = {k: v for k, v in report.items() if v.get("calls")}
    print("gmtpu guard report:", file=sys.stderr)
    if not tracked:
        print("  (no tracked engine jit calls)", file=sys.stderr)
    for name, rec in sorted(tracked.items()):
        print(f"  {name}: calls={rec['calls']} "
              f"recompiles={rec['recompiles']}", file=sys.stderr)
    if locksets is not None:
        print(f"  locksets: {locksets['locks_created']} lock(s) tracked, "
              f"{locksets['order_edges']} order edge(s), "
              f"{len(locksets['inversions'])} inversion(s), "
              f"{len(locksets['races'])} race(s)", file=sys.stderr)
        for inv in locksets["inversions"]:
            print(f"    INVERSION {inv['first']} vs {inv['second']}",
                  file=sys.stderr)
        for race in locksets["races"]:
            print(f"    RACE key={race['key']} "
                  f"threads={race['threads']} writes={race['writes']}",
                  file=sys.stderr)
        if locksets["violations"] and status == 0:
            # the harness's whole point: a racy-but-green script must
            # not exit 0 under --races
            status = 1
    return status


def _version(args) -> int:
    import geomesa_tpu

    print(geomesa_tpu.__version__)
    return 0


def _store(args):
    from geomesa_tpu.plan import DataStore

    return DataStore(args.catalog)


def _create_schema(args) -> int:
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.store.partition import scheme_from_config

    sft = SimpleFeatureType.from_spec(args.feature_name, args.spec)
    scheme = (
        scheme_from_config(json.loads(args.partition_scheme))
        if args.partition_scheme
        else None
    )
    _store(args).create_schema(sft, scheme, encoding=args.encoding)
    print(f"created schema {args.feature_name}")
    return 0


def _get_type_names(args) -> int:
    for n in _store(args).get_type_names():
        print(n)
    return 0


def _describe_schema(args) -> int:
    sft = _store(args).get_schema(args.feature_name)
    print(f"{sft.name}:")
    for a in sft.attributes:
        marks = []
        if a.default_geom:
            marks.append("*default geometry")
        if a.options:
            marks.append(",".join(f"{k}={v}" for k, v in a.options.items()))
        print(f"  {a.name:<24}{a.type:<16}{' '.join(marks)}")
    if sft.user_data:
        print("user data:")
        for k, v in sft.user_data.items():
            print(f"  {k}={v}")
    return 0


def _remove_schema(args) -> int:
    _store(args).remove_schema(args.feature_name)
    print(f"removed schema {args.feature_name}")
    return 0


def _delete_features(args) -> int:
    src = _store(args).get_feature_source(args.feature_name)
    n = src.delete_features(args.cql)
    print(f"deleted {n} features from {args.feature_name}")
    return 0


def _age_off(args) -> int:
    import datetime as _dt

    try:
        dt = _dt.datetime.fromisoformat(
            args.older_than.replace("Z", "+00:00"))
    except ValueError:
        print(f"error: --older-than {args.older_than!r} is not a valid "
              "ISO-8601 instant", file=sys.stderr)
        return 2
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    cutoff = int(dt.timestamp() * 1000)
    src = _store(args).get_feature_source(args.feature_name)
    n = src.age_off(cutoff)
    print(f"aged off {n} features from {args.feature_name}")
    return 0


def _ingest(args) -> int:
    from geomesa_tpu.convert import converter_from_config, schemas

    ds = _store(args)
    arrow_files = [p for p in args.files
                   if getattr(args, "arrow", False)
                   or p.endswith((".arrow", ".ipc"))]
    if arrow_files:
        # columnar bulk ingest: record batches go straight into the
        # store as NumPy views via DataStore.write_batch — no
        # converter, no per-feature Python dicts (docs/SERVING.md
        # "Columnar wire")
        if set(arrow_files) != set(args.files):
            print("error: cannot mix Arrow IPC and converter inputs "
                  "in one ingest", file=sys.stderr)
            return 2
        if args.feature_name not in ds.get_type_names():
            # the IPC stream embeds the SFT spec in its schema
            # metadata (arrow_io.arrow_schema) — create the schema
            # from it, or refuse TYPED instead of a raw traceback
            import pyarrow as pa

            from geomesa_tpu.core.sft import SimpleFeatureType

            with open(arrow_files[0], "rb") as f:
                meta = pa.ipc.open_stream(f).schema.metadata or {}
            spec = meta.get(b"geomesa.sft.spec")
            if spec is None:
                print(f"error: schema {args.feature_name!r} does not "
                      f"exist and {arrow_files[0]} carries no "
                      f"geomesa.sft.spec metadata — run create-schema "
                      f"first", file=sys.stderr)
                return 2
            ds.create_schema(SimpleFeatureType.from_spec(
                args.feature_name, spec.decode()))
        total = batches = 0
        for path in arrow_files:
            with open(path, "rb") as f:
                rows, nb = ds.write_batch(args.feature_name, f.read())
            total += rows
            batches += nb
        print(f"ingested {total} features ({batches} record batches, "
              f"columnar) into {args.feature_name}")
        return 0
    if not args.converter:
        print("error: --converter is required for non-Arrow inputs",
              file=sys.stderr)
        return 2
    if args.converter in schemas.WELL_KNOWN:
        sft, config = schemas.WELL_KNOWN[args.converter]
        sft = type(sft)(args.feature_name, sft.attributes, sft.user_data)
    else:
        with open(args.converter) as f:
            config = json.load(f)
        sft = ds.get_schema(args.feature_name)
    if args.feature_name in ds.get_type_names():
        src = ds.get_feature_source(args.feature_name)
    else:
        src = ds.create_schema(sft)
    if getattr(args, "workers", 1) > 1:
        from geomesa_tpu.jobs import ingest_files

        rep = ingest_files(
            src,
            lambda: converter_from_config(src.sft, config),
            args.files,
            workers=args.workers,
            resume=not getattr(args, "no_resume", False),
        )
        print(
            f"ingested {rep.features} features into {args.feature_name} "
            f"({len(rep.files_ok)} files ok, {len(rep.files_failed)} failed, "
            f"{rep.records_failed} records failed, "
            f"{len(rep.skipped)} skipped by checkpoint)"
        )
        for line in rep.files_failed:
            print(f"  FAILED {line}", file=sys.stderr)
        return 1 if rep.files_failed else 0
    conv = converter_from_config(src.sft, config)
    total = failed = 0
    for path in args.files:
        batch = conv.convert(path)
        src.write(batch)
        total += len(batch)
        failed += conv.failed
    print(f"ingested {total} features ({failed} failed) into {args.feature_name}")
    return 0


def _export(args) -> int:
    from geomesa_tpu.plan import Query, QueryHints

    ds = _store(args)
    src = ds.get_feature_source(args.feature_name)
    attrs = args.attributes.split(",") if args.attributes else None
    hints = QueryHints()
    binary = args.format in ("arrow", "bin", "parquet", "orc")
    if args.format == "bin":
        track = args.bin_track or next(
            (a.name for a in src.sft.attributes if not a.is_geometry), None
        )
        if track is None:
            raise ValueError("bin export needs --bin-track (no non-geometry attribute)")
        hints = QueryHints(bin_track=track)
    crs = None
    if getattr(args, "crs", None):
        if str(args.crs).lower() == "utm":
            # auto zone from the query's spatial center (reprojection to
            # the local UTM zone — the common analytic output request)
            from geomesa_tpu.core.crs import utm_zone_srid
            from geomesa_tpu.cql import parse_cql
            from geomesa_tpu.cql.extract import extract_bbox

            g = src.sft.default_geometry
            bbox = extract_bbox(parse_cql(args.cql),
                                g.name if g is not None else "")
            if bbox.is_whole_world:
                raise ValueError(
                    "--crs utm needs a spatial filter (the zone is picked "
                    "from the query bbox center); give an EPSG code instead"
                )
            crs = utm_zone_srid((bbox.xmin + bbox.xmax) / 2,
                                (bbox.ymin + bbox.ymax) / 2)
            print(f"auto UTM zone: EPSG:{crs}", file=sys.stderr)
        else:
            s = str(args.crs).lower().removeprefix("epsg:")
            try:
                crs = int(s)
            except ValueError:
                raise ValueError(
                    f"--crs must be 'utm' or an EPSG code (got {args.crs!r})"
                ) from None
    if crs is not None and crs != 4326:
        # bin results bypass finish_features (raw stored lon/lat), and
        # leaflet plots lat/lng — a projected CRS would silently corrupt both
        if args.format == "bin":
            raise ValueError("--crs is not supported for -F bin "
                             "(BIN encodes stored lon/lat)")
        if args.format == "leaflet":
            raise ValueError("--crs is not supported for -F leaflet "
                             "(leaflet maps plot EPSG:4326 lat/lng)")
    q = Query(args.feature_name, args.cql, attributes=attrs,
              max_features=args.max_features, hints=hints, crs=crs)
    r = src.get_features(q)
    if args.format == "shp":
        if args.output == "-":
            raise ValueError("shp export needs --output (writes .shp/.shx/.dbf)")
        from geomesa_tpu.convert.formats import write_shapefile

        if r.features is None or len(r.features) == 0:
            print("no features matched; nothing written", file=sys.stderr)
            return 0
        write_shapefile(args.output, r.features)
        return 0
    if args.format == "leaflet":
        html = _leaflet_html(r.features, args.feature_name)
        if args.output == "-":
            sys.stdout.write(html)
        else:
            with open(args.output, "w") as f:
                f.write(html)
        return 0
    if args.output == "-":
        out = sys.stdout.buffer if binary else sys.stdout
    else:
        out = open(args.output, "wb" if binary else "w")
    try:
        if args.format == "bin":
            out.write(r.bin_bytes or b"")
        elif args.format in ("arrow", "parquet", "orc"):
            out.write(_arrow_bytes(r.features, src.sft, args.format))
        elif args.format == "gml":
            _write_gml(out, r.features, args.feature_name)
        else:
            _write_text(out, r.features, args.format)
    finally:
        if args.output != "-":
            out.close()
    return 0


def _arrow_bytes(batch, sft, fmt: str) -> bytes:
    """Encode features as Arrow IPC / Parquet / ORC bytes. Zero matches
    still yields a VALID schema-only file (a 0-byte parquet/orc is corrupt
    to every reader), built from an empty batch of the feature type."""
    import io

    import pyarrow as pa

    from geomesa_tpu.core.arrow_io import to_arrow
    from geomesa_tpu.core.columnar import FeatureBatch

    if batch is None or len(batch) == 0:
        print("no features matched; writing schema-only output",
              file=sys.stderr)
        batch = FeatureBatch.from_pydict(
            sft, {a.name: [] for a in sft.attributes}
        )
    rb = to_arrow(batch)
    sink = io.BytesIO()
    if fmt == "arrow":
        with pa.ipc.new_stream(sink, rb.schema) as w:
            w.write_batch(rb)
    else:
        table = pa.Table.from_batches([rb])
        if fmt == "parquet":
            import pyarrow.parquet as papq

            papq.write_table(table, sink)
        else:
            import pyarrow.orc as paorc

            # ORC has no dictionary type: decode dict columns
            cols = [
                c.cast(c.type.value_type)
                if pa.types.is_dictionary(c.type) else c
                for c in (table.column(i).combine_chunks()
                          for i in range(table.num_columns))
            ]
            paorc.write_table(pa.table(cols, names=table.column_names), sink)
    return sink.getvalue()


def _gml_geometry(g) -> str:
    """GML 3.1 markup for a host Geometry (gml:pos/posList are lat lon
    order per the spec's EPSG:4326 axis order)."""

    def pos(ring):
        return " ".join(f"{p[1]} {p[0]}" for p in ring)

    def polygon(rings):
        s = (f"<gml:Polygon><gml:exterior><gml:LinearRing><gml:posList>"
             f"{pos(rings[0])}</gml:posList></gml:LinearRing></gml:exterior>")
        for hole in rings[1:]:
            s += (f"<gml:interior><gml:LinearRing><gml:posList>{pos(hole)}"
                  f"</gml:posList></gml:LinearRing></gml:interior>")
        return s + "</gml:Polygon>"

    k = g.kind
    if k == "Point":
        return (f'<gml:Point srsName="EPSG:4326"><gml:pos>{pos(g.rings[0])}'
                f"</gml:pos></gml:Point>")
    if k == "LineString":
        if not g.rings:
            return "<gml:LineString/>"
        return (f"<gml:LineString><gml:posList>{pos(g.rings[0])}"
                f"</gml:posList></gml:LineString>")
    if k == "Polygon":
        return polygon(g.rings) if g.rings else "<gml:Polygon/>"
    if k not in ("MultiPoint", "MultiLineString", "MultiPolygon"):
        # GeometryCollection / mixed columns have no single GML container
        # here — fail loudly rather than mislabel parts (the WKT export
        # formats handle them)
        raise ValueError(f"cannot encode {k} as GML")
    # Multi*: one member per part (parts = ring count per part); empty
    # parts (e.g. MULTIPOLYGON EMPTY) contribute no members
    members = []
    at = 0
    for count in (g.parts or [1] * len(g.rings)):
        rings = g.rings[at:at + count]
        at += count
        if not rings:
            continue
        if k == "MultiPoint":
            members.append(
                f"<gml:pointMember><gml:Point><gml:pos>{pos(rings[0])}"
                f"</gml:pos></gml:Point></gml:pointMember>")
        elif k == "MultiLineString":
            members.append(
                f"<gml:lineStringMember><gml:LineString><gml:posList>"
                f"{pos(rings[0])}</gml:posList></gml:LineString>"
                f"</gml:lineStringMember>")
        else:
            members.append(
                f"<gml:polygonMember>{polygon(rings)}</gml:polygonMember>")
    return f"<gml:{k}>{''.join(members)}</gml:{k}>"


def _write_gml(out, batch, type_name):
    """GML 3.1 FeatureCollection (the reference's GML export format). Point
    members use gml:pos lat-order per the GML spec's EPSG:4326 axis order."""
    from xml.sax.saxutils import escape, quoteattr

    from geomesa_tpu.core.columnar import DictColumn, GeometryColumn

    out.write(
        '<?xml version="1.0" encoding="UTF-8"?>\n'
        '<gml:FeatureCollection xmlns:gml="http://www.opengis.net/gml" '
        'xmlns:geomesa="http://geomesa.org">\n'
    )
    if batch is not None and len(batch):
        names = batch.sft.attribute_names
        # fail BEFORE writing anything: an unsupported geometry column kind
        # raising mid-stream would leave a truncated invalid document
        for n in names:
            col = batch.columns[n]
            if (isinstance(col, GeometryColumn) and not col.is_point
                    and col.kind not in ("LineString", "Polygon", "MultiPoint",
                                         "MultiLineString", "MultiPolygon")):
                raise ValueError(f"cannot encode {col.kind} as GML")
        fids = batch.fids.decode() if batch.fids is not None else range(len(batch))
        # decode()/materialize once per column — per-row decode is O(N^2)
        cols = {}
        for n in names:
            col = batch.columns[n]
            if isinstance(col, GeometryColumn):
                cols[n] = col
            elif isinstance(col, DictColumn):
                cols[n] = col.decode()
            else:
                cols[n] = col
        for i in range(len(batch)):
            out.write(f'  <gml:featureMember>\n    <geomesa:{type_name} '
                      f"gml:id={quoteattr(str(fids[i]))}>\n")
            for n in names:
                col = cols[n]
                if isinstance(col, GeometryColumn):
                    if col.is_point:
                        gml = (f'<gml:Point srsName="EPSG:4326"><gml:pos>'
                               f"{col.y[i]} {col.x[i]}</gml:pos></gml:Point>")
                    else:
                        gml = _gml_geometry(col.geometry(i))
                    out.write(f"      <geomesa:{n}>{gml}</geomesa:{n}>\n")
                else:
                    out.write(
                        f"      <geomesa:{n}>{escape(str(col[i]))}</geomesa:{n}>\n"
                    )
            out.write(f"    </geomesa:{type_name}>\n  </gml:featureMember>\n")
    out.write("</gml:FeatureCollection>\n")


def _write_text(out, batch, fmt):
    import csv

    import numpy as np

    from geomesa_tpu.core.columnar import DictColumn, GeometryColumn
    from geomesa_tpu.core.wkt import to_wkt

    if batch is None or len(batch) == 0:
        return
    names = batch.sft.attribute_names
    geom_attr = batch.sft.default_geometry

    def geom_wkt(col, i):
        return (
            f"POINT ({col.x[i]} {col.y[i]})"
            if col.is_point
            else to_wkt(col.geometry(i))
        )

    if fmt == "wkt":
        col = batch.columns[geom_attr.name]
        for i in range(len(batch)):
            out.write(geom_wkt(col, i) + "\n")
        return
    # materialize each column once (decode()/asarray are O(N) per call)
    materialized = {}
    for name in names:
        col = batch.columns[name]
        if isinstance(col, GeometryColumn):
            materialized[name] = col
        elif isinstance(col, DictColumn):
            materialized[name] = col.decode()
        else:
            materialized[name] = np.asarray(col)
    rows = []
    for i in range(len(batch)):
        row = {}
        for name in names:
            col = batch.columns[name]
            m = materialized[name]
            if isinstance(col, GeometryColumn):
                row[name] = geom_wkt(m, i)
            elif isinstance(col, DictColumn):
                v = m[i]
                row[name] = "" if v is None else v
            else:
                row[name] = m[i].item()
        rows.append(row)
    if fmt == "json":
        for r in rows:
            out.write(json.dumps(r) + "\n")
    else:
        writer = csv.writer(out, delimiter="\t" if fmt == "tsv" else ",")
        writer.writerow(names)
        for r in rows:
            writer.writerow([r[n] for n in names])


def _leaflet_html(batch, title: str) -> str:
    """Self-contained Leaflet HTML preview (geomesa-tools export -F leaflet
    analog): embedded GeoJSON over CDN Leaflet assets."""
    from geomesa_tpu.core.columnar import GeometryColumn

    features = []
    if batch is not None and len(batch):
        geom = batch.geometry
        fids = batch.fids.decode() if batch.fids is not None else None
        for i in range(len(batch)):
            if isinstance(geom, GeometryColumn) and geom.is_point:
                coords = [float(geom.x[i]), float(geom.y[i])]
                gj = {"type": "Point", "coordinates": coords}
            else:
                from geomesa_tpu.core.wkt import to_geojson

                gj = to_geojson(geom.geometry(i))
            features.append({
                "type": "Feature",
                "id": fids[i] if fids else str(i),
                "geometry": gj,
                "properties": {},
            })
    collection = json.dumps({"type": "FeatureCollection", "features": features})
    return f"""<!DOCTYPE html>
<html><head><title>{title}</title>
<link rel="stylesheet" href="https://unpkg.com/leaflet@1.9.4/dist/leaflet.css"/>
<script src="https://unpkg.com/leaflet@1.9.4/dist/leaflet.js"></script>
<style>#map {{ height: 100vh; }}</style></head>
<body><div id="map"></div><script>
var map = L.map('map').setView([0, 0], 2);
L.tileLayer('https://tile.openstreetmap.org/{{z}}/{{x}}/{{y}}.png',
            {{maxZoom: 19}}).addTo(map);
var data = {collection};
var layer = L.geoJSON(data).addTo(map);
if (data.features.length) map.fitBounds(layer.getBounds());
</script></body></html>
"""


def _manage_partitions(args) -> int:
    storage = _store(args).get_feature_source(args.feature_name).storage
    for name in storage.partitions():
        files = storage.manifest.get(name, [])
        count = sum(f["count"] for f in files)
        print(f"{name}\t{len(files)} file(s)\t{count} feature(s)")
    return 0


def _compact(args) -> int:
    storage = _store(args).get_feature_source(args.feature_name).storage
    removed = storage.compact(args.partition)
    print(f"compacted: {removed} file(s) merged")
    return 0


def _explain(args) -> int:
    src = _store(args).get_feature_source(args.feature_name)
    print(src.explain(args.cql))
    return 0


def _stats_analyze(args) -> int:
    from geomesa_tpu.plan.stats_manager import StatsManager

    src = _store(args).get_feature_source(args.feature_name)
    mgr = StatsManager(src.storage)
    summary = mgr.analyze()
    print(json.dumps(summary, indent=1, default=str))
    return 0


def _stats_bounds(args) -> int:
    from geomesa_tpu.plan import Query, QueryHints

    src = _store(args).get_feature_source(args.feature_name)
    attrs = (
        args.attributes.split(",")
        if args.attributes
        else [
            a.name
            for a in src.sft.attributes
            if not a.is_geometry and a.type not in ("String", "UUID", "Bytes")
        ]
    )
    expr = ";".join(f"MinMax({a})" for a in attrs)
    stats = src.get_features(
        Query(args.feature_name, args.cql, hints=QueryHints(stats_string=expr))
    ).stats
    for a, s in zip(attrs, stats.stats):
        print(f"{a}: {s.result()}")
    return 0


def _stats_count(args) -> int:
    from geomesa_tpu.plan import Query, QueryHints

    src = _store(args).get_feature_source(args.feature_name)
    q = Query(args.feature_name, args.cql,
              hints=QueryHints(exact_count=not args.no_exact))
    print(src.get_count(q))
    return 0


def _stats_histogram(args) -> int:
    import numpy as np

    from geomesa_tpu.plan import Query, QueryHints

    src = _store(args).get_feature_source(args.feature_name)
    attr = src.sft.attribute(args.attribute)
    if attr.is_geometry or attr.type in ("String", "UUID", "Bytes"):
        raise ValueError(
            f"stats-histogram requires a numeric or date attribute; "
            f"{args.attribute!r} is {attr.type} (use stats-top-k for strings)"
        )
    # bounds first, then histogram over them
    mm = src.get_features(
        Query(args.feature_name, args.cql,
              hints=QueryHints(stats_string=f"MinMax({args.attribute})"))
    ).stats.stats[0].result()
    lo, hi = mm
    if lo is None:
        print("no data")
        return 0
    hi = hi if hi > lo else lo + 1
    stats = src.get_features(
        Query(args.feature_name, args.cql,
              hints=QueryHints(
                  stats_string=f"Histogram({args.attribute},{args.bins},{lo},{hi})"
              ))
    ).stats
    counts = stats.stats[0].result()
    width = (hi - lo) / args.bins
    for i, c in enumerate(np.asarray(counts)):
        print(f"[{lo + i * width:.4g}, {lo + (i + 1) * width:.4g}) {int(c)}")
    return 0


def _stats_topk(args) -> int:
    from geomesa_tpu.plan import Query, QueryHints

    src = _store(args).get_feature_source(args.feature_name)
    stats = src.get_features(
        Query(args.feature_name, args.cql,
              hints=QueryHints(stats_string=f"TopK({args.attribute},{args.k})"))
    ).stats
    for value, count in stats.stats[0].result():
        print(f"{value}\t{count}")
    return 0


def _bench(args) -> int:
    """Run bench.py's configs through the CLI (upstream: the tools'
    stats/benchmark-ish commands; here the BASELINE harness itself)."""
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "bench.py"
    )
    if not os.path.exists(path):
        # bench.py lives at the repo root, next to the package — only a
        # source checkout has it
        raise FileNotFoundError(
            "bench.py not found (the bench command needs a source checkout; "
            f"looked at {path})"
        )
    spec = importlib.util.spec_from_file_location("geomesa_tpu_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    argv = ["--config", str(args.config), "--dist", args.dist]
    if args.smoke:
        argv.append("--smoke")
    if args.cold:
        argv.append("--cold")
    if args.n is not None:
        argv += ["--n", str(args.n)]
    return mod.main(argv)


def _sql(args) -> int:
    """SQL surface through the CLI (upstream exposes SQL via Spark; the
    engine here is sql/engine.py — pushdown, GROUP BY, JOIN)."""
    import numpy as np

    from geomesa_tpu.core.columnar import DictColumn, GeometryColumn
    from geomesa_tpu.core.wkt import to_wkt
    from geomesa_tpu.sql.engine import SqlContext

    r = SqlContext(_store(args)).sql(args.query)
    if r.kind == "count":
        print(r.count)
        return 0
    batch = r.features
    if batch is None or not len(batch):
        if args.format == "json":
            print("[]")
        return 0

    def cells(col):
        if isinstance(col, DictColumn):
            return col.decode()
        if isinstance(col, GeometryColumn):
            return [to_wkt(col.geometry(i)) for i in range(len(col))]
        return [v.item() if hasattr(v, "item") else v for v in np.asarray(col)]

    names = [a.name for a in batch.sft.attributes]
    table = {n: cells(batch.columns[n]) for n in names}
    if args.format == "json":
        def jval(v):
            # NaN is the engine's SQL NULL for doubles; bare NaN is not JSON
            if isinstance(v, float) and v != v:
                return None
            return v

        rows = [
            {n: jval(table[n][i]) for n in names} for i in range(len(batch))
        ]
        print(json.dumps(rows, default=str))
        return 0
    import csv as _csv

    w = _csv.writer(sys.stdout)
    w.writerow(names)
    for i in range(len(batch)):
        w.writerow(
            ["" if table[n][i] is None else table[n][i] for n in names]
        )
    return 0


def _env(args) -> int:
    from geomesa_tpu.utils.config import SystemProperties

    for name, prop in sorted(SystemProperties.all().items()):
        print(f"{name} = {prop.get()} ({prop.provenance}) — {prop.description}")
    return 0
