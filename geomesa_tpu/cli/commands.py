"""CLI subcommand registry.

Commands land here as their subsystems are built; each mirrors a
geomesa-tools command (create-schema, describe-schema, ingest, export,
explain, stats-*) [upstream, unverified].
"""

from __future__ import annotations

import argparse


def register(sub: "argparse._SubParsersAction") -> None:
    version = sub.add_parser("version", help="print version")
    version.set_defaults(func=_version)


def _version(args) -> int:
    import geomesa_tpu

    print(geomesa_tpu.__version__)
    return 0
