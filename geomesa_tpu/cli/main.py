"""geomesa-tpu CLI entry point.

Parity: the geomesa-tools command surface (geomesa-accumulo/geomesa-fs
launcher scripts) [upstream, unverified]. Subcommands are registered as the
corresponding subsystems land; unknown commands list what exists.
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="geomesa-tpu",
        description="TPU-native geospatial analytics (GeoMesa capabilities on JAX)",
    )
    sub = p.add_subparsers(dest="command")
    from geomesa_tpu.cli import commands

    commands.register(sub)
    return p


def main(argv=None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not getattr(args, "func", None):
        parser.print_help()
        return 1
    import os

    try:
        return args.func(args) or 0
    except BrokenPipeError:
        # output piped into head/less that exited early: not an error
        try:
            sys.stdout.close()
        except Exception:
            pass
        return 0
    except (FileNotFoundError, FileExistsError, ValueError, KeyError) as e:
        if os.environ.get("GEOMESA_TPU_DEBUG"):
            raise
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
