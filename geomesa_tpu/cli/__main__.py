"""Allow `python -m geomesa_tpu.cli` (mirrors the geomesa-* launcher scripts)."""

import sys

from geomesa_tpu.cli.main import main

sys.exit(main())
