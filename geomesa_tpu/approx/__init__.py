"""geomesa_tpu.approx — the approximate-answer serving tier.

Two pieces (docs/SERVING.md "Approximate answers"):

- **Sketch answer engine** (`SketchAnswerEngine`): `count` / `density`
  / `topk_cells` queries resolved in microseconds from per-partition
  mergeable occupancy sketches, merged under the plan's
  `manifest_snapshot()` (all-or-nothing per committed write version)
  and returned with TYPED deterministic error bounds on the wire
  (`approx=True, bound, confidence`). Routed only when the a-priori
  bound fits the client's `tolerance` hint and the SLO exactness
  budget is healthy — budget spent means MORE traffic to the exact
  device path, never silent accuracy loss.
- **Exact result cache** (`ResultCache`): count/execute results keyed
  on (typeName, canonical CQL, hints, manifest version) — invalidation
  is exact by construction, not TTL; repeated dashboard queries cost a
  dict lookup and return bit-identical results.
"""

from geomesa_tpu.approx.cache import ResultCache, result_key
from geomesa_tpu.approx.engine import (
    ApproxCount, SketchAnswerEngine, sketch_eligible)
from geomesa_tpu.approx.sketches import (
    PartitionSketch, PartitionSketchStore, StaleSketch, entry_token,
    merge_count_bounds, resample_bounds, topk_cell_bounds, world_cells)

__all__ = [
    "ApproxCount", "PartitionSketch", "PartitionSketchStore",
    "ResultCache", "SketchAnswerEngine", "StaleSketch", "entry_token",
    "merge_count_bounds", "resample_bounds", "result_key",
    "sketch_eligible", "topk_cell_bounds", "world_cells",
]
