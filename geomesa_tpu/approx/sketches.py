"""Per-partition, version-exact occupancy sketches for the approximate
answer tier (docs/SERVING.md "Approximate answers").

The stats layer's sketches (stats/sketches.py) are STORE-global and
rebuilt lazily — good enough for planner cost estimates, but unusable
as an answer path: a racing write can interleave with the lazy rebuild
and a merge over them is not pinned to any committed write version (the
torn-merge hazard ROADMAP item 2 names). This module keeps one sketch
PER PARTITION, keyed by the partition's manifest entry list — the exact
unit `FileSystemStorage.manifest_snapshot()` versions — so a merge over
a plan's snapshot either finds a sketch for every pruned partition at
the snapshot's committed version or refuses typed (`StaleSketch`);
it can never mix sketch state from two write versions.

Sketch contents: a `bins_per_dim x bins_per_dim` spatial occupancy grid
per time bin (the Z3Histogram shape, at serving resolution — default
64x64 per week bin), binned with the SAME arithmetic the stats layer
uses, plus the partition's exact row count. Mergeable by cell-wise sum;
every answer derives a deterministic [lo, hi] interval (inner cells =
fully inside the query, outer cells = overlapping it), so reported
bounds are a-priori guarantees, not confidence heuristics.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.curve.binned_time import TimePeriod, to_binned_time

# serving-resolution default: 64x64 cells per time bin. 16x (each dim)
# finer than the planner's 16x16 cost sketch — the bound scales with
# the query-edge cell mass, so resolution is what buys tolerance fits.
DEFAULT_BINS = 64

_WEEK_MS = 7 * 86400_000
_EPOCH_DOW_OFFSET_MS = 4 * 86400_000  # 1970-01-01 was a Thursday


def world_cells(x: np.ndarray, y: np.ndarray, b: int):
    """(rows, cols) world-grid cell indices of lon/lat arrays — THE
    binning arithmetic every sketch producer and consumer must share
    (partition builds, the subscribe tier's host fold, cell_ranges'
    edge classification): the bound guarantees hold only while all
    sides bin identically."""
    cols = np.clip(((np.asarray(x) + 180.0) / 360.0 * b).astype(int),
                   0, b - 1)
    rows = np.clip(((np.asarray(y) + 90.0) / 180.0 * b).astype(int),
                   0, b - 1)
    return rows, cols


class StaleSketch(RuntimeError):
    """Typed refusal: a pruned partition has no sketch at the plan
    snapshot's committed version (racing write, compaction, or a cold
    store with builds disabled). The caller falls through to the exact
    device path — never to a torn merge."""

    def __init__(self, partition: str, detail: str = ""):
        super().__init__(
            f"no version-exact sketch for partition {partition!r}"
            + (f": {detail}" if detail else ""))
        self.partition = partition


def entry_token(entries: Sequence[dict]) -> tuple:
    """The version token of one partition's manifest entry list: the
    (file, count) pairs IN ORDER. Writes append entries, compaction
    replaces them — both change the token, so equal tokens imply the
    partition's on-disk bytes are exactly what the sketch observed."""
    return tuple((e["file"], int(e["count"])) for e in entries)


def _week_bounds_ms(b: int) -> Tuple[int, int]:
    start = b * _WEEK_MS - _EPOCH_DOW_OFFSET_MS
    return start, start + _WEEK_MS


class PartitionSketch:
    """One partition's occupancy sketch at one manifest version."""

    __slots__ = ("token", "rows", "grids", "bins_per_dim", "has_time")

    def __init__(self, token: tuple, rows: int,
                 grids: Dict[int, np.ndarray], bins_per_dim: int,
                 has_time: bool):
        self.token = token
        self.rows = rows
        self.grids = grids          # time-bin -> [b, b] int64 (row=y)
        self.bins_per_dim = bins_per_dim
        self.has_time = has_time    # False: single bin 0, no dtg


class PartitionSketchStore:
    """Version-exact sketch cache over one FileSystemStorage.

    `get(name, entries)` returns the cached sketch only when its token
    matches `entries` exactly; `build(name, entries)` scans JUST those
    files (pinned — never the live manifest) and caches the result.
    Thread-safe; bounded (oldest partitions evicted past `max_parts` —
    a dropped sketch is never wrong, only rebuild-slow)."""

    def __init__(self, storage, bins_per_dim: int = DEFAULT_BINS,
                 max_parts: int = 4096):
        self.storage = storage
        self.bins_per_dim = int(bins_per_dim)
        self.max_parts = max_parts
        self._lock = threading.Lock()
        self._sketches: Dict[str, PartitionSketch] = {}
        self._sidecar_loaded = 0
        self._sidecar_stale = 0
        sft = storage.sft
        g = sft.default_geometry
        if g is None or g.type != "Point":
            raise ValueError(
                "partition sketches need a point default geometry")
        self._geom = g.name
        d = sft.default_dtg
        self._dtg = d.name if d is not None else None

    def get(self, name: str, entries: Sequence[dict]
            ) -> Optional[PartitionSketch]:
        token = entry_token(entries)
        with self._lock:
            sk = self._sketches.get(name)
        if sk is not None and sk.token == token:
            return sk
        return None

    def build(self, name: str, entries: Sequence[dict]) -> PartitionSketch:
        """Scan exactly `entries`' files and sketch them. Raises
        StaleSketch when a pinned file vanished under us (compaction
        won the race) — the caller's typed fallthrough, not a crash."""
        token = entry_token(entries)
        b = self.bins_per_dim
        grids: Dict[int, np.ndarray] = {}
        rows = 0
        try:
            batches = list(self.storage.scan_partitions(
                [name], manifest={name: list(entries)}))
        except OSError as e:
            raise StaleSketch(name, f"pinned read failed ({e})") from e
        for batch in batches:
            if batch.valid is not None and not batch.valid.all():
                batch = batch.select(batch.valid)
            n = len(batch)
            if not n:
                continue
            rows += n
            gc = batch.columns[self._geom]
            cy, cx = world_cells(gc.x, gc.y, b)
            if self._dtg is not None:
                bins, _ = to_binned_time(
                    np.asarray(batch.columns[self._dtg]), TimePeriod.WEEK)
                ubins, binv = np.unique(bins, return_inverse=True)
                cells = b * b
                flat = np.bincount(
                    binv * cells + cy * b + cx,
                    minlength=len(ubins) * cells).reshape(len(ubins), b, b)
                for i, tb in enumerate(ubins):
                    key = int(tb)
                    if key in grids:
                        grids[key] += flat[i]
                    else:
                        grids[key] = flat[i].astype(np.int64)
            else:
                g0 = np.bincount(cy * b + cx, minlength=b * b).reshape(b, b)
                if 0 in grids:
                    grids[0] += g0
                else:
                    grids[0] = g0.astype(np.int64)
        expected = sum(int(e["count"]) for e in entries)
        if rows != expected:
            # a pinned file was rewritten in place (never happens with
            # uuid file names) or partially read: refuse rather than
            # serve a sketch whose mass disagrees with the manifest
            raise StaleSketch(
                name, f"scanned {rows} rows, manifest says {expected}")
        sk = PartitionSketch(token, rows, grids, b,
                             has_time=self._dtg is not None)
        with self._lock:
            if len(self._sketches) >= self.max_parts and \
                    name not in self._sketches:
                # oldest-first eviction; a dropped sketch only costs a
                # rebuild on its next approximate query
                self._sketches.pop(next(iter(self._sketches)))
            self._sketches[name] = sk
        return sk

    def drop(self, name: Optional[str] = None) -> None:
        with self._lock:
            if name is None:
                self._sketches.clear()
            else:
                self._sketches.pop(name, None)

    def stats(self) -> dict:
        with self._lock:
            return {"partitions": len(self._sketches),
                    "bins_per_dim": self.bins_per_dim,
                    "sidecar_loaded": self._sidecar_loaded,
                    "sidecar_stale": self._sidecar_stale}

    # -- manifest-versioned sidecar (fleet warm spin-up) -------------------
    # ROADMAP item 2's remaining rung: sketches were per-process, rebuilt
    # from pinned reads on first use — every fleet replica paid the full
    # partition rescan cold. The sidecar persists each partition's
    # sketch WITH its manifest entry token; a loading process installs
    # only entries whose token still matches the CURRENT committed
    # manifest, so a stale entry (racing write, compaction) is a typed
    # skip-and-rebuild, never a torn load. One atomic file (tmp +
    # os.replace), exactly like the device-cache manifest.

    SIDECAR = ".approx_sketches.json"
    SIDECAR_VERSION = 1

    @property
    def sidecar_path(self) -> Optional[str]:
        root = getattr(self.storage, "root", None)
        if not root:
            return None
        return os.path.join(root, self.SIDECAR)

    def save_sidecar(self, path: Optional[str] = None) -> Optional[str]:
        """Persist every cached sketch with its version token. Snapshot
        under the lock, serialize+write outside it (the file I/O must
        not stall concurrent merges — the GT09 discipline)."""
        from geomesa_tpu.parallel.distributed import is_coordinator

        if not is_coordinator():
            # multi-host: the coordinator owns the sidecar (GT27).
            # Sketches are built from the shared store, so every host
            # holds the same ones — dropping the write loses nothing
            return None
        path = path or self.sidecar_path
        if path is None:
            return None
        with self._lock:
            snapshot = dict(self._sketches)
        doc = {
            "sidecar_version": self.SIDECAR_VERSION,
            "bins_per_dim": self.bins_per_dim,
            "partitions": {
                name: {
                    "token": [[f, int(c)] for f, c in sk.token],
                    "rows": int(sk.rows),
                    "has_time": bool(sk.has_time),
                    "grids": {str(b): g.ravel().tolist()
                              for b, g in sk.grids.items()},
                }
                for name, sk in snapshot.items()
            },
        }
        import tempfile

        # unique tmp in the SAME directory (os.replace needs one
        # filesystem): two savers — fleet replicas sharing a catalog,
        # two builder threads — must never interleave writes into one
        # tmp file; the last atomic replace wins with a complete document
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(path) or ".",
            prefix=os.path.basename(path) + ".", suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path

    def load_sidecar(self, path: Optional[str] = None
                     ) -> Tuple[int, int]:
        """Install sidecar sketches whose token matches the CURRENT
        committed manifest; returns (loaded, stale). Stale, malformed
        or schema-drifted entries are skipped typed — a rebuild on
        first use is the worst case, exactly the cold behavior."""
        path = path or self.sidecar_path
        if path is None or not os.path.exists(path):
            return 0, 0
        snap_fn = getattr(self.storage, "manifest_snapshot", None)
        if snap_fn is None:
            return 0, 0
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return 0, 0
        if doc.get("sidecar_version") != self.SIDECAR_VERSION \
                or doc.get("bins_per_dim") != self.bins_per_dim:
            return 0, 0
        snap = snap_fn()
        b = self.bins_per_dim
        loaded = stale = 0
        has_time_now = self._dtg is not None
        for name, meta in doc.get("partitions", {}).items():
            token = tuple((f, int(c)) for f, c in meta.get("token", ()))
            if token != entry_token(snap.get(name, [])) \
                    or bool(meta.get("has_time")) != has_time_now:
                stale += 1
                continue
            try:
                grids = {
                    int(bk): np.asarray(flat, np.int64).reshape(b, b)
                    for bk, flat in meta["grids"].items()
                }
                sk = PartitionSketch(token, int(meta["rows"]), grids, b,
                                     has_time=has_time_now)
            except (KeyError, TypeError, ValueError):
                stale += 1
                continue
            with self._lock:
                if len(self._sketches) >= self.max_parts and \
                        name not in self._sketches:
                    self._sketches.pop(next(iter(self._sketches)))
                self._sketches[name] = sk
            loaded += 1
        with self._lock:
            self._sidecar_loaded += loaded
            self._sidecar_stale += stale
        return loaded, stale


# -- merge + bound math ------------------------------------------------------


def cell_ranges(bbox, b: int) -> Tuple[int, int, int, int, int, int, int, int]:
    """(c0, c1, r0, r1, ci0, ci1, ri0, ri1): the outer (overlapping)
    and inner (fully contained) cell index ranges of `bbox` on a
    [b, b] world grid, computed with the SAME binning arithmetic points
    are sketched with — the edge cells holding the bbox boundary are
    always outer-only, so [inner, outer] sums bracket the true count
    regardless of float rounding at the edges."""
    c0 = max(0, min(b - 1, int((bbox.xmin + 180.0) / 360.0 * b)))
    c1 = max(0, min(b - 1, int((bbox.xmax + 180.0) / 360.0 * b)))
    r0 = max(0, min(b - 1, int((bbox.ymin + 90.0) / 180.0 * b)))
    r1 = max(0, min(b - 1, int((bbox.ymax + 90.0) / 180.0 * b)))
    ci0 = 0 if bbox.xmin <= -180.0 else c0 + 1
    ci1 = b - 1 if bbox.xmax >= 180.0 else c1 - 1
    ri0 = 0 if bbox.ymin <= -90.0 else r0 + 1
    ri1 = b - 1 if bbox.ymax >= 90.0 else r1 - 1
    return c0, c1, r0, r1, ci0, ci1, ri0, ri1


def split_time_bins(grids: Dict[int, np.ndarray], interval
                    ) -> Tuple[List[int], List[int]]:
    """(outer_bins, inner_bins) of the sketch's time bins against the
    query interval: outer = bins that may hold matching rows, inner =
    bins whose entire span lies inside the interval. Unbounded sides
    count as covered. Bin classification is conservative — a boundary
    bin is outer-only even when the interval lands exactly on its
    edge."""
    keys = sorted(grids)
    start = interval.start if interval is not None else None
    end = interval.end if interval is not None else None
    if start is None and end is None:
        return keys, keys
    outer: List[int] = []
    inner: List[int] = []
    for bkey in keys:
        b_start, b_end = _week_bounds_ms(bkey)
        if start is not None and b_end <= start:
            continue
        if end is not None and b_start > end:
            continue
        outer.append(bkey)
        # STRICT interior only: a bin whose start coincides with the
        # interval start stays outer — DURING has strict-interior
        # semantics (start < t < end), so a row at exactly t == start
        # must not be counted into the lower bound
        if (start is None or b_start > start) and \
                (end is None or b_end <= end):
            inner.append(bkey)
    return outer, inner


def merge_count_bounds(sketches: Sequence[PartitionSketch], bbox,
                       interval) -> Tuple[int, int]:
    """[lo, hi] bracketing the exact bbox+interval count over the
    merged sketches: lo sums inner cells of inner time bins (every row
    there matches), hi sums outer cells of outer bins (every matching
    row lands there). Deterministic — the interval is a guarantee, not
    a confidence statement."""
    lo = 0
    hi = 0
    for sk in sketches:
        b = sk.bins_per_dim
        c0, c1, r0, r1, ci0, ci1, ri0, ri1 = cell_ranges(bbox, b)
        t_outer, t_inner = split_time_bins(sk.grids, interval)
        inner_set = set(t_inner)
        inner_cells = ci0 <= ci1 and ri0 <= ri1
        for bkey in t_outer:
            g = sk.grids[bkey]
            hi += int(g[r0:r1 + 1, c0:c1 + 1].sum())
            if inner_cells and bkey in inner_set:
                lo += int(g[ri0:ri1 + 1, ci0:ci1 + 1].sum())
    return lo, hi


def merge_region(sketches: Sequence[PartitionSketch], interval
                 ) -> Tuple[Optional[np.ndarray], Optional[np.ndarray], int]:
    """(sure, maybe, b): the merged world grid split into mass that is
    certainly inside the time interval (`sure` — inner time bins) and
    mass that may or may not be (`maybe` — outer-minus-inner bins).
    Returns (None, None, 0) for an empty sketch set."""
    b = 0
    sure = maybe = None
    for sk in sketches:
        if b == 0:
            b = sk.bins_per_dim
            sure = np.zeros((b, b), np.int64)
            maybe = np.zeros((b, b), np.int64)
        t_outer, t_inner = split_time_bins(sk.grids, interval)
        inner_set = set(t_inner)
        for bkey in t_outer:
            (sure if bkey in inner_set else maybe)[:] += sk.grids[bkey]
    return sure, maybe, b


def resample_bounds(sure: np.ndarray, maybe: Optional[np.ndarray],
                    bbox: Tuple[float, float, float, float],
                    width: int, height: int
                    ) -> Tuple[np.ndarray, float]:
    """Resample a [b, b] world grid onto a `height x width` grid over
    `bbox`, returning (grid, bound) where `bound` is the maximum
    per-cell absolute error: |grid[r, c] - exact[r, c]| <= bound for
    every cell. A sketch cell mapping strictly inside one target cell
    with all its mass time-certain contributes exactly; straddling or
    time-uncertain cells distribute proportionally by overlap area and
    charge their full mass to every overlapped cell's uncertainty."""
    b = sure.shape[0]
    xmin, ymin, xmax, ymax = (float(v) for v in bbox)
    out = np.zeros((height, width), np.float64)
    uncert = np.zeros((height, width), np.float64)
    dx = (xmax - xmin) / width
    dy = (ymax - ymin) / height
    sx = 360.0 / b
    sy = 180.0 / b
    c0 = max(0, int((xmin + 180.0) / sx) - 1)
    c1 = min(b - 1, int((xmax + 180.0) / sx) + 1)
    r0 = max(0, int((ymin + 90.0) / sy) - 1)
    r1 = min(b - 1, int((ymax + 90.0) / sy) + 1)
    for r in range(r0, r1 + 1):
        y0s = -90.0 + r * sy
        y1s = y0s + sy
        for c in range(c0, c1 + 1):
            total = float(sure[r, c]) + (
                float(maybe[r, c]) if maybe is not None else 0.0)
            if total == 0.0:
                continue
            x0s = -180.0 + c * sx
            x1s = x0s + sx
            ox0, ox1 = max(x0s, xmin), min(x1s, xmax)
            oy0, oy1 = max(y0s, ymin), min(y1s, ymax)
            if ox0 >= ox1 or oy0 >= oy1:
                continue
            tc0 = max(0, min(width - 1, int((ox0 - xmin) / dx)))
            tc1 = max(0, min(width - 1, int(np.nextafter(
                (ox1 - xmin) / dx, -np.inf))))
            tr0 = max(0, min(height - 1, int((oy0 - ymin) / dy)))
            tr1 = max(0, min(height - 1, int(np.nextafter(
                (oy1 - ymin) / dy, -np.inf))))
            certain = (maybe is None or maybe[r, c] == 0)
            if (tc0 == tc1 and tr0 == tr1 and certain
                    and x0s > xmin + tc0 * dx and x1s < xmin + (tc0 + 1) * dx
                    and y0s > ymin + tr0 * dy and y1s < ymin + (tr0 + 1) * dy):
                # strictly inside one target cell, mass time-certain:
                # exact contribution (no float-edge ambiguity possible)
                out[tr0, tc0] += total
                continue
            area = (x1s - x0s) * (y1s - y0s)
            for tr in range(tr0, tr1 + 1):
                ty0 = ymin + tr * dy
                ty1 = ty0 + dy
                for tc in range(tc0, tc1 + 1):
                    tx0 = xmin + tc * dx
                    tx1 = tx0 + dx
                    ow = max(0.0, min(x1s, tx1) - max(x0s, tx0))
                    oh = max(0.0, min(y1s, ty1) - max(y0s, ty0))
                    if ow <= 0.0 or oh <= 0.0:
                        continue
                    out[tr, tc] += total * (ow * oh) / area
                    uncert[tr, tc] += total
    return out, float(uncert.max()) if uncert.size else 0.0


def topk_cell_bounds(sure: np.ndarray, maybe: Optional[np.ndarray],
                     bbox, k: int) -> List[dict]:
    """Top-k densest world-grid cells intersecting `bbox`, each with a
    deterministic [lo, hi] count interval: inner cells (fully inside
    the bbox) hold [sure, sure+maybe]; edge cells hold [0, sure+maybe]
    (their matching mass depends on where inside the cell the rows
    sit). Ranked by the interval midpoint, ties broken densest-upper-
    bound first then (row, col) for determinism."""
    b = sure.shape[0]
    c0, c1, r0, r1, ci0, ci1, ri0, ri1 = cell_ranges(bbox, b)
    cells: List[dict] = []
    for r in range(r0, r1 + 1):
        for c in range(c0, c1 + 1):
            hi = int(sure[r, c]) + (int(maybe[r, c])
                                    if maybe is not None else 0)
            if hi == 0:
                continue
            inner = ri0 <= r <= ri1 and ci0 <= c <= ci1
            lo = int(sure[r, c]) if inner else 0
            est = (lo + hi) // 2
            cells.append({
                "row": r, "col": c,
                "bbox": [-180.0 + c * 360.0 / b, -90.0 + r * 180.0 / b,
                         -180.0 + (c + 1) * 360.0 / b,
                         -90.0 + (r + 1) * 180.0 / b],
                "count": est,
                "bound": hi - est,
            })
    cells.sort(key=lambda d: (-(d["count"]), -(d["count"] + d["bound"]),
                              d["row"], d["col"]))
    return cells[:k]
