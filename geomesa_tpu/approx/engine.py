"""SketchAnswerEngine: the microsecond answer path for tolerant queries.

Resolves `count`, `density` and `topk_cells` queries directly from the
per-partition mergeable sketches (approx/sketches.py), merged under the
plan's `manifest_snapshot()` — reads are all-or-nothing per committed
write version — and returns answers with TYPED error bounds on the
wire: `approx=True, bound=B, confidence=1.0` means the exact answer is
guaranteed inside `[answer - B, answer + B]` (the bounds here are
deterministic cell-interval brackets, not probabilistic estimates).

Routing contract (docs/SERVING.md "Approximate answers"): the planner
consults this engine only when the client sent a `tolerance` hint (or
the serve ladder injected one), and the engine answers only when the
a-priori bound fits that tolerance — otherwise it returns None with a
metered reason and the query pays the exact device scan. Exactness is
therefore a budgeted contract: the serve layer strips tolerance hints
while the SLO exactness budget is spent, so budget exhaustion moves
traffic to the EXACT path, never to silent accuracy loss.
"""

from __future__ import annotations

import math
import time
from typing import List, Optional

import numpy as np

from geomesa_tpu.approx.sketches import (
    PartitionSketchStore, StaleSketch, merge_count_bounds, merge_region,
    resample_bounds, topk_cell_bounds)
from geomesa_tpu.cql import ast
from geomesa_tpu.telemetry.trace import TRACER

__all__ = ["ApproxCount", "SketchAnswerEngine", "StaleSketch",
           "sketch_eligible"]


class ApproxCount(int):
    """An int count carrying its typed error bound: every existing
    consumer (comparisons, JSON serialization, arithmetic) sees a plain
    int; approx-aware consumers (the wire payload, ServeEvents) read
    `.bound` / `.confidence`. The exact count is guaranteed in
    `[value - bound, value + bound]`."""

    approx = True

    def __new__(cls, value: int, bound: int, confidence: float = 1.0):
        self = super().__new__(cls, value)
        self.bound = int(bound)
        self.confidence = float(confidence)
        return self


def sketch_eligible(f, geom_name: Optional[str],
                    dtg_name: Optional[str]) -> bool:
    """True when the filter's EXACT semantics are captured by its
    covering (bbox AND interval) — the only shape the occupancy
    sketches can bracket. Anything else (OR/NOT, attribute predicates,
    DWITHIN, non-default columns) routes exact."""
    if isinstance(f, ast.Include):
        return True
    if isinstance(f, ast.And):
        return all(sketch_eligible(c, geom_name, dtg_name)
                   for c in f.children)
    if isinstance(f, ast.SpatialPredicate):
        return f.op == "BBOX" and f.prop.name == geom_name
    if isinstance(f, ast.TemporalPredicate):
        return f.prop.name == dtg_name
    if isinstance(f, ast.Comparison):
        return (isinstance(f.left, ast.Property)
                and f.left.name == dtg_name
                and isinstance(f.right, ast.Literal)
                and f.right.kind == "datetime"
                and f.op in ("=", "<", "<=", ">", ">="))
    if isinstance(f, ast.Between):
        return (f.prop.name == dtg_name
                and getattr(f.lo, "kind", None) == "datetime")
    return False


class SketchAnswerEngine:
    """One engine per planner (lazily built, like the stats manager).

    `answer(plan, query)` returns a QueryResult served from sketches,
    or None — in which case `last_reason` says why (metered):
      ineligible      — filter/hints outside the sketchable shape
      bound_exceeded  — a-priori bound does not fit the tolerance
      stale_sketch    — a pruned partition has no sketch at the plan's
                        snapshot version and the pinned rebuild raced
                        (typed fallthrough — satellite of ROADMAP
                        item 2: never a torn merge)
      cold            — admission peek only (build=False): the sketch
                        is not built yet; the dispatch path builds it
      no_snapshot     — storage without manifest versioning
    """

    def __init__(self, planner, bins_per_dim: Optional[int] = None,
                 allow_build: bool = True):
        import threading

        self.planner = planner
        self.allow_build = allow_build
        self.store: Optional[PartitionSketchStore] = None
        self.last_reason = ""
        # fast-count memos (the microsecond path): parsed-filter
        # eligibility/bounds per filter TEXT, and merged [lo, hi] per
        # (canonical CQL, manifest version) — version in the key makes
        # staleness impossible by construction. Both bounded.
        self._lock = threading.Lock()
        self._parsed: dict = {}
        self._count_memo: dict = {}
        # per-(partition, attribute) HyperLogLog sketches for the
        # distinct tier (fast_distinct), token-matched like the
        # occupancy store: equal entry tokens imply the partition's
        # on-disk bytes are exactly what the sketch observed. Bounded,
        # oldest-first eviction.
        self._hll_parts: dict = {}
        try:
            kw = {}
            if bins_per_dim is not None:
                kw["bins_per_dim"] = bins_per_dim
            self.store = PartitionSketchStore(planner.storage, **kw)
        except (ValueError, AttributeError):
            self.store = None  # non-point / sketchless storage: disabled
        if self.store is not None:
            # sketch-warm spin-up (ROADMAP item 2 remaining rung): a
            # fleet replica whose predecessor persisted the sidecar
            # starts with version-exact sketches installed instead of
            # paying the pinned partition rescans on first use; a
            # stale/missing sidecar is the cold path, typed
            try:
                loaded, stale = self.store.load_sidecar()
                if loaded or stale:
                    from geomesa_tpu.utils.metrics import metrics

                    metrics.counter("approx.sidecar.loaded", loaded)
                    metrics.counter("approx.sidecar.stale", stale)
            # gt: waive GT14
            # (deliberate degrade: the sidecar is a warm-start
            # optimization — a corrupt/unreadable file must cost a
            # rebuild, never engine construction)
            except Exception:
                pass

    # -- metering ----------------------------------------------------------

    def _miss(self, reason: str, meter: bool = True) -> None:
        self.last_reason = reason
        if meter:
            try:
                from geomesa_tpu.utils.metrics import metrics

                metrics.counter("approx.fallthrough", reason=reason)
            except Exception:
                pass
        return None

    def _served(self, kind: str, t0: float) -> None:
        try:
            from geomesa_tpu.utils.metrics import metrics

            metrics.counter("approx.sketch_served", kind=kind)
            metrics.histogram("approx.answer").update(
                time.perf_counter() - t0)
        except Exception:
            pass

    # -- sketch collection -------------------------------------------------

    def _sketches(self, plan) -> List:
        """A version-exact sketch per pruned partition, built on demand
        from the plan's pinned snapshot. Raises StaleSketch when any
        partition cannot be served at the snapshot's version. After a
        merge that built anything, the sidecar persists ONCE (not per
        partition — a cold P-partition store must pay one file write,
        not P rewrites of the whole store)."""
        manifest = plan.manifest
        out = []
        built = 0
        for name in plan.partitions:
            entries = manifest.get(name, [])
            if not entries:
                continue
            sk = self.store.get(name, entries)
            if sk is None:
                if not self.allow_build:
                    raise StaleSketch(name, "builds disabled")
                sk = self._build_metered(name, entries)
                built += 1
            out.append(sk)
        if built:
            self._save_sidecar()
        return out

    def _build_metered(self, name, entries):
        """Build one partition's sketch from a pinned read, metered —
        builds are the sketch tier's only non-microsecond cost and
        must be visible in /metrics, not folded silently into a
        query's latency."""
        t0 = time.perf_counter()
        sk = self.store.build(name, entries)
        try:
            from geomesa_tpu.utils.metrics import metrics

            metrics.counter("approx.sketch_built")
            metrics.histogram("approx.build").update(
                time.perf_counter() - t0)
        except Exception:
            pass
        return sk

    def _save_sidecar(self) -> None:
        """Persist the sketch store so the NEXT process (fleet replica
        spin-up, a restart) loads version-exact sketches instead of
        re-scanning partitions. Called once per merge that built
        anything."""
        try:
            self.store.save_sidecar()
        # gt: waive GT14
        # (deliberate degrade: sidecar persistence must never fail the
        # answer that triggered the build — an unwritable catalog dir
        # just means the next process starts cold)
        except Exception:
            pass

    # -- answers -----------------------------------------------------------

    def answer(self, plan, query):
        """The sketch tier's single entry point: a QueryResult (kind
        count/density/topk_cells, approx fields set) or None."""
        from geomesa_tpu.plan.planner import QueryResult

        hints = query.hints
        if self.store is None:
            return self._miss("ineligible")
        if plan.manifest is None:
            return self._miss("no_snapshot")
        sft = self.planner.storage.sft
        if (sft.user_data or {}).get("geomesa.vis.attr"):
            return self._miss("ineligible")  # auth masks need the rows
        if hints.sampling or hints.loose_bbox or hints.is_stats \
                or hints.is_bin or hints.is_arrow:
            return self._miss("ineligible")
        g = sft.default_geometry
        d = sft.default_dtg
        if not sketch_eligible(plan.filter, g.name if g else None,
                               d.name if d else None):
            return self._miss("ineligible")
        tol = hints.tolerance
        t0 = time.perf_counter()
        with TRACER.span("approx.answer"):
            try:
                if hints.topk_cells:
                    if tol is None:
                        return self._miss("ineligible")
                    sure, maybe, b = self._region(plan)
                    if sure is None:
                        cells: list = []
                        worst = 0
                        top = 0
                    else:
                        cells = topk_cell_bounds(sure, maybe, plan.bbox,
                                                 int(hints.topk_cells))
                        worst = max((c["bound"] for c in cells), default=0)
                        top = cells[0]["count"] if cells else 0
                    if worst > tol * max(top, 1):
                        return self._miss("bound_exceeded")
                    self._served("topk_cells", t0)
                    return QueryResult(
                        "topk_cells", stats=cells,
                        count=sum(c["count"] for c in cells),
                        approx=True, bound=float(worst),
                        confidence=1.0,
                        version=plan.manifest.version)
                if hints.is_density:
                    if hints.density_weight is not None:
                        return self._miss("ineligible")
                    if tol is None:
                        return self._miss("ineligible")
                    sure, maybe, b = self._region_clipped(plan)
                    h, w = int(hints.density_height), int(hints.density_width)
                    if sure is None:
                        grid = np.zeros((h, w), np.float64)
                        bound = 0.0
                    else:
                        grid, bound = resample_bounds(
                            sure, maybe, hints.density_bbox, w, h)
                    total = float(grid.sum())
                    if bound > tol * max(total, 1.0):
                        return self._miss("bound_exceeded")
                    self._served("density", t0)
                    return QueryResult(
                        "density", grid=grid, count=int(round(total)),
                        approx=True, bound=float(bound), confidence=1.0,
                        version=plan.manifest.version)
                # count
                if tol is None:
                    return self._miss("ineligible")
                if query.max_features is not None:
                    return self._miss("ineligible")
                lo, hi = merge_count_bounds(
                    self._sketches(plan), plan.bbox, plan.interval)
                est = (lo + hi) // 2
                bound = hi - est
                if bound > tol * max(est, 1):
                    return self._miss("bound_exceeded")
                self._served("count", t0)
                return QueryResult(
                    "count", count=est, approx=True, bound=float(bound),
                    confidence=1.0, version=plan.manifest.version)
            except StaleSketch:
                # satellite contract: a racing write / compaction can
                # never produce a torn merge — it produces a typed,
                # metered fallthrough to the exact device path
                return self._miss("stale_sketch")

    # -- the microsecond count path ----------------------------------------

    def _parse_filter(self, query):
        """(eligible, canonical_cql, bbox, interval) for the query's
        filter, memoized per filter TEXT — the fast path must not pay
        a CQL parse per request."""
        key = query.filter if isinstance(query.filter, str) else None
        if key is not None:
            with self._lock:
                got = self._parsed.get(key)
            if got is not None:
                return got
        from geomesa_tpu.cql.extract import (
            BBox, Interval, extract_bbox, extract_intervals)

        sft = self.planner.storage.sft
        g = sft.default_geometry
        d = sft.default_dtg
        f = query.filter_ast
        eligible = sketch_eligible(f, g.name if g else None,
                                   d.name if d else None)
        cql = ast.to_cql(f)
        bbox = extract_bbox(f, g.name) if g else BBox(-180, -90, 180, 90)
        interval = (extract_intervals(f, d.name) if d
                    else Interval(None, None))
        out = (eligible, cql, bbox, interval)
        if key is not None:
            with self._lock:
                if len(self._parsed) > 512:
                    self._parsed.clear()
                self._parsed[key] = out
        return out

    def fast_count(self, query, build: bool = True):
        """The serve-path count entry: answer a tolerant count from the
        (canonical CQL, manifest version)-memoized sketch merge without
        paying the full planner pipeline — one manifest_snapshot() plus
        a dict hit when warm. Returns a QueryResult or None (metered
        fallthrough; the caller pays the exact path). The interceptor
        chain must already have run on `query`.

        `build=False` (the ADMISSION peek): only version-exact sketches
        already cached may answer — a cold/stale partition falls
        through instead of running a synchronous parquet rescan on the
        submit thread (on a wire connection that thread is the reader
        loop; the dispatch path builds, metered, where exact scans
        already run)."""
        # the admission peek (build=False) meters only its ONE
        # distinctive outcome — "cold" (sketch not built yet, builds
        # deferred to the dispatch thread). Every other fallthrough
        # reason is metered by the dispatch-path retry, so one request
        # never counts the same reason twice.
        meter = build
        hints = query.hints
        if hints.distinct is not None:
            return self.fast_distinct(query, build=build)
        if self.store is None:
            return self._miss("ineligible", meter)
        if hints.sampling or hints.loose_bbox or hints.is_stats \
                or hints.is_bin or hints.is_arrow or hints.is_density \
                or hints.topk_cells or query.max_features is not None:
            return self._miss("ineligible", meter)
        sft = self.planner.storage.sft
        if (sft.user_data or {}).get("geomesa.vis.attr"):
            return self._miss("ineligible", meter)
        snap_fn = getattr(self.planner.storage, "manifest_snapshot", None)
        if snap_fn is None:
            return self._miss("no_snapshot", meter)
        t0 = time.perf_counter()
        with TRACER.span("approx.answer"):
            eligible, cql, bbox, interval = self._parse_filter(query)
            if not eligible:
                return self._miss("ineligible", meter)
            snap = snap_fn()
            version = getattr(snap, "version", None)
            mkey = (query.type_name, cql, version)
            with self._lock:
                bounds = self._count_memo.get(mkey)
            if bounds is None:
                try:
                    parts = self.planner.storage.prune_partitions(
                        bbox, interval, manifest=snap)
                    sketches = []
                    built = 0
                    for name in parts:
                        entries = snap.get(name, [])
                        if not entries:
                            continue
                        sk = self.store.get(name, entries)
                        if sk is None:
                            if not (build and self.allow_build):
                                raise StaleSketch(name, "builds disabled")
                            sk = self._build_metered(name, entries)
                            built += 1
                        sketches.append(sk)
                    if built:
                        self._save_sidecar()  # once per merge, not per build
                    bounds = merge_count_bounds(sketches, bbox, interval)
                except StaleSketch:
                    # admission peek: a missing sketch here is routine
                    # first-touch cold, not the racing-write signal —
                    # "stale_sketch" (alert-worthy) is reserved for the
                    # building path, where a pinned read actually raced
                    return self._miss("cold" if not build
                                      else "stale_sketch")
                with self._lock:
                    if len(self._count_memo) > 512:
                        self._count_memo.clear()
                    self._count_memo[mkey] = bounds
            lo, hi = bounds
            est = (lo + hi) // 2
            bound = hi - est
            tol = hints.tolerance
            if tol is None or bound > tol * max(est, 1):
                return self._miss("bound_exceeded", meter)
            self._served("count", t0)
            from geomesa_tpu.plan.planner import QueryResult

            return QueryResult("count", count=est, approx=True,
                               bound=float(bound), confidence=1.0,
                               version=version)

    # -- the distinct tier -------------------------------------------------

    # HLL precision for the distinct tier: p=12 -> 4096 registers,
    # relative standard error 1.04/sqrt(4096) ~ 1.6%. The wire bound is
    # the 3-sigma interval, shipped with confidence 0.99 (conservative
    # for a ~0.997 normal tail).
    _HLL_P = 12
    _HLL_RSE = 1.04 / math.sqrt(1 << _HLL_P)

    def _partition_hll(self, name, entries, attr: str, build: bool):
        """One partition's Cardinality sketch over `attr`: version-exact
        (entry-token-matched, like the occupancy store) and built from a
        PINNED scan of exactly `entries`' files. Raises StaleSketch on a
        cold miss with builds deferred (the admission peek) or a pinned
        read lost to compaction."""
        from geomesa_tpu.approx.sketches import entry_token
        from geomesa_tpu.stats.sketches import Cardinality

        token = entry_token(entries)
        key = (name, attr)
        with self._lock:
            got = self._hll_parts.get(key)
        if got is not None and got[0] == token:
            return got[1]
        if not (build and self.allow_build):
            raise StaleSketch(name, "builds disabled")
        sk = Cardinality(attr, p=self._HLL_P)
        t0 = time.perf_counter()
        try:
            batches = list(self.planner.storage.scan_partitions(
                [name], manifest={name: list(entries)}))
        except OSError as e:
            raise StaleSketch(name, f"pinned read failed ({e})") from e
        from geomesa_tpu.core.columnar import DictColumn

        for batch in batches:
            if batch.valid is not None and not batch.valid.all():
                batch = batch.select(batch.valid)
            col = batch.columns[attr]
            if isinstance(col, DictColumn):
                vals = np.asarray(col.decode(), dtype=object)
                sk.observe(vals[vals != None])  # noqa: E711 — elementwise
            else:
                sk.observe(np.asarray(col))
        try:
            from geomesa_tpu.utils.metrics import metrics

            metrics.counter("approx.hll_built")
            metrics.histogram("approx.build").update(
                time.perf_counter() - t0)
        except Exception:
            pass
        with self._lock:
            while len(self._hll_parts) > 512:
                self._hll_parts.pop(next(iter(self._hll_parts)))
            self._hll_parts[key] = (token, sk)
        return sk

    def fast_distinct(self, query, build: bool = True):
        """`distinct`-hinted counts: estimate COUNT(DISTINCT attr) by
        merging the version-exact per-partition HyperLogLog sketches
        under ONE manifest snapshot — Cardinality.merge is a register
        max, associative and lossless, so the merged estimate equals
        the estimate of one sketch over the whole store. INCLUDE
        filters only: a predicate changes WHICH rows count, and the
        partition sketches observed them all. The 3-sigma bound must
        fit the tolerance, like every other sketch answer; otherwise
        the caller pays the exact feature scan + host unique count
        (plan/planner.py count_result)."""
        meter = build
        hints = query.hints
        attr = hints.distinct
        if hints.sampling or hints.loose_bbox or hints.is_stats \
                or hints.is_bin or hints.is_arrow or hints.is_density \
                or hints.topk_cells or query.max_features is not None:
            return self._miss("ineligible", meter)
        sft = self.planner.storage.sft
        if (sft.user_data or {}).get("geomesa.vis.attr"):
            return self._miss("ineligible", meter)
        if attr not in sft:
            return self._miss("ineligible", meter)
        if not isinstance(query.filter_ast, ast.Include):
            return self._miss("ineligible", meter)
        tol = hints.tolerance
        if tol is None:
            return self._miss("ineligible", meter)
        snap_fn = getattr(self.planner.storage, "manifest_snapshot", None)
        if snap_fn is None:
            return self._miss("no_snapshot", meter)
        t0 = time.perf_counter()
        with TRACER.span("approx.answer"):
            snap = snap_fn()
            version = getattr(snap, "version", None)
            mkey = ("distinct", query.type_name, attr, version)
            with self._lock:
                est = self._count_memo.get(mkey)
            if est is None:
                from geomesa_tpu.stats.sketches import Cardinality

                merged = Cardinality(attr, p=self._HLL_P)
                try:
                    for name, entries in snap.items():
                        if entries:
                            merged.merge(self._partition_hll(
                                name, entries, attr, build))
                except StaleSketch:
                    # same cold-vs-raced split as the count tier
                    return self._miss("cold" if not build
                                      else "stale_sketch")
                est = int(round(merged.result()))
                with self._lock:
                    if len(self._count_memo) > 512:
                        self._count_memo.clear()
                    self._count_memo[mkey] = est
            bound = int(math.ceil(3.0 * self._HLL_RSE * est))
            if bound > tol * max(est, 1):
                return self._miss("bound_exceeded", meter)
            self._served("distinct", t0)
            from geomesa_tpu.plan.planner import QueryResult

            return QueryResult("count", count=est, approx=True,
                               bound=float(bound), confidence=0.99,
                               version=version)

    def _region(self, plan):
        return merge_region(self._sketches(plan), plan.interval)

    def _region_clipped(self, plan):
        """The merged region with the FILTER bbox folded in: cells
        fully inside it stay certain, cells its edge cuts through move
        their mass to the uncertain component (rows there may or may
        not match), cells outside drop to zero — so a density window
        wider than the filter bbox still gets a valid bound."""
        from geomesa_tpu.approx.sketches import cell_ranges

        sure, maybe, b = self._region(plan)
        if sure is None:
            return sure, maybe, b
        c0, c1, r0, r1, ci0, ci1, ri0, ri1 = cell_ranges(plan.bbox, b)
        keep = np.zeros((b, b), bool)
        keep[r0:r1 + 1, c0:c1 + 1] = True
        inner = np.zeros((b, b), bool)
        if ri0 <= ri1 and ci0 <= ci1:
            inner[ri0:ri1 + 1, ci0:ci1 + 1] = True
        maybe2 = np.where(keep, (maybe if maybe is not None else 0)
                          + np.where(inner, 0, sure), 0).astype(np.int64)
        sure2 = np.where(inner, sure, 0).astype(np.int64)
        return sure2, maybe2, b

    def stats(self) -> dict:
        out = {"enabled": self.store is not None,
               "allow_build": self.allow_build}
        if self.store is not None:
            out.update(self.store.stats())
        return out
