"""Version-exact result cache for the serve layer.

Keys are (kind, typeName, CANONICAL CQL, hints, result-shape extras,
`manifest_snapshot()` version) — so invalidation is exact BY
CONSTRUCTION, not TTL: a committed write bumps the manifest version and
every key minted before it simply stops matching. A hit is therefore
always bit-identical to re-running the query against the same committed
state (asserted in tests/test_approx.py); bounded LRU keeps memory flat
and old-version entries age out through normal eviction.

The canonical-CQL discipline is load-bearing: keying on raw filter text
would miss-storm on equivalent spellings ("a=1 AND b=2" vs
"a = 1 AND b = 2") — lint rule GT21 (docs/ANALYSIS.md) flags insertion
sites that bypass `result_key` with raw `.cql` text.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Tuple

_MISS = object()


def result_key(kind: str, query, version: Optional[int]
               ) -> Optional[tuple]:
    """The cache key for one (kind, query, manifest version), or None
    when the query is uncacheable: no committed version to pin
    (live/Kafka stores), a tolerance hint (approx answers are already
    microseconds and bound-dependent), or an unparseable filter. The
    filter ALWAYS canonicalizes through the AST (GT21)."""
    if version is None or kind == "knn":
        return None
    h = query.hints
    if h.tolerance is not None:
        return None
    try:
        from geomesa_tpu.cql import ast

        cql = ast.to_cql(query.filter_ast)
    except Exception:
        return None
    if kind == "count":
        return ("count", query.type_name, cql, str(h),
                query.max_features, int(version))
    attrs = tuple(query.attributes) if query.attributes is not None else None
    sort = tuple(query.sort_by) if query.sort_by else None
    return ("execute", query.type_name, cql, str(h), attrs, sort,
            query.max_features, query.crs, int(version))


class ResultCache:
    """Bounded LRU with hit/miss/evict metrics. Values are treated as
    immutable by every consumer (the same discipline the batcher's
    count/execute dedup already relies on), so sharing the object is
    safe and a hit is bit-identical by identity."""

    def __init__(self, max_entries: int = 256):
        if max_entries < 1:
            raise ValueError("result cache needs max_entries >= 1")
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, object]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key: Optional[tuple],
            count_miss: bool = True) -> Tuple[bool, object]:
        """(hit, value). A None key is a structural miss (unmetered —
        the query was never cacheable). `count_miss=False` suppresses
        miss accounting for second-chance peeks (the dispatch loop
        re-peeks requests the admission peek already counted)."""
        if key is None:
            return False, None
        with self._lock:
            got = self._entries.get(key, _MISS)
            if got is _MISS:
                if count_miss:
                    self.misses += 1
                hit = False
                val = None
            else:
                self._entries.move_to_end(key)
                self.hits += 1
                hit = True
                val = got
        if hit or count_miss:
            try:
                from geomesa_tpu.utils.metrics import metrics

                metrics.counter("serve.cache.hit" if hit
                                else "serve.cache.miss")
            except Exception:
                pass
        return hit, val

    def put(self, key: Optional[tuple], value) -> None:
        if key is None:
            return
        evicted = 0
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions += 1
                evicted += 1
        if evicted:
            try:
                from geomesa_tpu.utils.metrics import metrics

                metrics.counter("serve.cache.evict", evicted)
            except Exception:
                pass

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "max_entries": self.max_entries,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}
