"""geomesa_tpu.telemetry — per-query span tracing, flight recorder and
live metrics export for the serve path (docs/OBSERVABILITY.md).

Pieces:

- `trace.py`: the span core. `TRACER.span("phase")` context managers at
  every serve/plan/engine seam; <2µs per live span, a shared no-op when
  tracing is off or the thread has no scoped trace.
- `recorder.py`: `RECORDER`, a bounded ring buffer of the last N
  completed query traces plus breaker/quarantine/fault events, dumpable
  on demand or automatically on un-typed dispatcher errors.
- `export.py`: Chrome/Perfetto trace JSON, JSON-lines, and the
  `MetricsServer` behind `gmtpu serve --metrics-port` (`/metrics`,
  `/healthz`, `/debug/traces`, `/debug/stats`, `/debug/gap`).
- `gap.py`: the dispatch-gap report (`gmtpu trace --gap`) — host-gap vs
  kernel-time attribution aggregated from spans, the evidence ROADMAP
  item 2's pipelining work starts from.
- `slo.py`: declared objectives + sliding-window error-budget burn
  (`/debug/slo`, `slo.burn_rate`/`slo.budget_remaining` gauges, the
  degradation ladder's burn-rate input).
- `prof.py`: the always-on continuous profiler (`gmtpu prof`,
  `/debug/prof`) — reservoir-sampled per-phase/per-kernel/per-shard
  distributions folded from every recorded trace at bounded cost.
- `sentinel.py`: the perf-regression sentinel (`gmtpu sentinel`,
  `bench-serve --sentinel/--record-baseline`) — noise-tolerant
  baseline comparison with typed per-metric verdicts and a nonzero
  exit on regression.
"""

from geomesa_tpu.telemetry.export import (MetricsServer, from_perfetto,
                                          to_perfetto, write_jsonl)
from geomesa_tpu.telemetry.gap import gap_report, render_gap
from geomesa_tpu.telemetry.prof import (PROFILER, ContinuousProfiler,
                                        render_prof)
from geomesa_tpu.telemetry.recorder import RECORDER, FlightRecorder
from geomesa_tpu.telemetry.slo import SloEngine, SloSpec, render_slo
from geomesa_tpu.telemetry.trace import NOOP_SPAN, Span, Trace, Tracer, TRACER

__all__ = [
    "TRACER", "Tracer", "Trace", "Span", "NOOP_SPAN",
    "RECORDER", "FlightRecorder",
    "MetricsServer", "to_perfetto", "from_perfetto", "write_jsonl",
    "gap_report", "render_gap",
    "SloEngine", "SloSpec", "render_slo",
    "PROFILER", "ContinuousProfiler", "render_prof",
]
