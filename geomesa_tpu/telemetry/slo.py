"""SLO engine: declared objectives, sliding windows, error-budget burn.

The serve stack records what happened (histograms, ServeEvents, spans)
but renders no verdict: nothing in the process can say "the p99
objective is still met" or "we are burning error budget 14x faster than
sustainable". This module closes that loop (docs/OBSERVABILITY.md
"SLOs"):

- **Declared objectives** load from a TOML/JSON spec (`SloSpec.load`):
  per-query-kind latency thresholds, availability (1 - typed-error
  rate), exactness (1 - degraded-response rate), and a sustained
  throughput floor. Python < 3.11 has no tomllib, so a deliberately
  tiny TOML subset parser (sections, `key = value` scalars, comments)
  backs `.toml` specs there — the spec format stays portable either
  way.
- **Sliding windows**: the engine keeps a bounded deque of per-request
  observations (`observe()` is called by QueryService._finish_window —
  a few tuple ops, no locks beyond one deque append) and evaluates each
  objective over a fast and a slow window (default 5m/1h, scaled down
  for tests via the injectable `clock`).
- **Burn rate**: `bad_fraction / (1 - goal)` — 1.0 means the objective
  is consuming budget exactly as fast as the goal allows; the classic
  multi-window rule (fast AND slow over `burn_threshold`) gates
  alerting and the degradation ladder, so a single slow request can
  never shed traffic but a sustained breach does.
- **Error budget**: `slo.budget_remaining{objective}` = the fraction of
  the budget window's allowance still unspent; 0 means the objective is
  formally violated for that window.
- **Degradation input**: `degrade_boost()` maps breaching
  degrade-marked objectives onto the PR-2 ladder (1 = hint downgrades,
  2 = shed batch class), so shedding engages on budget exhaustion, not
  just queue occupancy (`QueryService.degrade_level` takes the max of
  the two signals).

Exported state: `slo.budget_remaining{objective}` and
`slo.burn_rate{objective,window}` gauges (refreshed by the service's
pre-scrape hook) plus the `/debug/slo` JSON report on MetricsServer.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

__all__ = ["Objective", "SloSpec", "SloEngine", "parse_toml_subset"]

KINDS = ("latency", "availability", "exactness", "throughput")

# statuses that spend availability budget. Rejections (load shedding)
# are deliberately NOT here: shedding is the system protecting its
# objectives, and counting it against availability would make the
# ladder burn the very budget it exists to preserve.
BAD_STATUSES = ("error", "timeout")


@dataclass(frozen=True)
class Objective:
    """One declared objective. `goal` is the target GOOD fraction
    (0.99 = "99% of requests meet the condition"); the error budget is
    `1 - goal`."""

    name: str
    kind: str                      # latency|availability|exactness|throughput
    goal: float = 0.99
    threshold_ms: float = 0.0      # latency: the per-request bound
    query_kind: str = ""           # filter: knn|count|execute ("" = all)
    min_per_s: float = 0.0         # throughput: served-requests/s floor
    pts_per_query: float = 0.0     # throughput: optional pts/s conversion
    degrade: bool = False          # feed the degradation ladder
    min_count: int = 8             # below this, verdict = insufficient-data

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"objective {self.name!r}: unknown kind {self.kind!r} "
                f"(one of {KINDS})")
        if not 0.0 < self.goal < 1.0:
            raise ValueError(
                f"objective {self.name!r}: goal must be in (0, 1), "
                f"got {self.goal}")
        if self.kind == "latency" and self.threshold_ms <= 0:
            raise ValueError(
                f"objective {self.name!r}: latency objectives need "
                f"threshold_ms > 0")
        if self.kind == "throughput" and self.min_per_s <= 0:
            raise ValueError(
                f"objective {self.name!r}: throughput objectives need "
                f"min_per_s > 0")

    @property
    def budget(self) -> float:
        return 1.0 - self.goal


@dataclass
class SloSpec:
    """The declared objective set plus window tuning. Windows are
    seconds; tests scale them down and drive a fake clock."""

    objectives: Dict[str, Objective] = field(default_factory=dict)
    fast_window_s: float = 300.0     # 5m: catches a fast burn
    slow_window_s: float = 3600.0    # 1h: confirms it is sustained
    burn_threshold: float = 2.0      # multi-window alert/degrade gate
    budget_window_s: float = 0.0     # 0 = slow window

    def __post_init__(self):
        if self.fast_window_s <= 0 or self.slow_window_s <= 0:
            raise ValueError("SLO windows must be > 0 seconds")
        if self.fast_window_s > self.slow_window_s:
            raise ValueError("fast window must not exceed the slow window")
        if not self.budget_window_s:
            self.budget_window_s = self.slow_window_s

    @classmethod
    def from_dict(cls, doc: dict) -> "SloSpec":
        cfg = dict(doc.get("slo", ()))
        objectives = {}
        objs = doc.get("objective", doc.get("objectives", {}))
        if not isinstance(objs, dict) or not objs:
            raise ValueError(
                "SLO spec has no [objective.<name>] sections")
        for name, body in objs.items():
            if not isinstance(body, dict):
                raise ValueError(
                    f"objective {name!r} body must be a table/object")
            known = {f.name for f in
                     Objective.__dataclass_fields__.values()}  # type: ignore
            unknown = set(body) - (known - {"name"})
            if unknown:
                raise ValueError(
                    f"objective {name!r}: unknown key(s) "
                    f"{sorted(unknown)}")
            objectives[name] = Objective(name=name, **body)
        known_cfg = {"fast_window_s", "slow_window_s", "burn_threshold",
                     "budget_window_s"}
        unknown = set(cfg) - known_cfg
        if unknown:
            raise ValueError(f"[slo] unknown key(s) {sorted(unknown)}")
        return cls(objectives=objectives, **cfg)

    @classmethod
    def load(cls, path: str) -> "SloSpec":
        with open(path) as f:
            text = f.read()
        if path.endswith(".json"):
            return cls.from_dict(json.loads(text))
        try:
            import tomllib  # Python >= 3.11

            doc = tomllib.loads(text)
        except ModuleNotFoundError:
            doc = parse_toml_subset(text)
        return cls.from_dict(doc)


def parse_toml_subset(text: str) -> dict:
    """A deliberately small TOML reader for SLO specs on hosts without
    tomllib: `[section]` / `[section.sub]` headers and scalar
    `key = value` lines (quoted strings, ints, floats, true/false),
    full-line and trailing comments. Arrays/dates/multiline strings are
    out of scope — a spec needing them should ship JSON instead."""
    root: dict = {}
    table = root
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("["):
            if not line.endswith("]"):
                raise ValueError(f"spec line {lineno}: malformed header")
            table = root
            for part in line[1:-1].strip().split("."):
                part = part.strip()
                if not part:
                    raise ValueError(
                        f"spec line {lineno}: empty header segment")
                table = table.setdefault(part, {})
                if not isinstance(table, dict):
                    raise ValueError(
                        f"spec line {lineno}: header collides with a "
                        f"value")
            continue
        if "=" not in line:
            raise ValueError(f"spec line {lineno}: expected key = value")
        key, _, val = line.partition("=")
        key = key.strip()
        val = val.strip()
        if val.startswith(('"', "'")):
            quote = val[0]
            end = val.find(quote, 1)
            if end < 0:
                raise ValueError(
                    f"spec line {lineno}: unterminated string")
            table[key] = val[1:end]
            continue
        # strip a trailing comment from non-string scalars
        val = val.split("#", 1)[0].strip()
        if val in ("true", "false"):
            table[key] = val == "true"
            continue
        try:
            table[key] = int(val)
        except ValueError:
            try:
                table[key] = float(val)
            except ValueError:
                raise ValueError(
                    f"spec line {lineno}: cannot parse value {val!r}"
                ) from None
    return root


# observation tuple layout:
# (ts_s, kind, status, latency_s, degraded, weight)


class SloEngine:
    """Sliding-window objective evaluation over per-request
    observations.

    `observe()` is the hot-path entry (QueryService._finish_window, one
    call per resolved request): a tuple build + deque append under one
    lock. Everything else — evaluation, burn rates, gauge export, the
    /debug/slo report — runs on scrape/introspection threads and walks
    a snapshot. The `clock` is injectable so tests drive windows with a
    fake clock instead of sleeping."""

    def __init__(self, spec: SloSpec,
                 clock: Callable[[], float] = time.monotonic,
                 max_observations: int = 65536):
        if not spec.objectives:
            raise ValueError("SloEngine needs at least one objective")
        self.spec = spec
        self.clock = clock
        self._lock = threading.Lock()
        self._obs: "deque[tuple]" = deque(maxlen=max_observations)
        self._started_at = clock()
        self._dropped = 0
        # degrade_boost cache: the ladder consults the engine on EVERY
        # admission, and a full window walk there would put an O(obs)
        # scan on the submit path. A short clock-TTL keeps the boost
        # fresh at SLO timescales (burn windows are minutes) while the
        # admission path pays one clock read + compare.
        self.boost_ttl_s = 0.25
        self._boost_cache: Tuple[float, int] = (-1e18, 0)
        self._exact_cache: Tuple[float, bool] = (-1e18, False)

    # -- ingest ------------------------------------------------------------

    def observe(self, kind: str, status: str, latency_s: float,
                degraded: bool = False, weight: float = 1.0) -> None:
        """Record one resolved request. status: ok|error|timeout|
        rejected|cancelled (the ServeEvent vocabulary)."""
        t = (self.clock(), kind, status, latency_s, degraded, weight)
        with self._lock:
            if len(self._obs) == self._obs.maxlen:
                self._dropped += 1
            self._obs.append(t)

    # -- evaluation --------------------------------------------------------

    def _context(self) -> Tuple[float, List[tuple]]:
        """ONE deque snapshot trimmed to the outermost window, shared
        by every evaluation a report/export/boost pass makes. The
        copy-under-lock is the only contention with the dispatch
        thread's observe(), so it happens once per pass — not once per
        (objective x window x metric) as the naive per-window copy
        would (a /debug/slo scrape runs ~6 evaluations per
        objective)."""
        with self._lock:
            snap = list(self._obs)
        now = self.clock()
        cutoff = now - max(self.spec.slow_window_s,
                           self.spec.budget_window_s)
        # observations are appended in clock order; scan from the right
        out: List[tuple] = []
        for t in reversed(snap):
            if t[0] < cutoff:
                break
            out.append(t)
        return now, out

    def _window(self, ctx: Tuple[float, List[tuple]],
                window_s: float) -> List[tuple]:
        now, obs = ctx
        cutoff = now - window_s
        return [t for t in obs if t[0] >= cutoff]

    def _bad_fraction(self, obj: Objective, now: float,
                      obs: List[tuple],
                      window_s: float) -> Tuple[float, int]:
        """(bad fraction in [0, 1], sample count) for one objective
        over one window's observations."""
        if obj.query_kind:
            obs = [t for t in obs if t[1] == obj.query_kind]
        if obj.kind == "availability":
            n = len(obs)
            if n == 0:
                return 0.0, 0
            bad = sum(1 for t in obs if t[2] in BAD_STATUSES)
            return bad / n, n
        if obj.kind == "throughput":
            n = len(obs)
            # rate over the EFFECTIVE window: a just-started engine has
            # seen less than window_s of traffic, and dividing by the
            # full window would report a phantom shortfall
            eff = min(window_s, max(now - self._started_at, 1e-9))
            served = sum(t[5] for t in obs
                         if t[2] not in ("rejected", "cancelled"))
            rate = served / eff
            return max(0.0, 1.0 - rate / obj.min_per_s), n
        # latency / exactness evaluate over SERVED requests: an errored
        # request has no meaningful latency or exactness, and it is the
        # availability objective's job to charge it
        served = [t for t in obs if t[2] == "ok"]
        n = len(served)
        if n == 0:
            return 0.0, 0
        if obj.kind == "latency":
            bound = obj.threshold_ms / 1000.0
            bad = sum(1 for t in served if t[3] > bound)
        else:  # exactness
            bad = sum(1 for t in served if t[4])
        return bad / n, n

    def burn_rates(self, obj: Objective, _ctx=None) -> dict:
        """{'fast': ..., 'slow': ..., 'n_fast': ..., 'n_slow': ...} —
        burn = bad_fraction / budget, 1.0 = spending exactly at goal."""
        ctx = _ctx if _ctx is not None else self._context()
        out = {}
        for label, window_s in (("fast", self.spec.fast_window_s),
                                ("slow", self.spec.slow_window_s)):
            bad, n = self._bad_fraction(
                obj, ctx[0], self._window(ctx, window_s), window_s)
            out[label] = bad / obj.budget
            out[f"n_{label}"] = n
        return out

    def budget_remaining(self, obj: Objective, _ctx=None) -> float:
        ctx = _ctx if _ctx is not None else self._context()
        window_s = self.spec.budget_window_s
        bad, _n = self._bad_fraction(
            obj, ctx[0], self._window(ctx, window_s), window_s)
        return max(0.0, 1.0 - bad / obj.budget)

    def breaching(self, _ctx=None) -> List[str]:
        """Objectives whose fast AND slow burn exceed the threshold
        (the multi-window rule: sustained, not a blip) with enough
        samples to mean anything."""
        ctx = _ctx if _ctx is not None else self._context()
        out = []
        for name, obj in self.spec.objectives.items():
            rates = self.burn_rates(obj, _ctx=ctx)
            if (rates["fast"] > self.spec.burn_threshold
                    and rates["slow"] > self.spec.burn_threshold
                    and rates["n_fast"] >= obj.min_count):
                out.append(name)
        return out

    def degrade_boost(self) -> int:
        """The ladder input (QueryService.degrade_level): 2 when a
        degrade-marked objective is breaching with its budget fully
        spent, 1 when merely breaching, else 0. Cached for
        `boost_ttl_s` of engine-clock time — admission calls this per
        request and must not pay a window walk each time."""
        now = self.clock()
        cached_at, value = self._boost_cache
        if now - cached_at < self.boost_ttl_s:
            return value
        ctx = self._context()
        boost = 0
        for name in self.breaching(_ctx=ctx):
            obj = self.spec.objectives[name]
            if not obj.degrade:
                continue
            if self.budget_remaining(obj, _ctx=ctx) <= 0.0:
                boost = 2
                break
            boost = 1
        self._boost_cache = (now, boost)
        return boost

    def exactness_spent(self) -> bool:
        """True when any EXACTNESS objective's error budget is fully
        spent — the approximate-answer tier's governor (docs/SERVING.md
        "Approximate answers"): sketch-served answers observe as
        degraded, so they spend this budget; once it is gone the serve
        layer strips tolerance hints and traffic moves to the EXACT
        path until the budget window recovers. Same clock-TTL cache
        discipline as degrade_boost (admission consults this per
        tolerant request)."""
        now = self.clock()
        cached_at, value = self._exact_cache
        if now - cached_at < self.boost_ttl_s:
            return value
        ctx = self._context()
        spent = False
        for obj in self.spec.objectives.values():
            if obj.kind != "exactness":
                continue
            rates = self.burn_rates(obj, _ctx=ctx)
            if (rates["n_slow"] >= obj.min_count
                    and self.budget_remaining(obj, _ctx=ctx) <= 0.0):
                spent = True
                break
        self._exact_cache = (now, spent)
        return spent

    # -- export ------------------------------------------------------------

    def export_gauges(self) -> None:
        """Refresh `slo.budget_remaining{objective}` and
        `slo.burn_rate{objective,window}` in the shared registry
        (called from the service's pre-scrape hook)."""
        from geomesa_tpu.utils.metrics import metrics

        ctx = self._context()
        for name, obj in self.spec.objectives.items():
            rates = self.burn_rates(obj, _ctx=ctx)
            metrics.gauge("slo.budget_remaining",
                          self.budget_remaining(obj, _ctx=ctx),
                          objective=name)
            metrics.gauge("slo.burn_rate", rates["fast"],
                          objective=name, window="fast")
            metrics.gauge("slo.burn_rate", rates["slow"],
                          objective=name, window="slow")

    def report(self) -> dict:
        """The /debug/slo document. One `_context()` walk serves every
        number in it — the breaching list and the ladder boost derive
        from the per-objective rates computed in the loop rather than
        re-walking the windows through breaching()/degrade_boost()."""
        ctx = self._context()
        objectives = {}
        breaching: List[str] = []
        boost = 0
        for name, obj in self.spec.objectives.items():
            rates = self.burn_rates(obj, _ctx=ctx)
            remaining = self.budget_remaining(obj, _ctx=ctx)
            is_breaching = (
                rates["fast"] > self.spec.burn_threshold
                and rates["slow"] > self.spec.burn_threshold
                and rates["n_fast"] >= obj.min_count)
            if is_breaching:
                breaching.append(name)
                if obj.degrade and boost < 2:
                    boost = 2 if remaining <= 0.0 else 1
            if rates["n_slow"] < obj.min_count:
                state = "insufficient-data"
            elif remaining <= 0.0:
                state = "violated"
            elif (rates["fast"] > self.spec.burn_threshold
                    and rates["slow"] > self.spec.burn_threshold):
                state = "burning"
            else:
                state = "ok"
            doc = {
                "kind": obj.kind,
                "goal": obj.goal,
                "state": state,
                "burn_rate": {"fast": round(rates["fast"], 4),
                              "slow": round(rates["slow"], 4)},
                "samples": {"fast": rates["n_fast"],
                            "slow": rates["n_slow"]},
                "budget_remaining": round(remaining, 4),
                "degrade": obj.degrade,
            }
            if obj.kind == "latency":
                doc["threshold_ms"] = obj.threshold_ms
            if obj.query_kind:
                doc["query_kind"] = obj.query_kind
            if obj.kind == "throughput":
                doc["min_per_s"] = obj.min_per_s
                if obj.pts_per_query:
                    doc["min_pts_per_s"] = (obj.min_per_s
                                            * obj.pts_per_query)
            objectives[name] = doc
        with self._lock:
            held, dropped = len(self._obs), self._dropped
        return {
            "enabled": True,
            "windows": {"fast_s": self.spec.fast_window_s,
                        "slow_s": self.spec.slow_window_s,
                        "budget_s": self.spec.budget_window_s},
            "burn_threshold": self.spec.burn_threshold,
            "objectives": objectives,
            "breaching": breaching,
            "degrade_boost": boost,
            "observations": {"held": held, "dropped": dropped},
        }


def render_slo(report: dict) -> str:
    """Human-readable /debug/slo summary (`gmtpu top`, docs)."""
    if not report.get("enabled"):
        return "slo: no spec loaded"
    lines = [
        f"slo: fast {report['windows']['fast_s']:g}s / slow "
        f"{report['windows']['slow_s']:g}s, burn threshold "
        f"{report['burn_threshold']:g}x"]
    for name, o in report["objectives"].items():
        lines.append(
            f"  {name:<20} {o['kind']:<13} {o['state']:<18} "
            f"burn {o['burn_rate']['fast']:.2f}x/"
            f"{o['burn_rate']['slow']:.2f}x  "
            f"budget {o['budget_remaining'] * 100:.1f}%")
    if report["breaching"]:
        lines.append(f"  BREACHING: {', '.join(report['breaching'])} "
                     f"(ladder boost {report['degrade_boost']})")
    return "\n".join(lines)
