"""Trace + metrics exporters: Perfetto JSON, JSON-lines, and the live
HTTP endpoint behind `gmtpu serve --metrics-port`.

Three consumers, three formats:

- **Offline flame views**: `to_perfetto()` emits Chrome/Perfetto
  `trace_event` JSON (`{"traceEvents": [...]}` with `ph:"X"` complete
  events) — load it at ui.perfetto.dev or chrome://tracing. Each query
  trace becomes one "process" row (pid = trace sequence, labelled with
  the trace name + id) with one track per OS thread, so nesting renders
  as a flame graph without any parent bookkeeping on the viewer's side.
  Span/parent ids ride in `args` so a dump re-parses losslessly
  (`from_perfetto()` — the round-trip the tests assert).
- **Streaming**: `write_jsonl()` — one JSON document per completed
  trace, the same shape `FlightRecorder.record` stores.
- **Live scrape**: `MetricsServer`, a stdlib `http.server` on a daemon
  thread serving `/metrics` (Prometheus text), `/healthz` (JSON
  liveness), `/debug/traces` (Perfetto JSON of the flight recorder),
  `/debug/stats` (the JSON the `gmtpu top` terminal view polls),
  `/debug/gap` (the dispatch-gap report over recorded traces),
  `/debug/slo` (the SLO engine's objective/burn report — telemetry/
  slo.py), `/debug/approx` (approximate-tier shares + result-cache
  counters — docs/SERVING.md "Approximate answers") and `/debug/prof`
  (the continuous profiler's lifetime distributions —
  telemetry/prof.py). No new dependencies: ThreadingHTTPServer + the
  shared metrics registry.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Dict, Iterable, List, Optional

from geomesa_tpu.telemetry.trace import Span

__all__ = ["to_perfetto", "from_perfetto", "write_jsonl", "MetricsServer"]


# -- Perfetto / Chrome trace_event -----------------------------------------


def _trace_doc(trace) -> dict:
    """Accept a Trace or its to_json() dict."""
    return trace if isinstance(trace, dict) else trace.to_json()


def to_perfetto(traces: Iterable) -> dict:
    """Chrome trace_event JSON for a set of query traces. Timestamps are
    microseconds from the process perf_counter epoch (all traces share
    it, so cross-query overlap — coalescing windows, queue contention —
    lines up on one timeline)."""
    events: List[dict] = []
    for pid, trace in enumerate(map(_trace_doc, traces), start=1):
        label = f"{trace.get('name', 'trace')} {trace.get('trace_id', '')}"
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": label.strip()},
        })
        spans = [trace["root"]] + list(trace.get("spans", ()))
        for s in spans:
            args = {
                "span_id": s["id"],
                "parent_id": s.get("parent"),
                "trace_id": trace.get("trace_id"),
            }
            if s.get("attrs"):
                args.update(s["attrs"])
            events.append({
                "ph": "X",
                "name": s["name"],
                "cat": "gmtpu",
                "pid": pid,
                "tid": s.get("thread", 0),
                "ts": s["t0_ns"] / 1000.0,
                "dur": max(s["t1_ns"] - s["t0_ns"], 0) / 1000.0,
                "args": args,
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def from_perfetto(doc: dict) -> List[dict]:
    """Re-parse a `to_perfetto()` document back into trace dicts (the
    recorder's storage shape). Spans regroup by the trace_id each event
    carries in args; the root is the span with no parent."""
    by_trace: Dict[str, List[dict]] = {}
    names: Dict[str, str] = {}
    for e in doc.get("traceEvents", ()):
        if e.get("ph") != "X":
            continue
        args = e.get("args", {})
        tid = args.get("trace_id")
        if tid is None:
            continue
        t0 = int(round(e["ts"] * 1000.0))
        span = {
            "name": e["name"],
            "id": args["span_id"],
            "parent": args.get("parent_id"),
            "t0_ns": t0,
            "t1_ns": t0 + int(round(e.get("dur", 0) * 1000.0)),
            "thread": e.get("tid", 0),
        }
        extra = {k: v for k, v in args.items()
                 if k not in ("span_id", "parent_id", "trace_id")}
        if extra:
            span["attrs"] = extra
        by_trace.setdefault(tid, []).append(span)
        if span["parent"] is None:
            names[tid] = e["name"]
    out = []
    for tid, spans in by_trace.items():
        root = next((s for s in spans if s["parent"] is None), None)
        rest = [s for s in spans if s is not root]
        out.append({
            "trace_id": tid,
            "name": names.get(tid, "trace"),
            "root": root,
            "spans": rest,
        })
    return out


def write_jsonl(traces: Iterable, write: Callable[[str], None]) -> int:
    """One JSON line per trace via `write`; returns the line count."""
    n = 0
    for trace in traces:
        write(json.dumps(_trace_doc(trace)) + "\n")
        n += 1
    return n


# -- live HTTP endpoint ----------------------------------------------------


class MetricsServer:
    """`/metrics` + `/healthz` + `/debug/*` on a daemon thread.

    `stats_fn` (optional) supplies the serving layer's live counters
    (`QueryService.stats()`); `pre_scrape` (optional) runs before each
    /metrics render so point-in-time gauges (queue depth, breaker
    states, quarantine size) are fresh at scrape time rather than
    last-update time. Both are called on the HTTP thread — they must be
    cheap and thread-safe, which `stats()`/gauge writes are."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 stats_fn: Optional[Callable[[], dict]] = None,
                 pre_scrape: Optional[Callable[[], None]] = None,
                 recorder=None,
                 slo_fn: Optional[Callable[[], dict]] = None):
        self.host = host
        self.port = port
        self.stats_fn = stats_fn
        self.pre_scrape = pre_scrape
        # /debug/slo provider (QueryService passes its engine's report;
        # None renders a typed "no spec loaded" document instead of 404
        # so dashboards can probe for SLO support)
        self.slo_fn = slo_fn
        if recorder is None:
            from geomesa_tpu.telemetry.recorder import RECORDER
            recorder = RECORDER
        self.recorder = recorder
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        from time import monotonic

        self._started_at = monotonic()

    # handlers return (status, content_type, body-bytes)

    def _route(self, path: str):
        from time import monotonic

        if path == "/metrics":
            if self.pre_scrape is not None:
                try:
                    self.pre_scrape()
                except Exception:
                    pass  # a scrape must degrade, not 500, on hook bugs
            from geomesa_tpu.utils.metrics import metrics

            return (200, "text/plain; version=0.0.4",
                    metrics.to_prometheus().encode())
        if path == "/healthz":
            doc = {"ok": True,
                   "uptime_s": round(monotonic() - self._started_at, 3),
                   # the BOUND port (port=0 requests an ephemeral one):
                   # a prober that reached us learns the canonical
                   # address other tools should use
                   "endpoint": {"host": self.host, "port": self.port}}
            if self.stats_fn is not None:
                try:
                    doc["serve"] = self.stats_fn()
                except Exception as e:
                    doc["ok"] = False
                    doc["error"] = str(e)
            return (200 if doc["ok"] else 503, "application/json",
                    json.dumps(doc).encode())
        if path == "/debug/traces":
            doc = to_perfetto(self.recorder.traces())
            return (200, "application/json", json.dumps(doc).encode())
        if path == "/debug/stats":
            return (200, "application/json",
                    json.dumps(self._debug_stats()).encode())
        if path == "/debug/gap":
            from geomesa_tpu.telemetry.gap import gap_report

            doc = gap_report(self.recorder.traces())
            return (200, "application/json", json.dumps(doc).encode())
        if path == "/debug/slo":
            doc = ({"enabled": False} if self.slo_fn is None
                   else self.slo_fn())
            return (200, "application/json", json.dumps(doc).encode())
        if path == "/debug/approx":
            # serving-tier shares (docs/SERVING.md "Approximate
            # answers"): sketch vs cached vs exact, the result-cache
            # hit/miss/evict counters, and whether the SLO exactness
            # governor currently allows sketch serving
            doc = {"enabled": False}
            if self.stats_fn is not None:
                try:
                    stats = self.stats_fn()
                    doc = dict(stats.get("approx") or {"enabled": False})
                    tiers = doc.get("tiers") or {}
                    total = sum(tiers.values())
                    if total:
                        doc["shares"] = {
                            k: round(v / total, 4)
                            for k, v in tiers.items()}
                    if "cache" in stats:
                        doc["cache"] = stats["cache"]
                except Exception as e:
                    doc = {"enabled": False, "error": str(e)}
            return (200, "application/json", json.dumps(doc).encode())
        if path == "/debug/prof":
            from geomesa_tpu.telemetry.prof import PROFILER

            # samples ride along (bounded: <= 256 per reservoir) so a
            # saved /debug/prof document is directly comparable by the
            # sentinel's distribution-overlap test
            doc = PROFILER.snapshot(include_samples=True)
            return (200, "application/json", json.dumps(doc).encode())
        return (404, "text/plain", b"not found\n")

    def _debug_stats(self) -> dict:
        """The `gmtpu top` payload: metrics registry snapshot + serve
        stats + breaker states + recorder occupancy, one JSON doc."""
        from geomesa_tpu.utils.metrics import metrics

        doc: dict = {"metrics": json.loads(metrics.to_json()),
                     "endpoint": {"host": self.host, "port": self.port,
                                  "url": self.url}}
        if self.stats_fn is not None:
            try:
                doc["serve"] = self.stats_fn()
            except Exception as e:
                doc["serve_error"] = str(e)
        try:
            from geomesa_tpu.faults import BREAKERS

            doc["breakers"] = BREAKERS.states()
        except Exception:
            doc["breakers"] = {}
        doc["recorder"] = self.recorder.stats()
        return doc

    def start(self) -> int:
        """Bind and serve; returns the actual port (port=0 lets the OS
        pick — the tests and smoke use that)."""
        if self._httpd is not None:
            return self.port
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    status, ctype, body = server._route(self.path)
                except Exception as e:  # noqa: BLE001 — 500, not a crash
                    status, ctype = 500, "text/plain"
                    body = f"error: {e}\n".encode()
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # quiet: stderr is for
                pass                            # the serve loop's use

        # gt: waive GT27
        # (deliberate per-process bind: every host of a pod exposes its
        # own metrics endpoint — scrape configs enumerate hosts; the
        # one-box multi-process smoke does not start the exporter)
        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.5},
            name="gmtpu-metrics-http", daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"
