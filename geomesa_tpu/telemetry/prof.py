"""Continuous serve profiler: always-on, bounded-cost trace folding.

The flight recorder keeps the last 256 traces; the gap report answers
"where did time go" over exactly that window. A fleet needs the same
attribution over the process LIFETIME at a fixed memory bound — that is
this module: every completed trace folds into reservoir-sampled
per-phase / per-kernel / per-shard distributions the moment the serve
layer records it, so `gmtpu prof` (and `/debug/prof`) answer from
hours of traffic, not the last few seconds.

What one fold extracts (a single pass over the trace's span dicts):

- **per-phase**: duration reservoir + count/total per span name (admit,
  queue.wait, dispatch, prepare, device.transfer, kernel.dispatch,
  device.sync, respond, ...). Riders adopt copies of the shared window
  spans with span ids PRESERVED, so the fold dedups device/dispatch
  spans by id against a bounded recently-seen set — N riders never
  count one kernel N times.
- **per-kernel family**: `kernel.dispatch` spans carry a `kernel` attr
  (filter.mask, knn_sparse, knn_mesh, ...); device time folds per
  family, and the enclosing dispatch window's host gap (window minus
  device-phase time) folds alongside — the device-vs-host-gap split per
  kernel family that BENCH hand-measured, now continuous.
- **per-shard**: device-phase spans stamped with owning `shards` (the
  PR-9 mesh lanes) accumulate per shard id; the report derives lane
  utilization shares and an imbalance ratio (max/mean device time — a
  slow chip reads as ITS lane, not a fleet-wide average).
- **pipeline overlap**: a streaming estimate over dispatch windows in
  completion order — each new window interval is compared against a
  small ring of recent windows, accumulating overlapped time and a
  windows-in-flight high-water. This deliberately trades exactness for
  O(1) per fold; the gap report remains the exact (recorder-window)
  number, and the two are cross-checked in tests.

Cost contract (asserted in tests like the tracer's): `fold()` is a
single span-list pass with per-span dict lookups and one reservoir
offer — budgeted vs the per-trace span count; `maybe_fold()` with the
profiler disabled is one attribute read. Reservoirs are fixed-size
(algorithm R), so memory is bounded regardless of uptime.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional

__all__ = ["Reservoir", "ContinuousProfiler", "PROFILER", "render_prof"]

# ring.slot is the persistent serve loop's slot write — device-facing
# like device.transfer (docs/SERVING.md "Persistent serve loop"); its
# kernel family (knn_ring) folds from the kernel.dispatch attr as usual
DEVICE_PHASES = ("kernel.dispatch", "device.sync", "device.transfer",
                 "ring.slot")
_DEVICE_SET = frozenset(DEVICE_PHASES)
RESERVOIR_K = 256
_SEEN_CAP = 4096          # recently-seen span ids (rider dedup window)
_WINDOW_RING = 8          # recent dispatch windows for overlap estimate


class Reservoir:
    """Fixed-size uniform sample (algorithm R) + count/total. Not
    thread-safe on its own — the profiler folds under one lock."""

    __slots__ = ("k", "n", "total", "samples", "_random")

    def __init__(self, k: int = RESERVOIR_K, seed: int = 0):
        self.k = k
        self.n = 0
        self.total = 0.0
        self.samples: List[float] = []
        # bound method, not randrange: the fold budget is single-digit
        # microseconds per trace and randrange() alone costs ~0.6µs —
        # `int(random() * n)` is the classic algorithm-R form and ~8x
        # cheaper (the float truncation bias at 2^53 is irrelevant at
        # reservoir scale)
        self._random = random.Random(seed).random

    def add(self, v: float) -> None:
        # gt: waive GT12
        # (caller-holds-lock: every Reservoir lives inside ONE
        # ContinuousProfiler, and add()/snapshot() run exclusively
        # under that profiler's _lock — a per-reservoir lock would
        # re-lock the same critical section per span)
        self.n += 1
        # gt: waive GT12
        # (same: guarded by the owning profiler's _lock)
        self.total += v
        samples = self.samples
        if len(samples) < self.k:
            # gt: waive GT12
            # (same: guarded by the owning profiler's _lock)
            samples.append(v)
        else:
            j = int(self._random() * self.n)
            if j < self.k:
                samples[j] = v

    def quantile(self, q: float) -> float:
        if not self.samples:
            return 0.0
        s = sorted(self.samples)
        i = min(int(q * len(s)), len(s) - 1)
        return s[i]

    def snapshot(self, include_samples: bool = False) -> dict:
        s = sorted(self.samples)

        def q(p):
            return s[min(int(p * len(s)), len(s) - 1)] if s else 0.0

        doc = {
            "n": self.n,
            "total_ms": round(self.total, 3),
            "mean_ms": round(self.total / self.n, 4) if self.n else 0.0,
            "p50_ms": round(q(0.50), 4),
            "p90_ms": round(q(0.90), 4),
            "p99_ms": round(q(0.99), 4),
        }
        if include_samples:
            doc["samples_ms"] = [round(v, 4) for v in s]
        return doc


class ContinuousProfiler:
    """The process-wide aggregator behind `/debug/prof` and
    `gmtpu prof`. Disabled by default; `enable()` makes the recorder
    fold every trace it stores (`FlightRecorder.record` calls
    `maybe_fold`), `disable()` restores the one-attribute-read no-op
    path. `reset()` drops accumulated state (bench runs isolate their
    measured window with it)."""

    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()
        self._traces = 0
        self._phases: Dict[str, Reservoir] = {}
        self._kernels: Dict[str, Dict[str, Reservoir]] = {}
        self._shards: Dict[str, List[float]] = {}   # sid -> [count, ms]
        self._seen: Dict[tuple, None] = {}          # insertion-ordered set
        # streaming pipeline-overlap estimate state
        self._recent_windows: List[tuple] = []      # (t0_ns, t1_ns)
        self._overlap_ns = 0
        self._window_ns = 0
        self._windows = 0
        self._inflight_max = 0

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._traces = 0
            self._phases.clear()
            self._kernels.clear()
            self._shards.clear()
            self._seen.clear()
            self._recent_windows.clear()
            self._overlap_ns = 0
            self._window_ns = 0
            self._windows = 0
            self._inflight_max = 0

    # -- folding -----------------------------------------------------------

    def maybe_fold(self, doc: Optional[dict]) -> None:
        """The recorder's hook: one attribute read when disabled."""
        if self.enabled and doc is not None:
            self.fold(doc)

    def fold(self, doc: dict) -> None:
        """Fold one completed trace (recorder storage shape). One pass
        over the span dicts; rider-adopted copies of shared window
        spans dedup by (process, span id) against a bounded
        recently-seen set."""
        spans = doc.get("spans")
        if not spans:
            return
        root = doc.get("root")
        proc = str(doc.get("trace_id", "")).split("-", 1)[0]
        # the hot loop binds everything it touches to locals — at the
        # single-digit-µs budget every self./global lookup shows up
        device_set = _DEVICE_SET
        with self._lock:
            self._traces += 1
            phases = self._phases
            phases_get = phases.get
            seen = self._seen
            if root is not None and root.get("t1_ns", 0):
                # the root is the request's end-to-end wall time — one
                # per request (riders own their roots), no dedup needed
                res = phases_get("query")
                if res is None:
                    res = phases["query"] = Reservoir()
                res.add(max(root["t1_ns"] - root["t0_ns"], 0) / 1e6)
            dispatch_windows = None
            device_in_window = 0
            kernel_fams = None
            for s in spans:
                key = (proc, s["id"])
                if key in seen:
                    continue
                seen[key] = None
                name = s["name"]
                dur_ns = s["t1_ns"] - s["t0_ns"]
                if dur_ns < 0:
                    dur_ns = 0
                dur_ms = dur_ns / 1e6
                res = phases_get(name)
                if res is None:
                    res = phases[name] = Reservoir()
                res.add(dur_ms)
                if name == "dispatch":
                    if dispatch_windows is None:
                        dispatch_windows = []
                    dispatch_windows.append((s["t0_ns"], s["t1_ns"]))
                elif name in device_set:
                    device_in_window += dur_ns
                    attrs = s.get("attrs")
                    if attrs:
                        if name == "kernel.dispatch":
                            fam = attrs.get("kernel")
                            if fam:
                                if kernel_fams is None:
                                    kernel_fams = {}
                                kernel_fams[fam] = kernel_fams.get(
                                    fam, 0.0) + dur_ms
                        ids = attrs.get("shards")
                        if ids:
                            for sid in str(ids).split(","):
                                lane = self._shards.get(sid)
                                if lane is None:
                                    lane = self._shards[sid] = [0, 0.0]
                                lane[0] += 1
                                lane[1] += dur_ms
            if len(seen) > _SEEN_CAP:
                # bounded dedup window: drop the oldest half. Rider
                # adoption happens within one dispatch window, so the
                # shared ids arrive near-adjacently — a 4096-entry
                # window dedups them with room to spare.
                for k in list(seen)[:_SEEN_CAP // 2]:
                    del seen[k]
            if dispatch_windows:
                self._fold_windows(dispatch_windows, device_in_window,
                                   kernel_fams)

    def _fold_windows(self, windows, device_ns: int, kernel_fams) -> None:
        """Per-kernel device/gap split + the streaming overlap
        estimate. Called under the lock from fold(); same local-binding
        discipline as the span loop — this runs once per window, and
        the ring comparison is the fold's second-hottest stretch."""
        win_ns = 0
        for t0, t1 in windows:
            if t1 > t0:
                win_ns += t1 - t0
        gap_ns = win_ns - device_ns
        if kernel_fams:
            # the window's host gap is attributed to every kernel
            # family that ran in it, weighted by its device share —
            # a per-family "what would speeding this kernel up buy"
            gap_ms = (gap_ns if gap_ns > 0 else 0) / 1e6
            kernels = self._kernels
            total_dev = sum(kernel_fams.values()) or 1.0
            for fam, dev_ms in kernel_fams.items():
                rec = kernels.get(fam)
                if rec is None:
                    rec = kernels[fam] = {
                        "device": Reservoir(), "gap": Reservoir()}
                rec["device"].add(dev_ms)
                rec["gap"].add(gap_ms * dev_ms / total_dev)
        recent = self._recent_windows
        overlap_ns = 0
        windows_n = 0
        inflight_max = self._inflight_max
        for t0, t1 in windows:
            if t1 <= t0:
                continue
            windows_n += 1
            inflight = 1
            win_overlap = 0
            for r0, r1 in recent:
                lo = t0 if t0 > r0 else r0
                hi = t1 if t1 < r1 else r1
                if hi > lo:
                    win_overlap += hi - lo
                    inflight += 1
            # clamp the pairwise sum to THIS window's extent: at depth
            # >2, three concurrent windows give 2x pairwise overlap per
            # window, and an unclamped sum would push overlap_share
            # past 1.0 ("150% of window time" is not a number an
            # operator can read)
            dur = t1 - t0
            overlap_ns += win_overlap if win_overlap < dur else dur
            if inflight > inflight_max:
                inflight_max = inflight
            recent.append((t0, t1))
            if len(recent) > _WINDOW_RING:
                del recent[0]
        self._windows += windows_n
        self._window_ns += win_ns
        self._overlap_ns += overlap_ns
        self._inflight_max = inflight_max

    # -- reporting ---------------------------------------------------------

    def snapshot(self, include_samples: bool = False) -> dict:
        """The /debug/prof document (and the sentinel's input)."""
        with self._lock:
            phases = {n: r.snapshot(include_samples)
                      for n, r in sorted(self._phases.items())}
            kernels = {
                fam: {"device": rec["device"].snapshot(include_samples),
                      "gap": rec["gap"].snapshot(include_samples)}
                for fam, rec in sorted(self._kernels.items())}
            lanes = {sid: {"count": int(c), "device_ms": round(ms, 3)}
                     for sid, (c, ms) in sorted(self._shards.items())}
            windows = self._windows
            window_ms = self._window_ns / 1e6
            overlap_ms = self._overlap_ns / 1e6
            inflight_max = self._inflight_max
            traces = self._traces
        imbalance = 0.0
        if lanes:
            vals = [v["device_ms"] for v in lanes.values()]
            mean = sum(vals) / len(vals)
            imbalance = max(vals) / mean if mean > 0 else 0.0
        return {
            "enabled": self.enabled,
            "traces": traces,
            "phases": phases,
            "kernels": kernels,
            "shards": {"lanes": lanes,
                       "imbalance_ratio": round(imbalance, 3)},
            "pipeline": {
                "windows": windows,
                "window_ms": round(window_ms, 3),
                "overlap_ms": round(overlap_ms, 3),
                "overlap_share": round(overlap_ms / window_ms, 4)
                if window_ms else 0.0,
                "windows_in_flight_max": inflight_max,
            },
        }


def render_prof(doc: dict) -> str:
    """`gmtpu prof` text output."""
    lines = [
        f"continuous profile over {doc['traces']} trace(s)"
        + ("" if doc.get("enabled", True) else " (profiler now off)"),
        f"{'phase':<18}{'n':>8}{'total ms':>12}{'p50 ms':>10}"
        f"{'p90 ms':>10}{'p99 ms':>10}",
    ]
    for name, p in doc["phases"].items():
        lines.append(
            f"{name:<18}{p['n']:>8}{p['total_ms']:>12.2f}"
            f"{p['p50_ms']:>10.3f}{p['p90_ms']:>10.3f}"
            f"{p['p99_ms']:>10.3f}")
    if doc["kernels"]:
        lines.append("kernel families (device ms vs attributed host "
                     "gap ms per window):")
        for fam, rec in doc["kernels"].items():
            d, g = rec["device"], rec["gap"]
            lines.append(
                f"  {fam:<20} n={d['n']:<7} device p50 "
                f"{d['p50_ms']:.3f} / p99 {d['p99_ms']:.3f}   "
                f"gap p50 {g['p50_ms']:.3f}")
    lanes = doc["shards"]["lanes"]
    if lanes:
        parts = ", ".join(f"shard {sid}: {v['device_ms']:.1f} ms"
                          f"/{v['count']}" for sid, v in lanes.items())
        lines.append(
            f"shard lanes: {parts} (imbalance "
            f"{doc['shards']['imbalance_ratio']:.2f}x)")
    p = doc["pipeline"]
    if p["windows"]:
        lines.append(
            f"pipeline: {p['windows']} window(s), overlap "
            f"{p['overlap_ms']:.1f} ms ({p['overlap_share'] * 100:.1f}% "
            f"of window time), up to {p['windows_in_flight_max']} in "
            f"flight (streaming estimate)")
    return "\n".join(lines)


# process-wide profiler: FlightRecorder.record() folds into it when
# enabled; MetricsServer serves its snapshot at /debug/prof
PROFILER = ContinuousProfiler()
