"""Perf-regression sentinel: committed baselines, typed verdicts.

BENCH numbers are hand-run snapshots; nothing fails when a PR slows the
serve hot path. The sentinel closes that gap: `bench-serve
--record-baseline` writes the measured profile (continuous-profiler
distributions + the load report's latency samples) to a committed
baseline file (`BASELINE_SERVE.json`), and `gmtpu sentinel` /
`bench-serve --sentinel` compare a fresh profile against it, emitting a
typed verdict per metric and a nonzero exit on regression — wired into
the lint gate so CPU CI catches a slowed hot path before any TPU run.

Noise tolerance is the design center. Wall-clock medians on shared CI
hosts jitter; point p99s jitter worse. So a metric regresses only when
BOTH hold:

- the median ratio (current/baseline) exceeds `threshold` (default
  1.5x), and
- the central mass of the two sample distributions has stopped
  overlapping (`overlap` of the [p10, p90] intervals below
  `min_overlap`) — a shifted median WITHIN overlapping distributions
  is noise, not a regression.

Verdicts per metric: `ok`, `regressed`, `improved` (the same two-part
test in the other direction), `insufficient-data` (either side has
fewer than `min_n` samples — never silently pass/fail on thin
evidence). The run verdict is `regressed` iff any metric regressed.

Baselines are hardware-specific by nature; the committed file records
host metadata, and the lint-gate smoke never compares against it — the
smoke is self-relative (record → replay in one process → `ok`;
synthetic 3x slowdown on one phase → `regressed`), which is exactly
the property CI can assert on any machine.
"""

from __future__ import annotations

import json
import os
import platform
import time
from typing import Dict, List, Optional

__all__ = ["baseline_from_profile", "save_baseline", "load_baseline",
           "compare", "render_verdicts", "exit_code",
           "DEFAULT_BASELINE"]

DEFAULT_BASELINE = "BASELINE_SERVE.json"
VERDICTS = ("ok", "regressed", "improved", "insufficient-data")

# default thresholds: a 3x synthetic slowdown must always trip, run-
# to-run CI jitter (typically < 1.3x on medians) must never
DEFAULT_THRESHOLD = 1.5
DEFAULT_MIN_OVERLAP = 0.20
DEFAULT_MIN_N = 8


def baseline_from_profile(profile: dict,
                          latency_samples_ms: Optional[List[float]] = None,
                          extra: Optional[dict] = None,
                          extra_samples: Optional[
                              Dict[str, List[float]]] = None) -> dict:
    """Flatten a ContinuousProfiler snapshot (include_samples=True)
    into the baseline's metric table. `latency_samples_ms` adds the
    load report's end-to-end `serve.latency` samples — the headline
    the sentinel guards even when tracing is off. `extra_samples` adds
    named latency-vector families wholesale (e.g. the approx bench's
    `approx.count.sketch` / `approx.count.exact` reservoirs — a
    regressed sketch path then fails CI like any other family)."""
    metrics: Dict[str, dict] = {}

    def put(name: str, snap: dict) -> None:
        samples = snap.get("samples_ms")
        if not samples:
            return
        metrics[name] = {
            "n": snap["n"],
            "median_ms": snap["p50_ms"],
            "samples_ms": samples,
        }

    for phase, snap in (profile.get("phases") or {}).items():
        put(f"phase.{phase}", snap)
    for fam, rec in (profile.get("kernels") or {}).items():
        put(f"kernel.{fam}.device", rec["device"])
    if latency_samples_ms:
        s = sorted(latency_samples_ms)
        metrics["serve.latency"] = {
            "n": len(s),
            "median_ms": s[len(s) // 2],
            "samples_ms": [round(v, 4) for v in s],
        }
    for name, samples in (extra_samples or {}).items():
        if not samples:
            continue
        s = sorted(samples)
        metrics[name] = {
            "n": len(s),
            "median_ms": s[len(s) // 2],
            "samples_ms": [round(v, 4) for v in s],
        }
    doc = {
        "version": 1,
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "host": {"platform": platform.platform(),
                 "machine": platform.machine(),
                 "python": platform.python_version()},
        "metrics": metrics,
    }
    if extra:
        doc["context"] = extra
    return doc


def save_baseline(path: str, doc: dict) -> str:
    from geomesa_tpu.parallel.distributed import is_coordinator

    if not is_coordinator():
        # multi-host: one BASELINE file, one writer (GT27) — verdicts
        # compare against shared history, which process 0 curates
        return path
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_baseline(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("version") != 1 or "metrics" not in doc:
        raise ValueError(
            f"{path} is not a v1 sentinel baseline (record one with "
            f"`gmtpu bench-serve --record-baseline`)")
    return doc


def _central_interval(samples: List[float]) -> tuple:
    s = sorted(samples)
    n = len(s)
    lo = s[min(int(0.10 * n), n - 1)]
    hi = s[min(int(0.90 * n), n - 1)]
    return lo, hi


def _overlap(a: List[float], b: List[float]) -> float:
    """Overlap of the two samples' central [p10, p90] intervals as a
    fraction of their combined span, in [0, 1]. Degenerate (zero-width)
    intervals compare by containment: a constant distribution inside
    the other's central interval overlaps fully."""
    alo, ahi = _central_interval(a)
    blo, bhi = _central_interval(b)
    lo, hi = max(alo, blo), min(ahi, bhi)
    span = max(ahi, bhi) - min(alo, blo)
    if span <= 0.0:
        return 1.0  # both degenerate at the same point
    if hi < lo:
        return 0.0
    inter = hi - lo
    if inter == 0.0:
        # touching or a zero-width interval inside the other
        return 1.0 if (alo == ahi or blo == bhi) else 0.0
    return inter / span


def _median(samples: List[float]) -> float:
    s = sorted(samples)
    return s[len(s) // 2]


def compare(baseline: dict, current: dict,
            threshold: float = DEFAULT_THRESHOLD,
            min_overlap: float = DEFAULT_MIN_OVERLAP,
            min_n: int = DEFAULT_MIN_N) -> dict:
    """Verdict per metric family over the union of baseline and current
    metric tables (both in the `baseline_from_profile` shape, or a raw
    {name: {n, samples_ms}} table for `current`)."""
    base_m = baseline.get("metrics", baseline)
    cur_m = current.get("metrics", current)
    verdicts: Dict[str, dict] = {}
    for name in sorted(set(base_m) | set(cur_m)):
        b, c = base_m.get(name), cur_m.get(name)
        if (b is None or c is None
                or b.get("n", 0) < min_n or c.get("n", 0) < min_n
                or not b.get("samples_ms") or not c.get("samples_ms")):
            verdicts[name] = {
                "verdict": "insufficient-data",
                "baseline_n": (b or {}).get("n", 0),
                "current_n": (c or {}).get("n", 0),
            }
            continue
        bm = _median(b["samples_ms"])
        cm = _median(c["samples_ms"])
        ov = _overlap(b["samples_ms"], c["samples_ms"])
        if bm <= 0.0:
            # a zero-cost baseline phase cannot express a ratio; only a
            # clear distribution separation upward can regress it
            ratio = float("inf") if cm > 0.0 else 1.0
        else:
            ratio = cm / bm
        if ratio > threshold and ov < min_overlap:
            verdict = "regressed"
        elif ratio < 1.0 / threshold and ov < min_overlap:
            verdict = "improved"
        else:
            verdict = "ok"
        verdicts[name] = {
            "verdict": verdict,
            "median_ratio": round(ratio, 3) if ratio != float("inf")
            else "inf",
            "overlap": round(ov, 3),
            "baseline_median_ms": round(bm, 4),
            "current_median_ms": round(cm, 4),
        }
    counts = {v: 0 for v in VERDICTS}
    for d in verdicts.values():
        counts[d["verdict"]] += 1
    return {
        "thresholds": {"median_ratio": threshold,
                       "min_overlap": min_overlap, "min_n": min_n},
        "metrics": verdicts,
        "counts": counts,
        "regressed": counts["regressed"] > 0,
    }


def exit_code(report: dict, strict: bool = False) -> int:
    """1 on regression. `strict` additionally fails on any
    `insufficient-data` verdict — the guard against instrumentation
    loss (a renamed phase/kernel family stops being COMPARED, which
    must not read as green when the caller expects full coverage; the
    lint-gate smoke asserts zero insufficient-data on its identical
    replay for the same reason)."""
    if report.get("regressed"):
        return 1
    if strict and report.get("counts", {}).get("insufficient-data"):
        return 1
    return 0


def render_verdicts(report: dict) -> str:
    lines = [
        f"sentinel: {report['counts']['ok']} ok, "
        f"{report['counts']['regressed']} regressed, "
        f"{report['counts']['improved']} improved, "
        f"{report['counts']['insufficient-data']} insufficient-data "
        f"(threshold {report['thresholds']['median_ratio']:g}x median, "
        f"overlap < {report['thresholds']['min_overlap']:g})"]
    order = {"regressed": 0, "improved": 1, "ok": 2,
             "insufficient-data": 3}
    for name, d in sorted(report["metrics"].items(),
                          key=lambda kv: (order[kv[1]["verdict"]],
                                          kv[0])):
        if d["verdict"] == "insufficient-data":
            lines.append(
                f"  {d['verdict']:<18} {name:<28} "
                f"n={d['baseline_n']}/{d['current_n']}")
        else:
            lines.append(
                f"  {d['verdict']:<18} {name:<28} median "
                f"{d['baseline_median_ms']:.3f} -> "
                f"{d['current_median_ms']:.3f} ms "
                f"({d['median_ratio']}x, overlap {d['overlap']})")
    return "\n".join(lines)
