"""Dispatch-gap report: host-gap vs kernel-time attribution from spans.

ROADMAP item 2's finding — dispatch RTT (0.101s) exceeding net kernel
time (0.066s) — came from one hand-instrumented bench run. This module
derives the same attribution from any set of query traces, so every
traced `bench-serve` run (and `gmtpu trace --gap` over a flight-recorder
dump) reports exactly where the serve path's wall time went:

- **per-phase attribution**: total/mean/share for every span name
  (admit, queue.wait, dispatch, plan, residency, device.transfer,
  kernel.dispatch, device.sync, merge, respond, compile.stall, ...);
- **coverage**: how much of each query's wall time the direct root
  phases explain (the acceptance bar: ≥95% — unexplained time means an
  un-instrumented seam);
- **dispatch gap**: within the dispatch windows themselves, time spent
  in device-facing spans (kernel dispatch + sync + transfer) vs host
  work between them — the number the item-2 pipelining work must drive
  toward zero. Coalesced riders adopt *copies* of the shared window
  spans (same span ids), so dispatch-window aggregation dedups by
  span id: N riders never count one kernel N times.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Tuple

__all__ = ["gap_report", "render_gap", "DEVICE_PHASES"]

# span names that represent the device-facing part of a dispatch window;
# everything else inside the window is host work (the "gap")
DEVICE_PHASES = ("kernel.dispatch", "device.sync", "device.transfer")


def _doc(trace) -> dict:
    return trace if isinstance(trace, dict) else trace.to_json()


def _union_ns(intervals: List[Tuple[int, int]]) -> int:
    """Total covered length of possibly-overlapping [t0, t1) intervals."""
    if not intervals:
        return 0
    intervals.sort()
    total = 0
    cur0, cur1 = intervals[0]
    for t0, t1 in intervals[1:]:
        if t0 > cur1:
            total += cur1 - cur0
            cur0, cur1 = t0, t1
        else:
            cur1 = max(cur1, t1)
    total += cur1 - cur0
    return total


def gap_report(traces: Iterable) -> dict:
    docs = [_doc(t) for t in traces]
    docs = [d for d in docs if d.get("root")]
    phases: Dict[str, Dict[str, float]] = {}
    wall_ns = 0
    covered_ns = 0
    # dispatch-window aggregation, deduped by (process, span id):
    # riders adopt the lead's window spans with ids PRESERVED, so the
    # same (process, id) appearing in several traces is one span. Span
    # ids alone are per-process counters — trace ids are pid-qualified
    # precisely so merged multi-process dumps (replica fleets) stay
    # distinguishable, and the dedup key must follow suit.
    windows: Dict[tuple, dict] = {}    # (proc, dispatch span id) -> span
    window_children: Dict[tuple, List[dict]] = {}
    seen_span_ids = set()
    for d in docs:
        proc = str(d.get("trace_id", "")).split("-", 1)[0]
        root = d["root"]
        root_dur = max(root["t1_ns"] - root["t0_ns"], 0)
        wall_ns += root_dur
        spans = list(d.get("spans", ()))
        by_id = {s["id"]: s for s in spans}
        root_children = [s for s in spans
                         if s.get("parent") == root["id"]]
        covered_ns += _union_ns(
            [(s["t0_ns"], s["t1_ns"]) for s in root_children])
        for s in spans:
            if (proc, s["id"]) in seen_span_ids:
                continue  # adopted copy of a shared dispatch span
            seen_span_ids.add((proc, s["id"]))
            dur_ms = max(s["t1_ns"] - s["t0_ns"], 0) / 1e6
            p = phases.setdefault(
                s["name"], {"count": 0, "total_ms": 0.0})
            p["count"] += 1
            p["total_ms"] += dur_ms
            if s["name"] == "dispatch":
                windows[(proc, s["id"])] = s
        for s in spans:
            parent = by_id.get(s.get("parent"))
            while parent is not None:
                if parent["name"] == "dispatch":
                    window_children.setdefault(
                        (proc, parent["id"]), []).append(s)
                    break
                parent = by_id.get(parent.get("parent"))
    # dedupe window children (riders adopt copies with the same ids)
    exec_ns = sum(max(w["t1_ns"] - w["t0_ns"], 0)
                  for w in windows.values())
    device_ns = 0
    host_work_ns = 0
    for wid, w in windows.items():
        kids = {s["id"]: s for s in window_children.get(wid, ())}
        device_ns += _union_ns(
            [(s["t0_ns"], s["t1_ns"]) for s in kids.values()
             if s["name"] in DEVICE_PHASES])
        host_work_ns += _union_ns(
            [(s["t0_ns"], s["t1_ns"]) for s in kids.values()
             if s["name"] not in DEVICE_PHASES])
    gap_ns = max(exec_ns - device_ns, 0)
    for name, p in phases.items():
        p["mean_ms"] = p["total_ms"] / p["count"] if p["count"] else 0.0
        p["share"] = (p["total_ms"] * 1e6 / wall_ns) if wall_ns else 0.0
        p["total_ms"] = round(p["total_ms"], 3)
        p["mean_ms"] = round(p["mean_ms"], 4)
        p["share"] = round(p["share"], 4)
    return {
        "traces": len(docs),
        "wall_ms": round(wall_ns / 1e6, 3),
        "coverage": round(covered_ns / wall_ns, 4) if wall_ns else 0.0,
        "phases": dict(sorted(phases.items())),
        "dispatch_gap": {
            "windows": len(windows),
            "exec_ms": round(exec_ns / 1e6, 3),
            "device_ms": round(device_ns / 1e6, 3),
            "host_instrumented_ms": round(host_work_ns / 1e6, 3),
            "host_gap_ms": round(gap_ns / 1e6, 3),
            "gap_fraction": round(gap_ns / exec_ns, 4) if exec_ns else 0.0,
        },
    }


def render_gap(report: dict) -> str:
    """Human-readable gap report (`gmtpu trace --gap` default output)."""
    lines = [
        f"dispatch-gap report over {report['traces']} trace(s), "
        f"wall {report['wall_ms']:.1f} ms "
        f"(root-phase coverage {report['coverage'] * 100:.1f}%)",
        f"{'phase':<18}{'count':>7}{'total ms':>12}{'mean ms':>11}"
        f"{'share':>8}",
    ]
    for name, p in report["phases"].items():
        lines.append(
            f"{name:<18}{p['count']:>7}{p['total_ms']:>12.2f}"
            f"{p['mean_ms']:>11.3f}{p['share'] * 100:>7.1f}%")
    g = report["dispatch_gap"]
    lines.append(
        f"dispatch windows: {g['windows']} — exec {g['exec_ms']:.1f} ms, "
        f"device {g['device_ms']:.1f} ms, "
        f"host gap {g['host_gap_ms']:.1f} ms "
        f"({g['gap_fraction'] * 100:.1f}% of window time)")
    if g["windows"] and g["gap_fraction"] > 0.5:
        lines.append(
            "  NOTE: >50% of dispatch-window time is host gap — the "
            "path is dispatch-bound (ROADMAP item 2), not kernel-bound")
    return "\n".join(lines)
