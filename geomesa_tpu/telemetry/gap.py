"""Dispatch-gap report: host-gap vs kernel-time attribution from spans.

ROADMAP item 2's finding — dispatch RTT (0.101s) exceeding net kernel
time (0.066s) — came from one hand-instrumented bench run. This module
derives the same attribution from any set of query traces, so every
traced `bench-serve` run (and `gmtpu trace --gap` over a flight-recorder
dump) reports exactly where the serve path's wall time went:

- **per-phase attribution**: total/mean/share for every span name
  (admit, queue.wait, dispatch, prepare, plan, residency,
  device.transfer, kernel.dispatch, device.sync, merge, respond,
  compile.stall, ...);
- **coverage**: how much of each query's wall time the direct root
  phases explain (the acceptance bar: >=95% — unexplained time means an
  un-instrumented seam). Child intervals are clamped to the root's
  extent, so overlapping pipelined phases can never report >1.0;
- **dispatch gap**: within the dispatch windows, time spent in
  device-facing spans (kernel dispatch + sync + transfer) vs host work
  between them — the number the item-2 pipelining work drives toward
  zero. Coalesced riders adopt *copies* of the shared window spans
  (same span ids), so dispatch-window aggregation dedups by span id:
  N riders never count one kernel N times. Pipelined windows OVERLAP in
  wall time, so window/stage intervals aggregate by interval union per
  process, never by summing durations — the same second of overlapped
  transfer+kernel counts once (the pre-pipelining union double-counted
  it and could report coverage > 1.0);
- **pipeline**: how deep the pipelining actually ran — max windows in
  flight, total time >=2 windows were open, and how much transfer time
  overlapped OTHER windows' execution (the structural invariant CPU CI
  asserts in place of a TPU throughput number; docs/OBSERVABILITY.md).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Tuple

__all__ = ["gap_report", "render_gap", "DEVICE_PHASES"]

# span names that represent the device-facing part of a dispatch window;
# everything else inside the window is host work (the "gap").
# `ring.slot` is the persistent serve loop's slot write (docs/SERVING.md
# "Persistent serve loop") — a staged transfer by another name, so it
# counts as device-facing exactly like device.transfer.
DEVICE_PHASES = ("kernel.dispatch", "device.sync", "device.transfer",
                 "ring.slot")


def _doc(trace) -> dict:
    return trace if isinstance(trace, dict) else trace.to_json()


def _union_ns(intervals: List[Tuple[int, int]]) -> int:
    """Total covered length of possibly-overlapping [t0, t1) intervals."""
    merged = _merge(intervals)
    return sum(t1 - t0 for t0, t1 in merged)


def _merge(intervals: List[Tuple[int, int]]) -> List[Tuple[int, int]]:
    """Sorted, merged copy of possibly-overlapping [t0, t1) intervals."""
    if not intervals:
        return []
    out: List[Tuple[int, int]] = []
    for t0, t1 in sorted(intervals):
        if out and t0 <= out[-1][1]:
            if t1 > out[-1][1]:
                out[-1] = (out[-1][0], t1)
        else:
            out.append((t0, t1))
    return out


def _clamp(t0: int, t1: int, lo: int, hi: int):
    """[t0, t1) clipped to [lo, hi), or None when empty."""
    a, b = max(t0, lo), min(t1, hi)
    return (a, b) if b > a else None


def _overlap_ns(a: List[Tuple[int, int]], b: List[Tuple[int, int]]) -> int:
    """Covered length of union(a) ∩ union(b)."""
    am, bm = _merge(a), _merge(b)
    i = j = 0
    total = 0
    while i < len(am) and j < len(bm):
        lo = max(am[i][0], bm[j][0])
        hi = min(am[i][1], bm[j][1])
        if hi > lo:
            total += hi - lo
        if am[i][1] <= bm[j][1]:
            i += 1
        else:
            j += 1
    return total


def _max_concurrent(intervals: List[Tuple[int, int]]):
    """(max simultaneously-open intervals, ns with >=2 open, the
    merged [t0, t1) regions where >=2 are open). One sweep — the
    multi-open regions also drive the transfer-overlap attribution
    without a per-window quadratic rescan."""
    if not intervals:
        return 0, 0, []
    events = []
    for t0, t1 in intervals:
        events.append((t0, 1))
        events.append((t1, -1))
    events.sort()
    depth = best = 0
    multi_ns = 0
    multi: List[Tuple[int, int]] = []
    open_at = None
    prev = events[0][0]
    for t, d in events:
        if depth >= 2:
            multi_ns += t - prev
            if open_at is None:
                open_at = prev
        elif open_at is not None:
            if prev > open_at:
                multi.append((open_at, prev))
            open_at = None
        prev = t
        depth += d
        best = max(best, depth)
    if open_at is not None and prev > open_at:
        multi.append((open_at, prev))
    return best, multi_ns, _merge(multi)


def gap_report(traces: Iterable) -> dict:
    docs = [_doc(t) for t in traces]
    docs = [d for d in docs if d.get("root")]
    phases: Dict[str, Dict[str, float]] = {}
    wall_ns = 0
    covered_ns = 0
    # dispatch-window aggregation, deduped by (process, span id):
    # riders adopt the lead's window spans with ids PRESERVED, so the
    # same (process, id) appearing in several traces is one span. Span
    # ids alone are per-process counters — trace ids are pid-qualified
    # precisely so merged multi-process dumps (replica fleets) stay
    # distinguishable, and the dedup key must follow suit.
    windows: Dict[tuple, dict] = {}    # (proc, dispatch span id) -> span
    window_children: Dict[tuple, List[dict]] = {}
    seen_span_ids = set()
    # per-shard lane (docs/SERVING.md "Sharded serving"): device-facing
    # spans stamped with the owning shards (kernel.dispatch/device.sync
    # on a mesh service) aggregate per shard id, so a slow chip shows up
    # as ITS lane's total, not a fleet-wide average. Whole-mesh windows
    # credit every owning shard; shard-affinity windows credit one.
    shard_lanes: Dict[str, Dict[str, float]] = {}
    # ring-mode attribution (docs/OBSERVABILITY.md "Ring mode"): the
    # persistent serve loop's per-window cost splits into slot-wait
    # (ring.slot — the staged write into the ring), kernel (the one
    # pre-compiled dispatch, kernel.dispatch tagged knn_ring) and
    # harvest (the completer's combined read, device.sync tagged ring).
    # Aggregated over the same deduped span set as the phases table.
    ring = {"windows": 0, "slot_ms": 0.0, "kernel_ms": 0.0,
            "harvest_ms": 0.0}
    # vmapped-lane attribution (docs/SERVING.md "Standing queries"):
    # each subscribe.lane.eval span is one per-class batched dispatch
    # stamped with its class and row count — aggregated per class so a
    # hot lane (say, 8k dwithin rows) shows up as ITS class's total,
    # next to the fused remainder in the phases table.
    lane_evals: Dict[str, Dict[str, float]] = {}
    for d in docs:
        proc = str(d.get("trace_id", "")).split("-", 1)[0]
        root = d["root"]
        root_dur = max(root["t1_ns"] - root["t0_ns"], 0)
        wall_ns += root_dur
        spans = list(d.get("spans", ()))
        by_id = {s["id"]: s for s in spans}
        root_children = [s for s in spans
                         if s.get("parent") == root["id"]]
        # clamp to the root's extent: a pipelined window's deferred sync
        # can outlive the rider that adopted it, and coverage is a share
        # of THIS root's wall time — it must stay <= 1.0
        covered_ns += _union_ns([iv for s in root_children
                                 if (iv := _clamp(s["t0_ns"], s["t1_ns"],
                                                  root["t0_ns"],
                                                  root["t1_ns"]))])
        for s in spans:
            if (proc, s["id"]) in seen_span_ids:
                continue  # adopted copy of a shared dispatch span
            seen_span_ids.add((proc, s["id"]))
            dur_ms = max(s["t1_ns"] - s["t0_ns"], 0) / 1e6
            p = phases.setdefault(
                s["name"], {"count": 0, "total_ms": 0.0})
            p["count"] += 1
            p["total_ms"] += dur_ms
            attrs = s.get("attrs") or {}
            if s["name"] == "ring.slot":
                ring["windows"] += 1
                ring["slot_ms"] += dur_ms
            elif s["name"] == "kernel.dispatch" \
                    and attrs.get("kernel") == "knn_ring":
                ring["kernel_ms"] += dur_ms
            elif s["name"] == "device.sync" and attrs.get("ring"):
                ring["harvest_ms"] += dur_ms
            if s["name"] == "subscribe.lane.eval":
                lane = lane_evals.setdefault(
                    str(attrs.get("cls", "?")),
                    {"count": 0, "total_ms": 0.0, "rows": 0})
                lane["count"] += 1
                lane["total_ms"] += dur_ms
                lane["rows"] += int(attrs.get("rows", 0) or 0)
            ids = attrs.get("shards", "")
            if ids and s["name"] in DEVICE_PHASES:
                for sid in str(ids).split(","):
                    lane = shard_lanes.setdefault(
                        sid.strip(), {"count": 0, "device_ms": 0.0})
                    lane["count"] += 1
                    lane["device_ms"] += dur_ms
            if s["name"] == "dispatch":
                windows[(proc, s["id"])] = s
        for s in spans:
            parent = by_id.get(s.get("parent"))
            while parent is not None:
                if parent["name"] == "dispatch":
                    window_children.setdefault(
                        (proc, parent["id"]), []).append(s)
                    break
                parent = by_id.get(parent.get("parent"))
    # per-process aggregation over the (deduped) windows. exec time is
    # the UNION of window intervals: pipelined windows overlap, and the
    # overlapped second is one second of device occupancy, not two.
    # Stage intervals are clamped to their window and unioned BY STAGE
    # NAME first, then across stages — overlapping transfer/kernel
    # windows dedup instead of double-counting (pre-fix, summing the
    # per-window unions let a pipelined run report device_ms > exec_ms
    # and coverage > 1.0).
    by_proc_windows: Dict[str, List[Tuple[int, int]]] = {}
    by_proc_device: Dict[str, List[Tuple[int, int]]] = {}
    by_proc_host: Dict[str, List[Tuple[int, int]]] = {}
    # transfer intervals clamped to their OWNING window (by span
    # parentage, not interval containment — overlapping windows both
    # contain the same instant)
    by_proc_transfer: Dict[str, List[Tuple[int, int]]] = {}
    transfer_overlap_ns = 0
    for (proc, wid), w in windows.items():
        w0, w1 = w["t0_ns"], w["t1_ns"]
        if w1 <= w0:
            continue
        by_proc_windows.setdefault(proc, []).append((w0, w1))
        kids = {s["id"]: s for s in window_children.get((proc, wid), ())}
        for s in kids.values():
            iv = _clamp(s["t0_ns"], s["t1_ns"], w0, w1)
            if iv is None:
                continue
            if s["name"] in DEVICE_PHASES:
                by_proc_device.setdefault(proc, []).append(iv)
                if s["name"] == "device.transfer":
                    by_proc_transfer.setdefault(proc, []).append(iv)
            else:
                by_proc_host.setdefault(proc, []).append(iv)
    exec_ns = sum(_union_ns(v) for v in by_proc_windows.values())
    device_ns = sum(_union_ns(v) for v in by_proc_device.values())
    host_work_ns = sum(_union_ns(v) for v in by_proc_host.values())
    inflight_max = 0
    multi_window_ns = 0
    for proc, ivs in by_proc_windows.items():
        depth, multi, multi_regions = _max_concurrent(ivs)
        inflight_max = max(inflight_max, depth)
        multi_window_ns += multi
        # transfer time spent while ANOTHER window was open — the
        # "transfer hides behind compute" evidence. Each transfer is
        # clamped to its OWNING window, which contributes depth 1
        # everywhere inside it, so "inside a >=2-deep region" is
        # exactly "overlapping some OTHER window" — one sweep per
        # process instead of a per-window quadratic rescan.
        if multi_regions:
            transfer_overlap_ns += _overlap_ns(
                by_proc_transfer.get(proc, []), multi_regions)
    gap_ns = max(exec_ns - device_ns, 0)
    for name, p in phases.items():
        p["mean_ms"] = p["total_ms"] / p["count"] if p["count"] else 0.0
        p["share"] = (p["total_ms"] * 1e6 / wall_ns) if wall_ns else 0.0
        p["total_ms"] = round(p["total_ms"], 3)
        p["mean_ms"] = round(p["mean_ms"], 4)
        p["share"] = round(p["share"], 4)
    return {
        "traces": len(docs),
        "wall_ms": round(wall_ns / 1e6, 3),
        "coverage": round(min(covered_ns / wall_ns, 1.0), 4)
        if wall_ns else 0.0,
        "phases": dict(sorted(phases.items())),
        "dispatch_gap": {
            "windows": len(windows),
            "exec_ms": round(exec_ns / 1e6, 3),
            "device_ms": round(device_ns / 1e6, 3),
            "host_instrumented_ms": round(host_work_ns / 1e6, 3),
            "host_gap_ms": round(gap_ns / 1e6, 3),
            "gap_fraction": round(gap_ns / exec_ns, 4) if exec_ns else 0.0,
        },
        "pipeline": {
            "windows_in_flight_max": inflight_max,
            "multi_window_ms": round(multi_window_ns / 1e6, 3),
            "transfer_overlap_ms": round(transfer_overlap_ns / 1e6, 3),
        },
        "ring": {
            "windows": ring["windows"],
            "slot_ms": round(ring["slot_ms"], 3),
            "kernel_ms": round(ring["kernel_ms"], 3),
            "harvest_ms": round(ring["harvest_ms"], 3),
        },
        "shards": {
            sid: {"count": lane["count"],
                  "device_ms": round(lane["device_ms"], 3)}
            for sid, lane in sorted(shard_lanes.items())
        },
        "lanes": {
            cls: {"count": lane["count"],
                  "total_ms": round(lane["total_ms"], 3),
                  "rows": lane["rows"]}
            for cls, lane in sorted(lane_evals.items())
        },
    }


def render_gap(report: dict) -> str:
    """Human-readable gap report (`gmtpu trace --gap` default output)."""
    lines = [
        f"dispatch-gap report over {report['traces']} trace(s), "
        f"wall {report['wall_ms']:.1f} ms "
        f"(root-phase coverage {report['coverage'] * 100:.1f}%)",
        f"{'phase':<18}{'count':>7}{'total ms':>12}{'mean ms':>11}"
        f"{'share':>8}",
    ]
    for name, p in report["phases"].items():
        lines.append(
            f"{name:<18}{p['count']:>7}{p['total_ms']:>12.2f}"
            f"{p['mean_ms']:>11.3f}{p['share'] * 100:>7.1f}%")
    g = report["dispatch_gap"]
    lines.append(
        f"dispatch windows: {g['windows']} — exec {g['exec_ms']:.1f} ms, "
        f"device {g['device_ms']:.1f} ms, "
        f"host gap {g['host_gap_ms']:.1f} ms "
        f"({g['gap_fraction'] * 100:.1f}% of window time)")
    p = report.get("pipeline") or {}
    if p.get("windows_in_flight_max", 0) >= 2:
        lines.append(
            f"pipeline: up to {p['windows_in_flight_max']} windows in "
            f"flight ({p['multi_window_ms']:.1f} ms with >=2 open, "
            f"{p['transfer_overlap_ms']:.1f} ms of transfer overlapped "
            f"other windows)")
    r = report.get("ring") or {}
    if r.get("windows", 0) >= 1:
        lines.append(
            f"ring: {r['windows']} window(s) — slot {r['slot_ms']:.1f} "
            f"ms, kernel {r['kernel_ms']:.1f} ms, harvest "
            f"{r['harvest_ms']:.1f} ms")
    lanes = report.get("shards") or {}
    if lanes:
        parts = ", ".join(
            f"shard {sid}: {lane['device_ms']:.1f} ms"
            f"/{lane['count']}" for sid, lane in lanes.items())
        lines.append(f"shard lanes: {parts}")
    sub_lanes = report.get("lanes") or {}
    if sub_lanes:
        parts = ", ".join(
            f"{cls}: {lane['total_ms']:.1f} ms/{lane['count']} eval(s)"
            f" over {lane['rows']} row(s)"
            for cls, lane in sub_lanes.items())
        lines.append(f"subscribe lanes: {parts}")
    if g["windows"] and g["gap_fraction"] > 0.5:
        lines.append(
            "  NOTE: >50% of dispatch-window time is host gap — the "
            "path is dispatch-bound (ROADMAP item 2), not kernel-bound")
    return "\n".join(lines)
