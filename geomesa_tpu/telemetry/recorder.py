"""Flight recorder: a bounded ring buffer of recent query traces and
fault-fabric events, dumpable on demand or on crash.

The postmortem story for the recovery fabric (docs/ROBUSTNESS.md): when
a dispatch dies with an un-typed error, the question is never "what was
THIS request" — the audit log has that — but "what were the last N
requests doing, and what was the breaker/quarantine fabric seeing while
they ran". The recorder keeps exactly that window in memory at a fixed
cost (two deques), independent of whether tracing is enabled: fault
events (breaker transitions, quarantine strikes/trips, injected faults,
crash notes) always record; completed query traces record when the
serve layer traces them.

Memory bound: `capacity` traces (stored as plain JSON dicts, so a
recorded trace keeps no live references into the serve layer) and
`event_capacity` events. Overwrites are counted, never silent
(`dropped_traces` / `dropped_events` in every snapshot).

Crash dumps: `crash_dump(reason)` writes the full snapshot as JSON to
`auto_dump_path` (or `GEOMESA_TPU_FLIGHT_DUMP`, or a pid-qualified file
in the system temp dir) and returns the path. The serve dispatch loop
calls it on un-typed dispatcher errors; `gmtpu serve` wires SIGTERM-free
shutdown dumps via `--flight-dump`.
"""

from __future__ import annotations

import collections
import json
import os
import tempfile
import threading
import time
from typing import List, Optional

from geomesa_tpu.telemetry.prof import PROFILER
from geomesa_tpu.telemetry.trace import Trace

__all__ = ["FlightRecorder", "RECORDER"]


class FlightRecorder:
    def __init__(self, capacity: int = 256, event_capacity: int = 2048):
        if capacity < 1 or event_capacity < 1:
            raise ValueError("flight recorder capacities must be >= 1")
        self.capacity = capacity
        self.event_capacity = event_capacity
        self._lock = threading.Lock()
        self._traces: "collections.deque[dict]" = collections.deque(
            maxlen=capacity)
        self._events: "collections.deque[dict]" = collections.deque(
            maxlen=event_capacity)
        self._trace_count = 0
        self._event_count = 0
        self.auto_dump_path: Optional[str] = None

    # -- recording ---------------------------------------------------------

    def record(self, trace: "Trace | dict | None") -> None:
        """Store one completed query trace. Accepts a Trace (snapshotted
        to JSON immediately — the ring must not pin live serve objects)
        or an already-serialized dict; None no-ops so callers can pass
        `req.trace` straight through."""
        if trace is None:
            return
        doc = trace.to_json() if isinstance(trace, Trace) else trace
        with self._lock:
            self._traces.append(doc)
            self._trace_count += 1
        # continuous profiler (telemetry/prof.py): every recorded trace
        # folds into the lifetime distributions when the profiler is on
        # — one attribute read when off. Outside the ring lock: the
        # fold takes the profiler's own lock and must not couple scrape
        # readers of the ring to fold latency.
        PROFILER.maybe_fold(doc)

    def note_event(self, kind: str, **detail) -> None:
        """Record one fault-fabric event (breaker transition, quarantine
        strike/trip, injected fault, crash). Always-on and cheap: one
        dict + a lock-guarded deque append; wall-clock `ts` is an event
        timestamp, never a duration operand."""
        evt = {"ts": time.time(), "kind": kind}
        if detail:
            evt.update(detail)
        with self._lock:
            self._events.append(evt)
            self._event_count += 1

    # -- reading -----------------------------------------------------------

    def traces(self) -> List[dict]:
        with self._lock:
            return list(self._traces)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "traces": list(self._traces),
                "events": list(self._events),
                "trace_count": self._trace_count,
                "event_count": self._event_count,
                "dropped_traces": max(
                    0, self._trace_count - len(self._traces)),
                "dropped_events": max(
                    0, self._event_count - len(self._events)),
            }

    def stats(self) -> dict:
        with self._lock:
            return {
                "traces_held": len(self._traces),
                "events_held": len(self._events),
                "trace_count": self._trace_count,
                "event_count": self._event_count,
            }

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._events.clear()
            self._trace_count = 0
            self._event_count = 0

    # -- dumping -----------------------------------------------------------

    def dump(self, path: Optional[str] = None, reason: str = "") -> str:
        """Write the snapshot as JSON; returns the path written. The
        write is tmp+rename so a dump raced by another dumper (or a
        dying process) never leaves a half-written file."""
        doc = self.snapshot()
        if reason:
            doc["reason"] = reason
        doc["pid"] = os.getpid()
        doc["dumped_at"] = time.time()
        from geomesa_tpu.parallel.distributed import process_suffix

        path = path or self._default_dump_path()
        root, ext = os.path.splitext(path)
        # a flight dump is per-host forensics — a coordinator gate would
        # throw away every other host's evidence, so instead each
        # process writes its own file on a pod (single-process: no-op)
        path = f"{root}{process_suffix()}{ext}"
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        # gt: waive GT27
        # (targets are disjoint per process via process_suffix() above)
        os.replace(tmp, path)
        return path

    def _default_dump_path(self) -> str:
        if self.auto_dump_path:
            return self.auto_dump_path
        env = os.environ.get("GEOMESA_TPU_FLIGHT_DUMP")
        if env:
            return env
        return os.path.join(tempfile.gettempdir(),
                            f"gmtpu-flight-{os.getpid()}.json")

    def crash_dump(self, reason: str,
                   exc: Optional[BaseException] = None) -> Optional[str]:
        """The automatic postmortem path: note the crash as an event,
        then dump. Never raises — a failing dump must not re-kill the
        dispatcher that is trying to report its own crash."""
        try:
            detail = {"reason": reason}
            if exc is not None:
                detail["error"] = f"{type(exc).__name__}: {exc}"
            self.note_event("crash", **detail)
            return self.dump(reason=reason)
        except Exception:
            return None


# process-wide recorder: the serve layer records completed traces, the
# fault fabric (breaker/quarantine/harness) notes events, exporters and
# `gmtpu top` read snapshots
RECORDER = FlightRecorder()
