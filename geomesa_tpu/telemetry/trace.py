"""Span-tracing core: where a query's wall time actually goes.

The serve path is dispatch-bound (BENCH r03: 0.101s dispatch RTT vs
0.066s kernel time), and the only per-request evidence so far is the
coarse `ServeEvent` queue_ms/exec_ms split. This module gives every
query a trace — a tree of `Span`s opened at each serve phase (admit,
queue-wait, coalesce, plan, residency, transfer, kernel dispatch,
device sync, merge, respond) — so a p99 investigation reads a flame
view instead of correlating counters. The same discipline GeoMesa
inherits from its iterator timing + geomesa-metrics module, applied to
the accelerator serving stack.

Design constraints, in priority order:

1. **Off = free.** `TRACER.span()` with tracing disabled is one
   attribute read and a shared no-op object — no allocation, no clock
   read. Serving with tracing off must be indistinguishable from a
   build without telemetry (asserted in tests/test_telemetry.py).
2. **On = cheap.** A live span is two `perf_counter_ns()` reads, a
   thread-local stack push/pop, ONE object allocation (the context
   manager) and one tuple append — budgeted at <2µs per span and
   asserted in tests. Completed spans are stored as plain tuples, not
   objects: on slow hosts a slotted-class construction alone costs
   ~0.7µs, so the hot path appends `(name, id, parent, t0, t1, thread,
   attrs)` and `snapshot_spans()` materializes `Span` views lazily.
   Appends are lock-free — `list.append` is a single atomic bytecode
   under the GIL, and readers copy via `list(...)` before iterating.
   All timestamps are `perf_counter_ns` (monotonic, ns, comparable
   across threads in one process); wall-clock `time.time()` never
   measures a duration here (lint rule GT15 enforces that tree-wide).
3. **Library code stays trace-unaware of requests.** The planner and
   engine open spans by name only; whether they land in a trace is
   decided by the thread's *scope* (`TRACER.scope(trace)`), installed
   by the serve dispatch loop around each dispatch window. A direct
   planner caller with no scope pays the no-op path even when tracing
   is globally on.

Cross-thread phases (queue wait spans the submitting thread and the
dispatch thread) are recorded retroactively via `Trace.record` with
explicit timestamps.
"""

from __future__ import annotations

import itertools
import os
import threading
from time import perf_counter_ns
from typing import List, Optional

__all__ = ["Span", "Trace", "Tracer", "TRACER", "NOOP_SPAN",
           "new_span_id"]

_span_ids = itertools.count(1)
_trace_ids = itertools.count(1)

# span storage tuple layout (hot path appends these, Span wraps them)
# (name, span_id, parent_id, start_ns, end_ns, thread, attrs-or-None)


def _new_trace_id() -> str:
    # pid-qualified so dumps merged across processes (replica fleets,
    # chaos runs) never collide
    return f"{os.getpid():x}-{next(_trace_ids):x}"


def new_span_id() -> int:
    """Pre-allocate a span id from the process-wide counter. The
    pipelined dispatch path records its window span only at completion
    (the window's extent is not known until the deferred device sync),
    but its stage spans need the window as parent while it is still
    open — so the id is allocated up front and passed to `Trace.record`
    / `Tracer.scope(parent_id=...)` until the closing record."""
    return next(_span_ids)


class Span:
    """One completed span — a typed view over the storage tuple. Plain
    data: the tracer writes tuples, exporters read these."""

    __slots__ = ("name", "span_id", "parent_id", "start_ns", "end_ns",
                 "thread", "attrs")

    def __init__(self, name: str, span_id: int, parent_id: Optional[int],
                 start_ns: int, end_ns: int, thread: int,
                 attrs: Optional[dict]):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.start_ns = start_ns
        self.end_ns = end_ns
        self.thread = thread
        self.attrs = attrs

    @property
    def duration_ns(self) -> int:
        return self.end_ns - self.start_ns

    def to_json(self) -> dict:
        return _tuple_json((self.name, self.span_id, self.parent_id,
                            self.start_ns, self.end_ns, self.thread,
                            self.attrs))

    @classmethod
    def from_json(cls, d: dict) -> "Span":
        return cls(d["name"], d["id"], d.get("parent"), d["t0_ns"],
                   d["t1_ns"], d.get("thread", 0), d.get("attrs"))


def _tuple_json(t: tuple) -> dict:
    name, span_id, parent_id, start_ns, end_ns, thread, attrs = t
    d = {
        "name": name,
        "id": span_id,
        "parent": parent_id,
        "t0_ns": start_ns,
        "t1_ns": end_ns,
        "thread": thread,
    }
    if attrs:
        d["attrs"] = dict(attrs)
    return d


class Trace:
    """One query's span tree. The submitting thread, the dispatch
    thread and protocol callbacks all contribute; `spans` holds raw
    storage tuples appended lock-free (GIL-atomic), and every reader
    copies the list before iterating. The root span opens at
    construction and closes at `finish()`."""

    __slots__ = ("trace_id", "name", "root", "_flock", "spans",
                 "finished")

    def __init__(self, name: str, **attrs):
        self.trace_id = _new_trace_id()
        self.name = name
        self._flock = threading.Lock()  # finish() only — never hot
        self.spans: List[tuple] = []
        self.finished = False
        self.root = Span(name, next(_span_ids), None, perf_counter_ns(), 0,
                         threading.get_ident(), dict(attrs) or None)

    def record(self, name: str, start_ns: int, end_ns: int,
               parent_id: Optional[int] = None,
               span_id: Optional[int] = None, **attrs) -> Span:
        """Record an already-measured phase (queue wait, respond): the
        caller holds both timestamps; parent defaults to the root.
        `span_id` lets a caller close a span whose id was pre-allocated
        via `new_span_id()` (the pipelined dispatch window)."""
        t = (name, span_id if span_id is not None else next(_span_ids),
             parent_id if parent_id is not None else self.root.span_id,
             start_ns, end_ns, threading.get_ident(), attrs or None)
        # gt: waive GT07
        # (deliberately outside _flock: single-bytecode list.append is
        # atomic under the GIL; readers snapshot via list(self.spans) —
        # see the module docstring. _flock guards only finish().)
        self.spans.append(t)
        return Span(*t)

    def adopt(self, spans: List[Span], clamp_start_ns: Optional[int] = None
              ) -> None:
        """Copy another trace's spans into this one (a coalesced rider
        adopting the shared dispatch-window spans from the lead trace).
        Span/parent ids are kept — they are globally unique — so the
        tree re-roots cleanly: a copied span whose parent is the OTHER
        trace's root re-parents to THIS root. `clamp_start_ns` floors
        adopted starts at this trace's root start (a rider admitted
        mid-gather would otherwise carry a child older than its root);
        clamped copies are marked with attr clamped=True."""
        other_ids = {s.span_id for s in spans}
        out = []
        for s in spans:
            parent = (s.parent_id if s.parent_id in other_ids
                      else self.root.span_id)
            attrs = dict(s.attrs) if s.attrs else None
            start = s.start_ns
            if clamp_start_ns is not None and start < clamp_start_ns:
                start = clamp_start_ns
                attrs = dict(attrs or ())
                attrs["clamped"] = True
            out.append((s.name, s.span_id, parent, start,
                        max(s.end_ns, start), s.thread, attrs))
        # gt: waive GT07
        # (GIL-atomic extend of the lock-free span list, as in record —
        # _flock guards only finish())
        self.spans.extend(out)

    def finish(self, **attrs) -> "Trace":
        """Close the root span; idempotent (the first close wins so a
        late finisher cannot stretch the recorded wall time)."""
        with self._flock:
            if not self.finished:
                self.finished = True
                self.root.end_ns = perf_counter_ns()
                if attrs:
                    merged = dict(self.root.attrs or ())
                    merged.update(attrs)
                    self.root.attrs = merged
        return self

    def snapshot_spans(self) -> List[Span]:
        return [Span(*t) for t in list(self.spans)]

    def span_count(self) -> int:
        return len(self.spans)

    def to_json(self) -> dict:
        spans = list(self.spans)
        root = self.root.to_json()
        if self.root.end_ns == 0:
            root["t1_ns"] = perf_counter_ns()  # still-open trace dump
        return {
            "trace_id": self.trace_id,
            "name": self.name,
            "root": root,
            "spans": [_tuple_json(t) for t in spans],
        }


class _NoopSpan:
    """Shared do-nothing span: the disabled/unscoped fast path returns
    this singleton, so `with TRACER.span(...)` costs one attribute read
    and two no-op calls."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class _SpanHandle:
    """The per-scope span context manager — ONE shared object per
    (thread, scope), not one per span, because on slow hosts a slotted
    allocation alone eats a third of the 2µs budget.

    How it works: `Tracer.span()` pushes an *open frame*
    `[name, span_id, parent_id, start_ns, attrs]` onto the scope's
    frame stack and returns this shared handle; `__exit__` pops the top
    frame, stamps the end time and appends the completed storage tuple.
    Correct because with-blocks are strictly LIFO per thread — the
    frame `__exit__` pops is always the one the matching `span()` call
    pushed (ExitStack unwinds in reverse order, preserving LIFO). The
    GT15 lint rule enforces the contract's precondition: every
    `.span()` call is a `with` context expression (or enter_context
    argument), so frames can never leak unbalanced.

    After a `with ... as s:` block exits, `s.span_id` / `s.start_ns` /
    `s.end_ns` hold the values of the span that just closed — the
    innermost-exit-last order makes that exactly the span the with
    opened. `set()` targets the innermost OPEN span, which inside a
    with-body (and before any child opens) is the with's own span."""

    __slots__ = ("_ctx", "span_id", "start_ns", "end_ns")

    def __init__(self, ctx: tuple):
        self._ctx = ctx

    def __enter__(self) -> "_SpanHandle":
        return self

    def set(self, **attrs) -> None:
        frame = self._ctx[2][-1]
        if frame[4] is None:
            frame[4] = attrs
        else:
            frame[4].update(attrs)

    def __exit__(self, exc_type, exc, tb, _pc=perf_counter_ns) -> bool:
        end_ns = _pc()
        ctx = self._ctx
        name, span_id, parent_id, start_ns, attrs = ctx[2].pop()
        if exc_type is not None:
            if attrs is None:
                attrs = {}
            attrs["error"] = exc_type.__name__
        self.span_id = span_id
        self.start_ns = start_ns
        self.end_ns = end_ns
        # gt: waive GT12
        # (GIL-atomic append to the lock-free span list — module doc)
        ctx[0].append(
            (name, span_id, parent_id, start_ns, end_ns, ctx[3], attrs))
        return False


class _Scope:
    __slots__ = ("_tracer", "_trace", "_prev", "_parent_id")

    def __init__(self, tracer: "Tracer", trace: Optional[Trace],
                 parent_id: Optional[int] = None):
        self._tracer = tracer
        self._trace = trace
        self._parent_id = parent_id

    def __enter__(self) -> Optional[Trace]:
        tls = self._tracer._tls
        self._prev = getattr(tls, "ctx", None)
        trace = self._trace
        if trace is None:
            tls.ctx = None  # explicit silence (warmup replay)
        else:
            # the per-scope span context: (spans list, base parent id,
            # open-frame stack, thread ident, trace, shared handle) —
            # ONE tls read per span instead of separate lookups. The
            # handle closes over the ctx, so build it in two steps.
            # The base parent defaults to the root; the pipelined
            # dispatch passes its pre-allocated window span id so stage
            # spans nest under the (not-yet-recorded) window.
            handle = _SpanHandle.__new__(_SpanHandle)
            base = (self._parent_id if self._parent_id is not None
                    else trace.root.span_id)
            ctx = (trace.spans, base, [],
                   threading.get_ident(), trace, handle)
            handle._ctx = ctx
            tls.ctx = ctx
        return trace

    def __exit__(self, *exc) -> bool:
        self._tracer._tls.ctx = self._prev
        return False


class Tracer:
    """Process-wide tracing switch + per-thread scope. One instance
    (`TRACER`) serves the whole process; QueryServices, the planner and
    the engine all open spans through it."""

    __slots__ = ("enabled", "_tls")

    def __init__(self):
        self.enabled = False
        self._tls = threading.local()

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def start_trace(self, name: str, **attrs) -> Optional[Trace]:
        """A new Trace (opening its root span), or None when tracing is
        off — callers thread the None through untouched and every
        downstream telemetry call no-ops."""
        if not self.enabled:
            return None
        return Trace(name, **attrs)

    def scope(self, trace: Optional[Trace],
              parent_id: Optional[int] = None) -> _Scope:
        """Bind `trace` as this thread's active trace for the duration
        (`with TRACER.scope(trace): ...`). Spans opened by ANY code on
        this thread inside the scope land in it; scoping None explicitly
        silences spans (used by warmup replay). `parent_id` re-bases the
        scope: top-level spans parent to that span instead of the root
        (the pipelined dispatch window's pre-allocated id)."""
        return _Scope(self, trace, parent_id)

    def current_trace(self) -> Optional[Trace]:
        if not self.enabled:
            return None
        ctx = getattr(self._tls, "ctx", None)
        return ctx[4] if ctx is not None else None

    def span(self, name: str, _noop=NOOP_SPAN,
             _next_id=_span_ids.__next__, _pc=perf_counter_ns, **attrs):
        """Open a span under the thread's scoped trace: pushes an open
        frame and returns the scope's shared handle (see _SpanHandle —
        the span opens HERE; `with` must close it). The no-op path
        (tracing off, or no scope installed) returns a shared no-op
        singleton: library code can call this unconditionally on hot
        paths."""
        if not self.enabled:
            return _noop
        ctx = getattr(self._tls, "ctx", None)
        if ctx is None:
            return _noop
        stack = ctx[2]
        parent_id = stack[-1][1] if stack else ctx[1]
        stack.append([name, _next_id(), parent_id, _pc(), attrs or None])
        return ctx[5]


TRACER = Tracer()
