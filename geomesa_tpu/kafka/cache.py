"""KafkaFeatureCache: latest-feature-per-id in-memory state + spatial index.

Parity: geomesa-kafka KafkaFeatureCache + KafkaFeatureEventSource [upstream,
unverified]: consumers fold GeoMessages into a map fid -> latest feature,
maintain a gridded spatial index for bbox queries, push feature events to
registered listeners, and expire features by age.

TPU integration (SURVEY.md C12): `snapshot()` materializes the live state as
an immutable columnar FeatureBatch — the double-buffered device refresh
boundary. Queries can run host-side from the index (low latency, small
results) or device-side on the latest snapshot (analytics).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.core.wkt import Geometry
from geomesa_tpu.kafka.messages import Change, Clear, Delete, GeoMessage
from geomesa_tpu.utils.spatial_index import BucketIndex


@dataclasses.dataclass
class FeatureEvent:
    kind: str  # changed | removed | cleared
    fid: Optional[str] = None
    attributes: Optional[Dict[str, object]] = None


Listener = Callable[[FeatureEvent], None]


class KafkaFeatureCache:
    def __init__(
        self,
        sft: SimpleFeatureType,
        expiry_ms: Optional[int] = None,
        xbuckets: int = 360,
        ybuckets: int = 180,
        index_attrs: Optional[List[str]] = None,
    ):
        self.sft = sft
        self.expiry_ms = expiry_ms
        self._geom = sft.default_geometry.name if sft.default_geometry else None
        self._rows: Dict[str, Dict[str, object]] = {}
        self._stamps: Dict[str, float] = {}
        self._index: BucketIndex[str] = BucketIndex(xbuckets, ybuckets)
        # CQEngine-analog attribute hash indexes (SURVEY.md:323-324): for
        # each indexed attribute, value -> set of fids, so live-layer
        # equality queries avoid the full snapshot scan
        if index_attrs is None:
            index_attrs = [
                a.name
                for a in sft.attributes
                if a.options.get("index", "").lower() in ("true", "full", "join")
            ]
        self._attr_index: Dict[str, Dict[object, set]] = {
            a: {} for a in index_attrs
        }
        self.attr_index_hits = 0  # counter: fast-path queries served
        self._listeners: List[Listener] = []
        self._lock = threading.Lock()
        self._snapshot: Optional[FeatureBatch] = None
        self._snapshot_dirty = True

    # -- message application ----------------------------------------------

    def apply(self, msg: GeoMessage) -> None:
        if isinstance(msg, Change):
            self._upsert(msg.fid, msg.attributes)
        elif isinstance(msg, Delete):
            self._delete(msg.fid)
        elif isinstance(msg, Clear):
            self.clear()
        else:
            raise TypeError(f"not a GeoMessage: {msg!r}")

    def _unindex_attrs(self, fid: str) -> None:
        """Caller holds the lock. Remove fid's old values from the
        attribute indexes."""
        old = self._rows.get(fid)
        if old is None:
            return
        for name, idx in self._attr_index.items():
            fids = idx.get(old.get(name))
            if fids is not None:
                fids.discard(fid)
                if not fids:
                    del idx[old.get(name)]

    def _upsert(self, fid: str, attrs: Dict[str, object]) -> None:
        with self._lock:
            self._unindex_attrs(fid)
            for name, idx in self._attr_index.items():
                idx.setdefault(attrs.get(name), set()).add(fid)
            self._rows[fid] = attrs
            self._stamps[fid] = time.time()
            if self._geom is not None:
                g = attrs.get(self._geom)
                if isinstance(g, Geometry):
                    cx, cy = g.point if g.is_point else (
                        (g.bbox[0] + g.bbox[2]) / 2.0,
                        (g.bbox[1] + g.bbox[3]) / 2.0,
                    )
                    self._index.insert(fid, cx, cy, fid)
            self._snapshot_dirty = True
        self._emit(FeatureEvent("changed", fid, attrs))

    def _delete(self, fid: str) -> None:
        with self._lock:
            self._unindex_attrs(fid)
            existed = self._rows.pop(fid, None) is not None
            self._stamps.pop(fid, None)
            self._index.remove(fid)
            if existed:
                self._snapshot_dirty = True
        if existed:
            self._emit(FeatureEvent("removed", fid))

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()
            self._stamps.clear()
            self._index.clear()
            for idx in self._attr_index.values():
                idx.clear()
            self._snapshot_dirty = True
        self._emit(FeatureEvent("cleared"))

    # -- expiry ------------------------------------------------------------

    def expire(self, now: Optional[float] = None) -> int:
        """Drop features older than expiry_ms; returns the evicted count.
        Called by the store's maintenance tick (upstream: Caffeine expiry).

        Expiry-driven removals emit `removed` FeatureEvents exactly like
        explicit deletes — a geofence subscription must see the EXIT
        when a feature ages out, not just when a Delete message arrives
        (geomesa_tpu.subscribe). Selection and removal happen under ONE
        lock acquisition (the old collect-then-re-lock shape let a
        racing upsert refresh a fid between the scan and its delete,
        dropping a fresh row); events emit OUTSIDE the lock against a
        listener snapshot — the `_emit` discipline (GT11)."""
        if self.expiry_ms is None:
            return 0
        now = now if now is not None else time.time()
        cutoff = now - self.expiry_ms / 1000.0
        events = []
        with self._lock:
            stale = [fid for fid, ts in self._stamps.items()
                     if ts < cutoff]
            for fid in stale:
                self._unindex_attrs(fid)
                self._rows.pop(fid, None)
                self._stamps.pop(fid, None)
                self._index.remove(fid)
                events.append(FeatureEvent("removed", fid))
            if stale:
                self._snapshot_dirty = True
            listeners = list(self._listeners)
        for event in events:
            for fn in listeners:
                fn(event)
        return len(events)

    # -- reads -------------------------------------------------------------

    def get(self, fid: str) -> Optional[Dict[str, object]]:
        with self._lock:
            return self._rows.get(fid)

    def query_bbox(
        self, bbox: Tuple[float, float, float, float]
    ) -> List[Tuple[str, Dict[str, object]]]:
        """Host-side bbox query straight off the gridded index."""
        with self._lock:
            fids = [fid for fid, _ in self._index.query(bbox)]
            return [(fid, self._rows[fid]) for fid in fids if fid in self._rows]

    @property
    def indexed_attributes(self) -> List[str]:
        return sorted(self._attr_index)

    def query_attribute(
        self, name: str, values
    ) -> List[Tuple[str, Dict[str, object]]]:
        """Equality/IN lookup off the attribute hash index — O(matches),
        no snapshot scan. Raises KeyError for unindexed attributes."""
        with self._lock:
            idx = self._attr_index[name]
            self.attr_index_hits += 1
            fids: set = set()
            for v in values:
                fids |= idx.get(v, set())
            return [
                (fid, self._rows[fid])
                for fid in sorted(fids)
                if fid in self._rows
            ]

    def snapshot(self) -> Optional[FeatureBatch]:
        """Immutable columnar view of current state (device refresh boundary).
        Rebuilt only when dirty — repeated calls between updates are free."""
        with self._lock:
            if not self._snapshot_dirty:
                return self._snapshot
            if not self._rows:
                self._snapshot = None
                self._snapshot_dirty = False
                return None
            fids = list(self._rows.keys())
            data: Dict[str, list] = {a.name: [] for a in self.sft.attributes}
            for fid in fids:
                row = self._rows[fid]
                for a in self.sft.attributes:
                    data[a.name].append(row.get(a.name))
            self._snapshot = FeatureBatch.from_pydict(self.sft, data, fids=fids)
            self._snapshot_dirty = False
            return self._snapshot

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    # -- events ------------------------------------------------------------

    def add_listener(self, fn: Listener) -> None:
        with self._lock:
            self._listeners.append(fn)

    def remove_listener(self, fn: Listener) -> None:
        with self._lock:
            self._listeners.remove(fn)

    def _emit(self, event: FeatureEvent) -> None:
        # snapshot under the lock; INVOKE outside it (GT11): a listener
        # that queries the cache re-enters without self-deadlocking
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            fn(event)
