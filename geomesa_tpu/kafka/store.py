"""KafkaDataStore: topic-per-type live layer over a pluggable broker.

Parity: geomesa-kafka KafkaDataStore [upstream, unverified]: writers produce
GeoMessages to one topic per feature type; consumers fold them into a
KafkaFeatureCache; queries are served from memory. The broker is pluggable:
`InProcessBroker` (default) is an in-process append-only log with offsets —
the "embedded broker" testing idea from the reference's test strategy — and
a real Kafka client could implement the same two methods.

Queries ride the standard QueryPlanner via a MemoryStorage adapter, so the
live layer supports the full hint surface (density/stats/bin/sampling) on
the latest snapshot: host upserts, device analytics (SURVEY.md C12).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from geomesa_tpu.core.columnar import DictColumn, FeatureBatch, GeometryColumn
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.core.wkt import Geometry, point
from geomesa_tpu.cql import ast, parse_cql
from geomesa_tpu.cql.extract import BBox, Interval
from geomesa_tpu.faults import BREAKERS, RetryPolicy, retry_call
from geomesa_tpu.faults import harness as _faults
from geomesa_tpu.kafka.cache import KafkaFeatureCache
from geomesa_tpu.kafka.messages import (
    Change,
    Clear,
    Delete,
    GeoMessage,
    GeoMessageSerializer,
)
from geomesa_tpu.plan.audit import AuditWriter
from geomesa_tpu.plan.datastore import FeatureSource
from geomesa_tpu.plan.planner import QueryPlanner
from geomesa_tpu.plan.query import Query


# broker-boundary fault sites + retry policy (docs/ROBUSTNESS.md): a
# real Kafka client drops connections and rebalances; the in-process
# broker never does — the harness makes those failure modes injectable
# on the exact code path a real client would take. Retries run OUTSIDE
# the store lock (see poll) so a flapping broker never stalls other
# topics' consumers behind a backoff sleep.
_POLL_SITE = _faults.site(
    "kafka.poll", "broker consume (offset window read)")
_PRODUCE_SITE = _faults.site(
    "kafka.produce", "broker produce (one GeoMessage)")
_KAFKA_RETRY = RetryPolicy(max_attempts=4, base_ms=5.0, cap_ms=200.0)


class InProcessBroker:
    """Append-only log per topic with consumer offsets (embedded broker)."""

    def __init__(self):
        self._topics: Dict[str, List[bytes]] = {}
        self._lock = threading.Lock()

    def produce(self, topic: str, payload: bytes) -> int:
        with self._lock:
            log = self._topics.setdefault(topic, [])
            log.append(payload)
            return len(log) - 1

    def consume(self, topic: str, offset: int) -> List[bytes]:
        with self._lock:
            log = self._topics.get(topic, [])
            return log[offset:]

    def end_offset(self, topic: str) -> int:
        with self._lock:
            return len(self._topics.get(topic, []))


class MemoryStorage:
    """Duck-typed storage over a KafkaFeatureCache snapshot, so the standard
    QueryPlanner (and its full hint surface) runs against live state."""

    def __init__(self, sft: SimpleFeatureType, cache: KafkaFeatureCache):
        self.sft = sft
        self.cache = cache
        # stats.json is never written for a live layer; point the stats
        # manager at a directory that does not exist
        self.root = os.path.join(".", f".geomesa-live-{sft.name}-nostats")

    @property
    def count(self) -> int:
        return len(self.cache)

    def partitions(self) -> List[str]:
        return ["live"]

    def prune_partitions(self, bbox: BBox, interval: Interval) -> List[str]:
        return ["live"] if len(self.cache) else []

    def scan(
        self,
        bbox: Optional[BBox] = None,
        interval: Optional[Interval] = None,
        columns: Optional[Sequence[str]] = None,
    ) -> Iterator[FeatureBatch]:
        snap = self.cache.snapshot()
        if snap is None:
            return
        yield snap  # covering superset; residual mask is the engine's job


class KafkaFeatureSource(FeatureSource):
    """FeatureSource whose writes produce GeoMessages and whose reads fold
    the topic into the cache first (lazy consume on query)."""

    def __init__(self, store: "KafkaDataStore", name: str):
        self._store = store
        self._name = name
        state = store._state[name]
        super().__init__(
            state["storage"],
            QueryPlanner(state["storage"], store.audit, store.mesh),
        )

    def write(self, batch: FeatureBatch) -> None:
        self._store.write(self._name, batch)

    def _attr_fast_path(self, query: Query):
        """Serve `attr = 'v'` / `attr IN (...)` on an INDEXED attribute
        straight from the cache's hash index (the CQEngine analog,
        SURVEY.md:323-324) — no snapshot build, no device round trip.
        Only plain feature fetches qualify; every hint/sort/aggregation
        falls through to the full planner path."""
        h = query.hints
        if (
            h != type(h)()  # any non-default hint
            or query.sort_by
            or query.attributes is not None
            or self.planner.interceptors  # must not bypass the chain
            # feature-level visibility rides the planner mask; the index
            # has no auth awareness, so it must not serve those types
            or (self.sft.user_data or {}).get("geomesa.vis.attr")
        ):
            return None
        f = query.filter_ast
        if isinstance(f, ast.Comparison) and f.op == "=":
            prop, lit = f.left, f.right
            if isinstance(prop, ast.Literal):
                prop, lit = lit, prop
            if not isinstance(prop, ast.Property) or not isinstance(lit, ast.Literal):
                return None
            name, values = prop.name, [lit.value]
        elif isinstance(f, ast.In) and not f.negate:
            name, values = f.prop.name, list(f.values)
        else:
            return None
        cache = self._store.cache(self._name)
        if name not in cache.indexed_attributes:
            return None
        import time as _time

        t0 = _time.perf_counter()
        rows = cache.query_attribute(name, values)
        from geomesa_tpu.plan.planner import QueryResult

        if not rows:
            result = QueryResult("features", features=None, count=0)
        else:
            sft = self.sft
            data = {
                a.name: [row.get(a.name) for _, row in rows]
                for a in sft.attributes
            }
            batch = FeatureBatch.from_pydict(
                sft, data, fids=[fid for fid, _ in rows]
            )
            from geomesa_tpu.plan.runner import finish_features

            batch = finish_features(batch, query)
            result = QueryResult(
                "features", features=batch, count=len(batch)
            )
        # the fast path must not dodge the audit trail: these are the most
        # frequent live-layer queries
        audit = self._store.audit
        if audit is not None:
            from geomesa_tpu.plan.audit import QueryEvent

            dt = (_time.perf_counter() - t0) * 1000
            audit.write(
                QueryEvent(
                    type_name=query.type_name,
                    filter=ast.to_cql(query.filter_ast),
                    hints="attr-index-fast-path",
                    plan_time_ms=0.0,
                    scan_time_ms=dt,
                    compute_time_ms=0.0,
                    result_count=result.count,
                    partitions_scanned=1,
                    partitions_total=1,
                )
            )
        return result

    def get_features(self, query="INCLUDE"):
        self._store.poll(self._name)
        if isinstance(query, str):
            query = Query(self.sft.name, query)
        fast = self._attr_fast_path(query)
        if fast is not None:
            return fast
        return super().get_features(query)

    def get_count(self, query="INCLUDE") -> int:
        self._store.poll(self._name)
        return super().get_count(query)


class KafkaLayerView(KafkaFeatureSource):
    """Filtered/projected derived view over a live layer (read-only)."""

    def __init__(self, store, base_name, view_name, cql, attributes):
        super().__init__(store, base_name)
        self.view_name = view_name
        self.view_filter = parse_cql(cql) if isinstance(cql, str) else cql
        self.view_attributes = list(attributes) if attributes else None

    def _narrow(self, query):
        if isinstance(query, str):
            query = Query(self._name, query)
        f = query.filter_ast
        merged = (
            self.view_filter
            if isinstance(f, ast.Include)
            else ast.And((self.view_filter, f))
        )
        attrs = query.attributes
        if self.view_attributes is not None:
            attrs = (
                self.view_attributes
                if attrs is None
                else [a for a in attrs if a in self.view_attributes]
            )
        import dataclasses as _dc

        return _dc.replace(query, filter=merged, attributes=attrs)

    def write(self, batch) -> None:
        raise TypeError(f"layer view {self.view_name!r} is read-only")

    def get_features(self, query="INCLUDE"):
        return super().get_features(self._narrow(query))

    def get_count(self, query="INCLUDE") -> int:
        return super().get_count(self._narrow(query))


class KafkaDataStore:
    def __init__(
        self,
        broker: Optional[InProcessBroker] = None,
        audit: Optional[AuditWriter] = None,
        mesh=None,
        expiry_ms: Optional[int] = None,
    ):
        self.broker = broker if broker is not None else InProcessBroker()
        self.audit = audit if audit is not None else AuditWriter()
        self.mesh = mesh
        self.expiry_ms = expiry_ms
        self._state: Dict[str, dict] = {}
        # reentrant: schema registration and poll (consume -> cache fold
        # -> offset advance, one atomic unit per topic) run from query
        # threads AND the serve dispatch thread; a feature listener
        # calling back into the store must not self-deadlock
        self._lock = threading.RLock()
        # post-fold hooks (geomesa_tpu.subscribe): invoked with the
        # type name after a poll commits its window, OUTSIDE the store
        # lock — the standing-query evaluator dispatches device kernels
        # from here, which must never run under this lock (GT09)
        self._fold_hooks: List = []

    def add_fold_hook(self, fn) -> None:
        """Register `fn(type_name)` to run after every committed poll
        fold (and after expiry sweeps), outside the store lock."""
        with self._lock:
            self._fold_hooks.append(fn)

    def remove_fold_hook(self, fn) -> None:
        """Detach a fold hook (a closed SubscriptionManager must stop
        costing every future poll). Raises ValueError if absent."""
        with self._lock:
            self._fold_hooks.remove(fn)

    # -- schema ------------------------------------------------------------

    def create_schema(self, sft: SimpleFeatureType) -> KafkaFeatureSource:
        cache = KafkaFeatureCache(sft, expiry_ms=self.expiry_ms)
        with self._lock:
            self._state[sft.name] = {
                "sft": sft,
                "serializer": GeoMessageSerializer(sft),
                "cache": cache,
                "storage": MemoryStorage(sft, cache),
                "offset": 0,
            }
        return KafkaFeatureSource(self, sft.name)

    def get_type_names(self) -> List[str]:
        with self._lock:
            return sorted(self._state)

    def get_schema(self, name: str) -> SimpleFeatureType:
        with self._lock:
            return self._state[name]["sft"]

    def get_feature_source(self, name: str) -> KafkaFeatureSource:
        with self._lock:
            if name not in self._state:
                raise KeyError(f"no live schema {name!r}")
        return KafkaFeatureSource(self, name)

    def cache(self, name: str) -> KafkaFeatureCache:
        with self._lock:
            return self._state[name]["cache"]

    # -- layer views -------------------------------------------------------

    def create_layer_view(
        self,
        view_name: str,
        base_name: str,
        cql: str = "INCLUDE",
        attributes: Optional[List[str]] = None,
    ) -> "KafkaLayerView":
        """A derived read-only view of a live layer: the base layer's
        stream with a standing filter and/or projection (upstream: Kafka
        layer views, SURVEY.md C12). Views share the base cache — no data
        is duplicated; the view filter ANDs into every query."""
        with self._lock:
            if base_name not in self._state:
                raise KeyError(f"no live schema {base_name!r}")
        view = KafkaLayerView(self, base_name, view_name, cql, attributes)
        with self._lock:
            self._state[base_name].setdefault("views", {})[view_name] = view
        return view

    def get_layer_view(self, base_name: str, view_name: str) -> "KafkaLayerView":
        with self._lock:
            return self._state[base_name]["views"][view_name]

    # -- producer side -----------------------------------------------------

    def _produce(self, name: str, payload: bytes) -> int:
        """One broker produce under the recovery fabric: transient
        broker failures retry with backoff against the "kafka" breaker.
        Produces are latest-wins upserts keyed by fid, so a duplicate
        from an ambiguous failure (produced, then the ack was lost) is
        absorbed by the fold — retrying is safe."""

        def attempt():
            _PRODUCE_SITE.fire()
            return self.broker.produce(name, payload)

        return retry_call(attempt, policy=_KAFKA_RETRY, label="kafka",
                          breaker=BREAKERS.get("kafka"))

    def write(self, name: str, batch: FeatureBatch) -> None:
        """Produce one Change per feature (latest-wins upsert semantics)."""
        with self._lock:
            ser: GeoMessageSerializer = self._state[name]["serializer"]
        for fid, attrs in _batch_rows(batch):
            self._produce(name, ser.serialize(Change(fid, attrs)))

    def delete(self, name: str, fid: str) -> None:
        with self._lock:
            ser = self._state[name]["serializer"]
        self._produce(name, ser.serialize(Delete(fid)))

    def clear(self, name: str) -> None:
        with self._lock:
            ser = self._state[name]["serializer"]
        self._produce(name, ser.serialize(Clear()))

    # -- consumer side -----------------------------------------------------

    def poll(self, name: str) -> int:
        """Consume new messages into the cache; returns messages applied.
        The fold -> offset advance stays one atomic unit per topic: two
        query threads polling concurrently must not double-apply a
        message window (latest-wins would hide it for Change, not for
        Clear+replay interleavings) or skip one by racing the offset
        bump. The broker CONSUME (the part that can fail and back off)
        runs outside the lock against the pinned start offset; before
        folding, the offset is re-checked — if another poller applied a
        window meanwhile, this one discards its (now superseded) read
        instead of double-applying."""
        with self._lock:
            st = self._state[name]
            start = st["offset"]
            ser: GeoMessageSerializer = st["serializer"]
            cache: KafkaFeatureCache = st["cache"]

        def attempt():
            _POLL_SITE.fire()
            return self.broker.consume(name, start)

        msgs = retry_call(attempt, policy=_KAFKA_RETRY, label="kafka",
                          breaker=BREAKERS.get("kafka"))
        with self._lock:
            if st["offset"] != start:
                # a concurrent poll won the race and advanced the
                # offset; its fold covered log[start:its_end] — ours
                # would re-apply that prefix. The messages past its end
                # are picked up by the next poll (offset is authority).
                return 0
            for payload in msgs:
                cache.apply(ser.deserialize(payload))
            st["offset"] += len(msgs)
            if self.expiry_ms is not None:
                cache.expire()
            hooks = list(self._fold_hooks)
        # post-fold hooks OUTSIDE the lock: the standing-query
        # evaluator pumps its delta buffer here (device dispatch); the
        # winner of the offset race is the only caller that reaches
        # this point, so one committed window pumps exactly once
        for hook in hooks:
            hook(name)
        return len(msgs)


def _batch_rows(batch: FeatureBatch) -> Iterator[Tuple[str, Dict[str, object]]]:
    """Iterate a columnar batch as (fid, attribute-dict) rows."""
    n = len(batch)
    fids = batch.fids.decode() if batch.fids is not None else [f"f{i}" for i in range(n)]
    cols = {}
    for a in batch.sft.attributes:
        col = batch.columns[a.name]
        if isinstance(col, GeometryColumn):
            if col.is_point:
                cols[a.name] = [point(float(x), float(y)) for x, y in zip(col.x, col.y)]
            else:
                cols[a.name] = [_extended_geom(col, i) for i in range(n)]
        elif isinstance(col, DictColumn):
            cols[a.name] = col.decode()
        else:
            arr = np.asarray(col)
            cols[a.name] = [v.item() if hasattr(v, "item") else v for v in arr]
    for i in range(n):
        yield str(fids[i]), {name: vals[i] for name, vals in cols.items()}


def _extended_geom(col: GeometryColumn, i: int) -> Geometry:
    return col.geometry(i)
