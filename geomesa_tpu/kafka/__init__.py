"""Near-real-time live feature layer (the geomesa-kafka analog).

Parity: geomesa-kafka KafkaDataStore / GeoMessage / KafkaFeatureCache
[upstream, unverified]. Streaming upsert is host-side by design; TPU parity
is periodic double-buffered snapshot refresh of device-resident arrays, not
per-message device updates (SURVEY.md C12 TPU note).
"""

from geomesa_tpu.kafka.cache import FeatureEvent, KafkaFeatureCache
from geomesa_tpu.kafka.messages import (
    Change,
    Clear,
    Delete,
    GeoMessageSerializer,
)
from geomesa_tpu.kafka.store import InProcessBroker, KafkaDataStore

__all__ = [
    "Change",
    "Clear",
    "Delete",
    "FeatureEvent",
    "GeoMessageSerializer",
    "InProcessBroker",
    "KafkaDataStore",
    "KafkaFeatureCache",
]
