"""GeoMessage types + versioned binary wire format.

Parity: geomesa-kafka GeoMessage / GeoMessageSerializer [upstream,
unverified]: three message kinds on one topic per feature type —
Change (upsert one feature), Delete (by feature id), Clear (drop all) —
with a versioned, self-describing-enough binary encoding.

The reference's encoding is Kryo-based; here it is a typed struct packing
driven by the SFT (the schema is known on both ends, exactly as upstream):

    [u8 version=1][u8 kind]                       kind: 1=Change 2=Delete 3=Clear
    fid: [u16 len][utf8]                          (Change/Delete)
    Change payload, per attribute in SFT order:
      null byte (0/1), then if non-null:
        String/UUID: [u32 len][utf8]
        Integer: i32   Long/Date/Timestamp: i64   Double: f64  Float: f32
        Boolean: u8    Bytes: [u32 len][raw]
        Point geometry: f64 x, f64 y
        other geometry: [u32 len][WKT utf8]
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Dict, Optional, Union

from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.core.wkt import Geometry, parse_wkt, point, to_wkt

VERSION = 1
_KIND_CHANGE, _KIND_DELETE, _KIND_CLEAR = 1, 2, 3


@dataclasses.dataclass
class Change:
    fid: str
    attributes: Dict[str, object]  # attr name -> python value


@dataclasses.dataclass
class Delete:
    fid: str


@dataclasses.dataclass
class Clear:
    pass


GeoMessage = Union[Change, Delete, Clear]


class GeoMessageSerializer:
    def __init__(self, sft: SimpleFeatureType):
        self.sft = sft

    # -- encode ------------------------------------------------------------

    def serialize(self, msg: GeoMessage) -> bytes:
        out = bytearray()
        if isinstance(msg, Clear):
            out += struct.pack("<BB", VERSION, _KIND_CLEAR)
            return bytes(out)
        if isinstance(msg, Delete):
            out += struct.pack("<BB", VERSION, _KIND_DELETE)
            self._put_str16(out, msg.fid)
            return bytes(out)
        out += struct.pack("<BB", VERSION, _KIND_CHANGE)
        self._put_str16(out, msg.fid)
        for a in self.sft.attributes:
            v = msg.attributes.get(a.name)
            if v is None:
                out.append(0)
                continue
            out.append(1)
            if a.is_geometry:
                g = self._as_geometry(v)
                if g.is_point:
                    out.append(1)
                    out += struct.pack("<dd", *g.point)
                else:
                    out.append(0)
                    self._put_str32(out, to_wkt(g))
            elif a.type in ("String", "UUID"):
                self._put_str32(out, str(v))
            elif a.type == "Integer":
                out += struct.pack("<i", int(v))
            elif a.type in ("Long", "Date", "Timestamp"):
                out += struct.pack("<q", int(v))
            elif a.type == "Double":
                out += struct.pack("<d", float(v))
            elif a.type == "Float":
                out += struct.pack("<f", float(v))
            elif a.type == "Boolean":
                out.append(1 if v else 0)
            elif a.type == "Bytes":
                b = bytes(v)
                out += struct.pack("<I", len(b))
                out += b
            else:
                raise NotImplementedError(f"wire format for {a.type!r}")
        return bytes(out)

    # -- decode ------------------------------------------------------------

    def deserialize(self, data: bytes) -> GeoMessage:
        version, kind = struct.unpack_from("<BB", data, 0)
        if version != VERSION:
            raise ValueError(f"unsupported GeoMessage version {version}")
        off = 2
        if kind == _KIND_CLEAR:
            return Clear()
        fid, off = self._get_str16(data, off)
        if kind == _KIND_DELETE:
            return Delete(fid)
        attrs: Dict[str, object] = {}
        for a in self.sft.attributes:
            present = data[off]
            off += 1
            if not present:
                attrs[a.name] = None
                continue
            if a.is_geometry:
                is_point = data[off]
                off += 1
                if is_point:
                    x, y = struct.unpack_from("<dd", data, off)
                    off += 16
                    attrs[a.name] = point(x, y)
                else:
                    wkt, off = self._get_str32(data, off)
                    attrs[a.name] = parse_wkt(wkt)
            elif a.type in ("String", "UUID"):
                attrs[a.name], off = self._get_str32(data, off)
            elif a.type == "Integer":
                (attrs[a.name],) = struct.unpack_from("<i", data, off)
                off += 4
            elif a.type in ("Long", "Date", "Timestamp"):
                (attrs[a.name],) = struct.unpack_from("<q", data, off)
                off += 8
            elif a.type == "Double":
                (attrs[a.name],) = struct.unpack_from("<d", data, off)
                off += 8
            elif a.type == "Float":
                (attrs[a.name],) = struct.unpack_from("<f", data, off)
                off += 4
            elif a.type == "Boolean":
                attrs[a.name] = bool(data[off])
                off += 1
            elif a.type == "Bytes":
                (n,) = struct.unpack_from("<I", data, off)
                off += 4
                attrs[a.name] = data[off : off + n]
                off += n
            else:
                raise NotImplementedError(f"wire format for {a.type!r}")
        return Change(fid, attrs)

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _as_geometry(v) -> Geometry:
        if isinstance(v, Geometry):
            return v
        if isinstance(v, str):
            return parse_wkt(v)
        if isinstance(v, (tuple, list)) and len(v) == 2:
            return point(float(v[0]), float(v[1]))
        raise TypeError(f"not a geometry: {v!r}")

    @staticmethod
    def _put_str16(out: bytearray, s: str) -> None:
        b = s.encode("utf-8")
        out += struct.pack("<H", len(b))
        out += b

    @staticmethod
    def _get_str16(data: bytes, off: int):
        (n,) = struct.unpack_from("<H", data, off)
        off += 2
        return data[off : off + n].decode("utf-8"), off + n

    @staticmethod
    def _put_str32(out: bytearray, s: str) -> None:
        b = s.encode("utf-8")
        out += struct.pack("<I", len(b))
        out += b

    @staticmethod
    def _get_str32(data: bytes, off: int):
        (n,) = struct.unpack_from("<I", data, off)
        off += 4
        return data[off : off + n].decode("utf-8"), off + n
