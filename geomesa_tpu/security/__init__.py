"""Visibility security.

Parity: geomesa-security (AuthorizationsProvider SPI, VisibilityEvaluator
for Accumulo-style boolean visibility expressions like "admin&(usa|gbr)")
[upstream, unverified]. TPU design (SURVEY.md C21): visibilities live in a
dictionary-coded label column; a user's authorizations precompute a per-batch
allow table over the vocabulary, AND-ed into every predicate mask — cheap
and exact.
"""

from geomesa_tpu.security.visibility import (
    VisibilityEvaluator,
    AuthorizationsProvider,
    StaticAuthorizationsProvider,
    allow_mask,
)

__all__ = [
    "VisibilityEvaluator",
    "AuthorizationsProvider",
    "StaticAuthorizationsProvider",
    "allow_mask",
]
