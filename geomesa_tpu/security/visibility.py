"""Visibility expression parsing and evaluation.

Grammar (Accumulo visibility expressions, the reference's model):

    expr   := term (('&' | '|') term)*   -- no mixing & and | without parens
    term   := label | '(' expr ')'
    label  := [A-Za-z0-9_.:/-]+ | "quoted"

An empty expression is visible to everyone. Evaluation: a set of granted
authorizations satisfies a label iff the label is granted; '&' = all,
'|' = any.
"""

from __future__ import annotations

import re
from typing import FrozenSet, List, Optional, Sequence, Set

import numpy as np

_LABEL = re.compile(r'[A-Za-z0-9_.:/-]+|"(?:[^"\\]|\\.)*"')


class _Node:
    def evaluate(self, auths: FrozenSet[str]) -> bool:
        raise NotImplementedError


class _Label(_Node):
    def __init__(self, name: str):
        self.name = name

    def evaluate(self, auths):
        return self.name in auths


class _And(_Node):
    def __init__(self, children):
        self.children = children

    def evaluate(self, auths):
        return all(c.evaluate(auths) for c in self.children)


class _Or(_Node):
    def __init__(self, children):
        self.children = children

    def evaluate(self, auths):
        return any(c.evaluate(auths) for c in self.children)


class _True(_Node):
    def evaluate(self, auths):
        return True


class VisibilityEvaluator:
    """Parse once, evaluate against many auth sets (cached per expression)."""

    def __init__(self):
        self._cache = {}

    def parse(self, expression: str) -> _Node:
        if expression in self._cache:
            return self._cache[expression]
        node = _parse(expression)
        self._cache[expression] = node
        return node

    def can_see(self, expression: Optional[str], auths: Sequence[str]) -> bool:
        if not expression:
            return True
        return self.parse(expression).evaluate(frozenset(auths))


def _parse(expr: str) -> _Node:
    expr = expr.strip()
    if not expr:
        return _True()
    pos = [0]

    def term() -> _Node:
        _ws()
        if pos[0] < len(expr) and expr[pos[0]] == "(":
            pos[0] += 1
            n = parse_expr()
            _ws()
            if pos[0] >= len(expr) or expr[pos[0]] != ")":
                raise ValueError(f"visibility parse error: missing ')' in {expr!r}")
            pos[0] += 1
            return n
        m = _LABEL.match(expr, pos[0])
        if not m:
            raise ValueError(f"visibility parse error at {expr[pos[0]:]!r}")
        pos[0] = m.end()
        name = m.group()
        if name.startswith('"'):
            name = name[1:-1].replace('\\"', '"')
        return _Label(name)

    def _ws():
        while pos[0] < len(expr) and expr[pos[0]].isspace():
            pos[0] += 1

    def parse_expr() -> _Node:
        nodes = [term()]
        op = None
        while True:
            _ws()
            if pos[0] >= len(expr) or expr[pos[0]] == ")":
                break
            c = expr[pos[0]]
            if c not in "&|":
                raise ValueError(f"visibility parse error at {expr[pos[0]:]!r}")
            if op is None:
                op = c
            elif op != c:
                raise ValueError(
                    f"cannot mix & and | without parentheses: {expr!r}"
                )
            pos[0] += 1
            nodes.append(term())
        if len(nodes) == 1:
            return nodes[0]
        return _And(nodes) if op == "&" else _Or(nodes)

    node = parse_expr()
    if pos[0] != len(expr):
        raise ValueError(f"visibility parse error: trailing input in {expr!r}")
    return node


class AuthorizationsProvider:
    """SPI: which authorizations does the current user hold."""

    def get_authorizations(self) -> List[str]:
        raise NotImplementedError


class StaticAuthorizationsProvider(AuthorizationsProvider):
    def __init__(self, auths: Sequence[str]):
        self.auths = list(auths)

    def get_authorizations(self) -> List[str]:
        return self.auths


def allow_mask(
    vis_vocab: Sequence[Optional[str]],
    vis_codes: np.ndarray,
    auths: Sequence[str],
    evaluator: Optional[VisibilityEvaluator] = None,
) -> np.ndarray:
    """Per-feature bool mask from a dictionary-coded visibility column.

    The allow table is computed once per vocabulary (|vocab| evaluations,
    not |features|), then gathered by code — the precomputed per-batch
    bitmask design from SURVEY.md C21. Null visibility (-1 code) = public.
    """
    ev = evaluator or VisibilityEvaluator()
    aset = frozenset(auths)
    table = np.array(
        [ev.parse(v).evaluate(aset) if v else True for v in vis_vocab],
        dtype=bool,
    )
    codes = np.asarray(vis_codes)
    in_range = (codes >= 0) & (codes < len(table))
    safe = np.clip(codes, 0, max(len(table) - 1, 0))
    gathered = table[safe] if len(table) else np.zeros(len(codes), bool)
    # fail-closed: out-of-range codes (stale vocab / corruption) are DENIED;
    # only the null code (-1) means "no visibility" = public
    return np.where(in_range, gathered, codes < 0)
