"""Request coalescing: compatible in-flight queries share one device
execution.

The engine kernels are already batched over query sets — `knn_sparse_scan`
/ `knn_fullscan_tiled` take [Q] query-point arrays and compute every row
independently — so N concurrent kNN requests with the same store, filter,
k and kernel choice stack their query points into ONE kernel launch
instead of N. That is the continuous-batching lever (Orca/Clipper shape,
PAPERS.md): under concurrent load, throughput-per-chip is bounded by
dispatches, not by rows.

Compatibility rules (see docs/SERVING.md):
- knn:   same (type, canonical CQL, hints, k, impl) — query points are
         the batched axis; results split back per request. Stacked Q pads
         to a pow2 (floor 8) so the pallas jit cache sees a handful of
         shapes, not one per batch size.
- count / execute: same (type, canonical CQL, hints, projection, sort,
         limit, crs) — byte-identical queries, executed ONCE with the
         result shared (dedup). QueryResult is treated as immutable by
         every consumer, so sharing the object is safe.

Anything else returns key None and never coalesces. Correctness first:
keys include the full hint string, so auths/visibility, sampling and
aggregation hints can never alias across tenants.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

import numpy as np

from geomesa_tpu.cql import ast
from geomesa_tpu.plan.planner import QueryTimeout
from geomesa_tpu.serve.scheduler import ServeRequest
from geomesa_tpu.telemetry.trace import TRACER
from geomesa_tpu.utils.padding import next_pow2 as _next_pow2

# floor for the padded stacked-query axis: keeps the kernel shape set
# tiny ({8, 16, 32, ...}) across ragged batch sizes
MIN_KNN_BATCH = 8


def compat_key(req: ServeRequest) -> Optional[tuple]:
    """Coalescing key, or None when the request must run alone. The
    filter canonicalizes through the AST so textual variants ("a=1 AND
    b=2" vs "a = 1 AND b = 2") still coalesce."""
    q = req.query
    try:
        cql = ast.to_cql(q.filter_ast)
    except Exception:
        return None
    hints = str(q.hints)
    if req.kind == "knn":
        return ("knn", q.type_name, cql, hints, req.k, req.impl)
    if req.kind == "count":
        return ("count", q.type_name, cql, hints, q.max_features)
    # execute: only byte-identical result specs dedup
    attrs = tuple(q.attributes) if q.attributes is not None else None
    sort = tuple(q.sort_by) if q.sort_by else None
    return ("execute", q.type_name, cql, hints, attrs, sort,
            q.max_features, q.crs)


def ring_key(req: ServeRequest, q_padded: int) -> Optional[tuple]:
    """Ring-program window-class key (docs/SERVING.md "Persistent serve
    loop"): the kNN compat key extended with the padded stacked-query
    bucket — an AOT ring executable is shape-specific, so window sizes
    that pad to different pow2 buckets arm separate programs (the
    bucket floor keeps that a handful of entries, exactly like the
    kernel jit cache). None = this request never rides the ring
    (non-kNN, or a filter the canonicalizer cannot key)."""
    if req.kind != "knn":
        return None
    base = compat_key(req)
    if base is None:
        return None
    return base + (int(q_padded),)


def fused_count_key(req: ServeRequest) -> Optional[tuple]:
    """Cross-kind fusion (docs/SERVING.md "Pipelined dispatch"): the
    compat key of a COUNT request that may ride this kNN request's
    dispatch window, or None when fusion is unsafe. A count against the
    same (type, canonical CQL, hints) is one reduction over the filter
    mask the kNN launch computes anyway — fusing it eliminates the
    count's entire dispatch RTT.

    Gates (each one is a case where the fused mask count could diverge
    from `planner.count`):
    - INCLUDE filters: `count` answers them from the manifest without
      device work — nothing to fuse, and manifest vs mask semantics
      may differ mid-write;
    - sampling / loose_bbox hints: the count path samples or re-checks
      the mask differently from the kNN mask;
    - the fused key pins max_features=None: a bounded count clamps.
    The launch-side contract (KnnLaunch.fused_ok) lets the planner
    decline too; today it never does — the reduction runs over the
    f64-exact mask, band corrections included — but callers must treat
    a decline as "dispatch the count serially"."""
    if req.kind != "knn":
        return None
    q = req.query
    try:
        from geomesa_tpu.cql import ast as _ast

        if isinstance(q.filter_ast, _ast.Include):
            return None
        cql = ast.to_cql(q.filter_ast)
    except Exception:
        return None
    h = q.hints
    if h.sampling or h.loose_bbox or h.is_density or h.is_stats \
            or h.is_bin or h.is_arrow:
        return None
    return ("count", q.type_name, cql, str(h), None)


def stack_queries(reqs: List[ServeRequest]):
    """Host prep for one kNN window: stack member query points into one
    [Q] array pair padded to a pow2 (floor MIN_KNN_BATCH). Shared by the
    serial path and the pipeline's prepare stage so the two build
    byte-identical kernel inputs. Returns (qx, qy, offsets) with qx/qy
    already padded (repeat of the first point: cheap, in-bounds,
    discarded on split)."""
    xs = [np.asarray(r.qx, np.float64).ravel() for r in reqs]
    ys = [np.asarray(r.qy, np.float64).ravel() for r in reqs]
    offsets = np.cumsum([0] + [len(x) for x in xs])
    qx = np.concatenate(xs)
    qy = np.concatenate(ys)
    total = len(qx)
    padded = max(MIN_KNN_BATCH, _next_pow2(total))
    if padded > total:
        qx = np.concatenate([qx, np.full(padded - total, qx[0])])
        qy = np.concatenate([qy, np.full(padded - total, qy[0])])
    return qx, qy, offsets


def split_knn_results(reqs: List[ServeRequest], offsets, dists, idx,
                      batch) -> None:
    """Resolve one kNN window's member futures from the stacked [Q, k]
    result rows ("merge": set_result runs protocol callbacks inline)."""
    with TRACER.span("merge", members=len(reqs)):
        for i, r in enumerate(reqs):
            a, b = offsets[i], offsets[i + 1]
            r.future.set_result((dists[a:b], idx[a:b], batch))


def batch_timeout_ms(reqs: List[ServeRequest]) -> Optional[int]:
    """Deadline for a shared dispatch: the LONGEST remaining budget among
    members (a short-deadline rider must not kill work others still
    want). None if any member is deadline-free. Floored at 1ms so a
    nearly-expired straggler doesn't disable the check entirely."""
    remaining = []
    for r in reqs:
        ms = r.remaining_ms
        if ms is None:
            return None
        remaining.append(ms)
    return max(1, int(max(remaining)))


def split_expired(
    reqs: List[ServeRequest],
) -> Tuple[List[ServeRequest], List[ServeRequest]]:
    """Requests whose deadline passed while queued never reach the
    device; their futures get a typed QueryTimeout(phase="queued")."""
    live, dead = [], []
    for r in reqs:
        (dead if r.expired else live).append(r)
    return live, dead


def fail_expired(reqs: List[ServeRequest]) -> None:
    now = time.monotonic()
    for r in reqs:
        if r.future.set_running_or_notify_cancel():
            waited_ms = (now - r.enqueued_at) * 1000.0
            # the original budget = wait so far + (negative) remaining
            budget_ms = waited_ms + (r.remaining_ms or 0.0)
            r.future.set_exception(
                QueryTimeout("queued", waited_ms, budget_ms)
            )


def execute_batch(source, reqs: List[ServeRequest]) -> None:
    """Run one coalesced group against its FeatureSource and resolve
    every member future. `reqs` share a compat key (or are a singleton).
    Exceptions fan out to every member — a failed shared dispatch fails
    all riders identically, like N serial runs of the same query would.

    Device OOM is the exception to the fan-out: a batch that exhausts
    device memory HALVES its bucket (the padded stacked-query axis
    shrinks with it) and retries each half; a request that still OOMs
    alone falls back to exact host evaluation (faults/fallback.py), so
    a memory-squeezed accelerator degrades to slower answers instead of
    failed ones."""
    running = [r for r in reqs if r.future.set_running_or_notify_cancel()]
    if not running:
        return
    _run_group(source, running)


def _run_group(source, reqs: List[ServeRequest]) -> None:
    from geomesa_tpu.faults import classify

    timeout_ms = batch_timeout_ms(reqs)
    try:
        if reqs[0].kind == "knn":
            _execute_knn(source, reqs, timeout_ms)
        else:
            _execute_shared(source, reqs, timeout_ms)
    except BaseException as e:  # noqa: BLE001 — fan the failure out
        if isinstance(e, Exception) and classify(e) == "oom":
            _oom_fallback(source, reqs, e)
            return
        for r in reqs:
            r.future.set_exception(e)


def _oom_fallback(source, reqs: List[ServeRequest],
                  oom: BaseException) -> None:
    from geomesa_tpu.telemetry.recorder import RECORDER
    from geomesa_tpu.utils.metrics import metrics

    if reqs[0].kind == "knn" and len(reqs) > 1:
        # halve the batch bucket: each kNN half pads to a smaller pow2
        # stacked-query axis, so the retried program is genuinely
        # smaller — not the same allocation failing twice. Only kNN
        # qualifies: count/execute groups DEDUP to one planner run
        # whose program size is independent of rider count, so halving
        # them would just re-fail the identical allocation
        metrics.counter("serve.oom.halved")
        # flight-recorder lifecycle event: each ladder step records, so
        # a crash dump shows the descent (64 -> 32 -> 16 -> host) that
        # preceded an incident instead of one opaque OOM
        RECORDER.note_event("oom", action="halved", batch=len(reqs),
                            query_kind=reqs[0].kind)
        mid = len(reqs) // 2
        _run_group(source, reqs[:mid])
        _run_group(source, reqs[mid:])
        return
    # host evaluation, ONCE per group: shared count/execute riders get
    # the same (immutable) result object, exactly like _execute_shared
    RECORDER.note_event("oom", action="hosteval", batch=len(reqs),
                        query_kind=reqs[0].kind)
    try:
        from geomesa_tpu.faults.fallback import host_fallback

        out = host_fallback(source, reqs[0])
    except BaseException as e:  # noqa: BLE001 — surface typed, not raw
        exc = e if isinstance(e, Exception) else oom
        for r in reqs:
            r.future.set_exception(exc)
        return
    metrics.counter("serve.oom.hosteval")
    for r in reqs:
        r.future.set_result(out)


# result-cache value-size gates: the LRU bounds entry COUNT, so
# entries must be individually small or a handful of wide execute
# results pins gigabytes. Feature results cap at the wire's row
# ceiling; grids/payloads at a few MB. Oversized results simply
# re-execute — correctness is untouched.
_CACHE_MAX_ROWS = 10_000          # == protocol.MAX_FEATURE_ROWS
_CACHE_MAX_GRID_CELLS = 1 << 20   # 1M f64 cells = 8 MB
_CACHE_MAX_BYTES = 8 << 20        # arrow/bin payloads


def _cacheable_value(provenance) -> bool:
    feats = getattr(provenance, "features", None)
    if feats is not None and len(feats) > _CACHE_MAX_ROWS:
        return False
    grid = getattr(provenance, "grid", None)
    if grid is not None and grid.size > _CACHE_MAX_GRID_CELLS:
        return False
    for attr in ("arrow_bytes", "bin_bytes"):
        b = getattr(provenance, attr, None)
        if b is not None and len(b) > _CACHE_MAX_BYTES:
            return False
    return True


def _cache_put(lead: ServeRequest, provenance, value) -> None:
    """Populate the service's version-exact result cache from one
    executed dispatch. `provenance` is the QueryResult carrying the
    manifest version the PLAN pinned — keying on a version read any
    later could stamp a pre-write key onto post-write data. Approx,
    degraded and oversized results never cache (the cache's contract
    is exact bit-identical replay within a bounded memory envelope)."""
    cache = lead.cache
    if (cache is None or lead.degraded or provenance.approx
            or provenance.version is None
            or not _cacheable_value(provenance)):
        return
    from geomesa_tpu.approx.cache import result_key

    cache.put(result_key(lead.kind, lead.query, provenance.version),
              value)


def _execute_shared(source, reqs: List[ServeRequest],
                    timeout_ms: Optional[int]) -> None:
    """count/execute dedup: one planner run, every rider gets the same
    (immutable) result object. Successful exact results populate the
    version-exact result cache (docs/SERVING.md "Approximate
    answers"); sketch-served answers mark every rider `approx` for
    ServeEvent/SLO attribution."""
    lead = reqs[0]
    if lead.kind == "count":
        qr = source.planner.count_result(lead.query, timeout_ms=timeout_ms)
        if qr.approx:
            from geomesa_tpu.approx.engine import ApproxCount

            out = ApproxCount(int(qr.count), int(qr.bound), qr.confidence)
        else:
            out = int(qr.count)
        provenance = qr
    else:
        out = source.planner.execute(lead.query, timeout_ms=timeout_ms)
        provenance = out
    if provenance.approx:
        for r in reqs:
            r.approx = True
    _cache_put(lead, provenance, out)
    with TRACER.span("merge", members=len(reqs)):
        for r in reqs:
            r.future.set_result(out)


def note_launch_route(reqs: List[ServeRequest], launch) -> None:
    """Stamp the launch's routing attribution (mesh topology + owning
    shards — docs/SERVING.md "Sharded serving") onto every member so
    ServeEvents report where the window actually ran. The admission-time
    affinity tag is a prediction; the launch's value is authoritative."""
    mesh_shape = getattr(launch, "mesh_shape", ()) or ()
    shards = getattr(launch, "shards", ()) or ()
    if not mesh_shape and not shards:
        return
    ms = str(tuple(mesh_shape)) if mesh_shape else ""
    sh = ",".join(map(str, shards))
    for r in reqs:
        r.mesh_shape = ms
        r.shards = sh


def _execute_knn(source, reqs: List[ServeRequest],
                 timeout_ms: Optional[int] = None) -> None:
    """Stack member query points into one [Q] kernel launch and split
    the [Q, k] result rows back out. Rows are computed independently by
    the kernels, so per-request results are identical to serial runs of
    the same kernel — asserted in tests/test_serve.py.

    The dispatch seam is launch + sync (planner.knn IS the same
    composition), so the serial path shares the pipeline's route
    selection — single-chip kernel, shard-affinity local kernel, or the
    one-program mesh dispatch — and its attribution."""
    with TRACER.span("knn.stack", members=len(reqs)):
        qx, qy, offsets = stack_queries(reqs)
    lead = reqs[0]
    launch = source.planner.knn_launch(
        lead.query, qx, qy, k=lead.k, impl=lead.impl,
        timeout_ms=timeout_ms,
    )
    note_launch_route(reqs, launch)
    dists, idx, batch = launch.sync()
    split_knn_results(reqs, offsets, dists, idx, batch)
